"""Seeded process supervisor for the distributed control plane.

``ProcessSupervisor`` owns the child processes of a distributed run:
it spawns them (``python -m kueue_tpu.dist.child``), waits for
readiness by polling the child's bound-port file and ``/readyz``
endpoint (never by sleeping a guessed interval), SIGKILLs them on a
deterministic schedule, and respawns them on the *same* bound port so
client base URLs survive the restart (``DrainingHTTPServer`` sets
SO_REUSEADDR for exactly this handoff).

Kills follow the chaos-injector site pattern: every barrier the
harness consults :meth:`maybe_kill`, which asks the installed injector
for a ``dist.kill`` fault whose payload names the target process.
Arming ``dist.kill`` with ``at=N`` therefore kills the named child at
the Nth consultation — the same deterministic replayable schedule the
in-process crash sites use, but delivered as a real SIGKILL.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from ..chaos import injector as _chaos
from ..features import env_int


@dataclass
class ManagedProcess:
    """One supervised child: its spawn recipe plus live state."""
    name: str
    role: str                       # shard | worker | submitter | service
    argv: list[str]
    env: dict[str, str]
    port_file: Optional[str] = None
    port: Optional[int] = None
    proc: Optional[subprocess.Popen] = None
    restarts: int = 0
    pipe_stdio: bool = False

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ProcessSupervisor:
    """Spawn, monitor, kill, and respawn the run's child processes."""

    def __init__(self, seed: Optional[int] = None):
        self.seed = env_int("KUEUE_TPU_DIST_SEED") if seed is None else seed
        self.procs: dict[str, ManagedProcess] = {}
        self.stats: dict[str, dict[str, int]] = {}
        self.kill_log: list[str] = []

    def _bump(self, role: str, what: str) -> None:
        per = self.stats.setdefault(
            role, {"spawns": 0, "kills": 0, "restarts": 0})
        per[what] += 1

    # -- lifecycle --

    def spawn(self, name: str, role: str, argv: list[str],
              env: Optional[dict] = None, port_file: Optional[str] = None,
              pipe_stdio: bool = False) -> ManagedProcess:
        mp = self.procs.get(name)
        if mp is None:
            mp = ManagedProcess(name=name, role=role, argv=list(argv),
                                env=dict(env or os.environ),
                                port_file=port_file, pipe_stdio=pipe_stdio)
            self.procs[name] = mp
        else:
            mp.argv = list(argv)
            if env is not None:
                mp.env = dict(env)
        self._launch(mp)
        self._bump(role, "spawns")
        return mp

    def _launch(self, mp: ManagedProcess) -> None:
        pipe = subprocess.PIPE if mp.pipe_stdio else None
        mp.proc = subprocess.Popen(
            mp.argv, env=mp.env, stdin=pipe, stdout=pipe,
            stderr=subprocess.PIPE, text=True)

    def wait_port(self, mp: ManagedProcess, timeout: float = 30.0) -> int:
        """Poll the child's port file until the bound port lands there
        (the child writes it after bind, before serving)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if mp.port_file and os.path.exists(mp.port_file):
                try:
                    with open(mp.port_file) as f:
                        txt = f.read().strip()
                    if txt:
                        mp.port = int(txt)
                        return mp.port
                except (OSError, ValueError):
                    pass
            if not mp.alive:
                raise RuntimeError(
                    f"{mp.name} died before binding: "
                    f"{self._death_note(mp)}")
            time.sleep(0.02)
        raise TimeoutError(f"{mp.name}: no port after {timeout}s")

    def wait_ready(self, mp: ManagedProcess, timeout: float = 30.0) -> int:
        """Bound-port handoff + readiness: poll the port file, then the
        child's ``/readyz`` until it answers 200."""
        self.wait_port(mp, timeout=timeout)
        deadline = time.monotonic() + timeout
        url = f"http://127.0.0.1:{mp.port}/readyz"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=1.0) as resp:
                    if resp.status == 200:
                        return mp.port
            except (urllib.error.URLError, OSError, ConnectionError):
                pass
            if not mp.alive:
                raise RuntimeError(
                    f"{mp.name} died before ready: {self._death_note(mp)}")
            time.sleep(0.02)
        raise TimeoutError(f"{mp.name}: not ready after {timeout}s")

    def _death_note(self, mp: ManagedProcess) -> str:
        if mp.proc is None:
            return "never spawned"
        err = ""
        try:
            if mp.proc.stderr is not None:
                err = mp.proc.stderr.read()[-2000:]
        except (OSError, ValueError):
            pass
        return f"exit={mp.proc.returncode} stderr={err!r}"

    # -- killing --

    def kill(self, name: str) -> bool:
        """SIGKILL the named child (no warning, no cleanup — the whole
        point).  True when a live process was actually killed."""
        mp = self.procs.get(name)
        if mp is None or not mp.alive:
            return False
        os.kill(mp.proc.pid, signal.SIGKILL)
        mp.proc.wait(timeout=10.0)
        self._bump(mp.role, "kills")
        self.kill_log.append(name)
        return True

    def maybe_kill(self, name: str) -> bool:
        """Consult the chaos schedule: a ``dist.kill`` fault whose
        payload names this process (or names nothing) SIGKILLs it.
        Call once per barrier per candidate — the injector's hit
        counter is the deterministic clock."""
        inj = _chaos.ACTIVE
        if inj is None:
            return False
        f = inj.hit("dist.kill")
        if f is None:
            return False
        if f.payload not in (None, "", name):
            return False
        return self.kill(name)

    def restart(self, name: str, argv: Optional[list] = None,
                timeout: float = 30.0) -> ManagedProcess:
        """Respawn a killed child.  Pass ``argv`` to pin the restart to
        the old bound port (``--port N`` instead of ``--port 0``); the
        port file is cleared first so ``wait_ready`` reads the fresh
        bind, whatever port it lands on."""
        mp = self.procs[name]
        if mp.alive:
            self.kill(name)
        if argv is not None:
            mp.argv = list(argv)
        if mp.port_file and os.path.exists(mp.port_file):
            os.unlink(mp.port_file)
        self._launch(mp)
        mp.restarts += 1
        self._bump(mp.role, "restarts")
        if mp.port_file:
            self.wait_ready(mp, timeout=timeout)
        return mp

    def terminate_all(self) -> None:
        for mp in self.procs.values():
            if mp.alive:
                try:
                    os.kill(mp.proc.pid, signal.SIGKILL)
                except OSError:
                    pass
        for mp in self.procs.values():
            if mp.proc is not None:
                try:
                    mp.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass

    # -- reporting --

    def report(self) -> dict:
        return {
            "seed": self.seed,
            "procs": {n: {"role": mp.role, "port": mp.port,
                          "alive": mp.alive, "restarts": mp.restarts}
                      for n, mp in self.procs.items()},
            "by_role": {r: dict(s) for r, s in sorted(self.stats.items())},
            "kill_log": list(self.kill_log),
        }


def child_argv(role: str, **kw) -> list[str]:
    """argv for ``python -m kueue_tpu.dist.child`` with ``--key value``
    pairs (None values skipped, bools as 1/0)."""
    argv = [sys.executable, "-m", "kueue_tpu.dist.child", "--role", role]
    for key, val in kw.items():
        if val is None:
            continue
        if isinstance(val, bool):
            val = int(val)
        argv += [f"--{key.replace('_', '-')}", str(val)]
    return argv


def read_json(url: str, timeout: float = 5.0) -> Optional[dict]:
    """One unretried GET returning parsed JSON (supervisor probes)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else None
    except (urllib.error.URLError, OSError, ConnectionError,
            json.JSONDecodeError):
        return None
