"""Socket-fault proxy: transport chaos injected at the wire.

``SocketFaultProxy`` is a listen-and-forward TCP proxy placed between
an ``HttpWorkerClient`` (or any HTTP caller) and a real server
process.  Each accepted connection consults the fault schedule and
either forwards cleanly or injects one of four wire-level faults —
the failure modes a mock transport can't produce honestly:

- ``reset``: close the client socket with SO_LINGER=0 (a hard RST),
  before anything reaches upstream — the client sees
  ``ConnectionResetError`` mid-request;
- ``latency``: sleep ``payload`` seconds before dialing upstream (a
  slow link; drives the client's timeout/deadline budget);
- ``truncate``: forward the request, then relay only the first
  ``payload`` bytes of the response and RST — the client sees a
  half-delivered body (``IncompleteRead``/``BadStatusLine``), the
  mid-body retry path's home turf;
- ``blackhole``: accept, read, and never answer — the client's socket
  timeout is the only way out.

Faults come from two seeded sources, chaos-site first: an armed
``dist.proxy_fault`` fault fires by deterministic hit count (its
``action`` picks the verb, its ``payload`` the seconds/bytes), and an
optional :class:`FaultPlan` of per-connection probabilities (the
``KUEUE_TPU_DIST_PROXY_*`` flags) drives longer soaks through the
proxy's own ``random.Random(seed)`` — reproducible either way.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..chaos import injector as _chaos
from ..features import env_int, env_value

#: default fault magnitudes when an armed fault carries no payload
_DEFAULT_LATENCY_S = 0.2
_DEFAULT_TRUNCATE_BYTES = 24


@dataclass(frozen=True)
class FaultPlan:
    """Per-connection fault probabilities (all default 0 = clean)."""
    reset: float = 0.0
    latency: float = 0.0
    truncate: float = 0.0
    blackhole: float = 0.0
    latency_s: float = _DEFAULT_LATENCY_S

    @classmethod
    def resolved(cls, **overrides) -> "FaultPlan":
        """Build from the ``KUEUE_TPU_DIST_PROXY_*`` flags, with
        keyword overrides taking precedence."""
        def flag(name):
            try:
                return float(env_value(name) or 0.0)
            except ValueError:
                return 0.0
        vals = {"reset": flag("KUEUE_TPU_DIST_PROXY_RESET"),
                "latency": flag("KUEUE_TPU_DIST_PROXY_LATENCY_S") and 1.0,
                "latency_s": flag("KUEUE_TPU_DIST_PROXY_LATENCY_S")
                or _DEFAULT_LATENCY_S,
                "truncate": flag("KUEUE_TPU_DIST_PROXY_TRUNCATE"),
                "blackhole": flag("KUEUE_TPU_DIST_PROXY_BLACKHOLE")}
        vals.update(overrides)
        return cls(**vals)

    @property
    def any(self) -> bool:
        return bool(self.reset or self.latency or self.truncate
                    or self.blackhole)


def _rst_close(sock: socket.socket) -> None:
    """Close with SO_LINGER=0: the peer gets a hard RST, not FIN."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class SocketFaultProxy:
    """Seeded listen-and-forward proxy in front of one upstream port."""

    def __init__(self, upstream_port: int, host: str = "127.0.0.1",
                 port: int = 0, plan: Optional[FaultPlan] = None,
                 seed: Optional[int] = None):
        import random
        self.upstream = (host, upstream_port)
        self.plan = plan or FaultPlan()
        self.rng = random.Random(
            env_int("KUEUE_TPU_DIST_SEED") if seed is None else seed)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self.stats = {"connections": 0, "forwarded": 0, "resets": 0,
                      "latencies": 0, "truncations": 0, "blackholes": 0,
                      "bytes_up": 0, "bytes_down": 0}

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- fault schedule --

    def _decide(self) -> tuple[Optional[str], float]:
        """(fault verb, magnitude) for the next connection — the armed
        chaos site wins over the probability plan."""
        inj = _chaos.ACTIVE
        if inj is not None:
            f = inj.hit("dist.proxy_fault")
            if f is not None and f.action in ("reset", "latency",
                                              "truncate", "blackhole"):
                return f.action, float(f.payload or 0.0)
        p = self.plan
        if p.any:
            roll = self.rng.random()
            for verb, prob, mag in (("reset", p.reset, 0.0),
                                    ("latency", p.latency, p.latency_s),
                                    ("truncate", p.truncate,
                                     _DEFAULT_TRUNCATE_BYTES),
                                    ("blackhole", p.blackhole, 0.0)):
                if prob <= 0.0:
                    continue
                if roll < prob:
                    return verb, mag
                roll -= prob
        return None, 0.0

    # -- lifecycle --

    def start(self) -> None:
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            self.stats["connections"] += 1
            threading.Thread(target=self._handle, args=(client,),
                             daemon=True).start()

    # -- per-connection forwarding --

    def _handle(self, client: socket.socket) -> None:
        verb, mag = self._decide()
        if verb == "reset":
            self.stats["resets"] += 1
            _rst_close(client)
            return
        if verb == "blackhole":
            self.stats["blackholes"] += 1
            self._blackhole(client)
            return
        if verb == "latency":
            self.stats["latencies"] += 1
            time.sleep(mag or _DEFAULT_LATENCY_S)
        limit = None
        if verb == "truncate":
            self.stats["truncations"] += 1
            limit = int(mag) or _DEFAULT_TRUNCATE_BYTES
        try:
            up = socket.create_connection(self.upstream, timeout=10.0)
        except OSError:
            # upstream itself is down (e.g. mid-restart): behave like
            # the wire — refuse by RST
            _rst_close(client)
            return
        with self._lock:
            self._conns += [client, up]
        self.stats["forwarded"] += 1
        t_up = threading.Thread(
            target=self._pump, args=(client, up, "bytes_up", None),
            daemon=True)
        t_up.start()
        self._pump(up, client, "bytes_down", limit)
        t_up.join(timeout=10.0)

    def _pump(self, src: socket.socket, dst: socket.socket,
              counter: str, limit: Optional[int]) -> None:
        """Copy bytes src→dst until EOF; with ``limit``, relay that
        many bytes then RST both ends (a truncated write)."""
        sent = 0
        try:
            while True:
                buf = src.recv(65536)
                if not buf:
                    break
                if limit is not None and sent + len(buf) >= limit:
                    dst.sendall(buf[:max(0, limit - sent)])
                    self.stats[counter] += max(0, limit - sent)
                    self._abort_pair(dst, src)
                    return
                dst.sendall(buf)
                sent += len(buf)
                self.stats[counter] += len(buf)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                if not self._lingering(s):
                    # graceful path: FIN both directions before close
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                try:
                    s.close()
                except OSError:
                    pass

    @staticmethod
    def _lingering(s: socket.socket) -> bool:
        """True when :meth:`_abort_pair` armed linger-0 on this socket
        — the marker telling the pump's teardown to stay abortive."""
        try:
            onoff, _ = struct.unpack("ii", s.getsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER, 8))
            return bool(onoff)
        except OSError:
            return False

    @staticmethod
    def _abort_pair(a: socket.socket, b: socket.socket) -> None:
        """Abortive teardown of a forwarding pair: the peer must see an
        RST, not a FIN — a truncated-then-FINed response can parse as a
        short-but-valid success.  A bare linger-0 close is not enough
        either: the opposite pump thread is blocked in ``recv`` on one
        of these sockets, which keeps the kernel file alive past
        ``close()`` and the RST in limbo forever.  So: arm linger-0
        (makes the *last* close abortive, and flags the peer pump's
        teardown via :meth:`_lingering` to skip its graceful FIN), wake
        the parked thread with a local-only ``SHUT_RD`` (no wire
        traffic), then drop our reference."""
        for s in (a, b):
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
        for s in (a, b):
            try:
                s.shutdown(socket.SHUT_RD)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def _blackhole(self, client: socket.socket) -> None:
        """Swallow the request and never answer; the client's socket
        timeout is the only exit."""
        client.settimeout(0.5)
        deadline = time.monotonic() + 30.0
        try:
            while not self._stop.is_set() and time.monotonic() < deadline:
                try:
                    if not client.recv(65536):
                        break
                except socket.timeout:
                    continue
                except OSError:
                    break
        finally:
            try:
                client.close()
            except OSError:
                pass
