"""Federation-worker process build and recovery.

A federation worker process is a real ``WorkerServer`` (remote.py)
around a Driver with the FederationSim worker topology.  Its durable
state is two journals: a ``ManifestJournal`` of every workload
manifest the manager created (written before the create's ack) and a
``CycleWAL`` of every decision since.  A SIGKILLed worker therefore
rebuilds bit-identically: manifests → initial store, WAL committed
history → every decision replayed (``replay_history``; compaction is
off in worker processes), WAL tail → the possibly half-applied last
cycle (``Driver.recover_from``).  The restarted server presents a
fresh watch epoch, which is what drives the manager's ``__resync__``
path over a real socket.
"""

from __future__ import annotations

from typing import Optional

from ..api import manifests as m
from ..api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
)
from ..controller.driver import Driver
from ..utils.journal import CycleWAL, ManifestJournal
from .serving import VirtualClock


def worker_topology(remote_cqs: int, quota_m: int = 4000):
    """The FederationSim worker shape: cohorts of 4, BEST_EFFORT_FIFO,
    lq-N → cq-N, one cpu flavor."""
    def fn(d):
        d.apply_resource_flavor(ResourceFlavor(name="default"))
        with d.bulk_apply():
            for q in range(remote_cqs):
                d.apply_cluster_queue(ClusterQueue(
                    name=f"cq-{q}", cohort=f"co-{q // 4}",
                    queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                    preemption=PreemptionPolicy(),
                    resource_groups=[ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[FlavorQuotas(name="default", resources={
                            "cpu": ResourceQuota(nominal=quota_m)})])]))
                d.apply_local_queue(LocalQueue(
                    name=f"lq-{q}", cluster_queue=f"cq-{q}"))
    return fn


def worker_paths(state_dir: str, name: str) -> tuple[str, str]:
    return (f"{state_dir}/{name}.wal", f"{state_dir}/{name}.manifests")


def build_worker(name: str, remote_cqs: int, state_dir: str,
                 quota_m: int = 4000, epoch_t: float = 1000.0
                 ) -> tuple[Driver, VirtualClock, CycleWAL, ManifestJournal]:
    """Fresh worker process state: driver + virtual clock + both
    durable journals (WAL compaction off — recovery replays the full
    decision history)."""
    wal_path, mf_path = worker_paths(state_dir, name)
    clock = VirtualClock(epoch_t)
    d = Driver(clock=clock, use_device_solver=False)
    worker_topology(remote_cqs, quota_m)(d)
    wal = CycleWAL(wal_path, compact_every=0)
    d.attach_wal(wal)
    journal = ManifestJournal(mf_path)
    return d, clock, wal, journal


def recover_worker(name: str, remote_cqs: int, state_dir: str,
                   quota_m: int = 4000, epoch_t: float = 1000.0,
                   resume_t: Optional[float] = None
                   ) -> tuple[Driver, VirtualClock, CycleWAL,
                              ManifestJournal, int]:
    """Rebuild a SIGKILLed worker from its journals alone.

    Initial store = the manifest journal folded (tombstones applied);
    then the WAL's committed history replays every admit/evict/finish
    since; then ``recover_from`` rolls the uncommitted tail forward and
    rebuilds cache/queues.  ``resume_t`` positions the virtual clock
    (the lockstep parent knows the step time at kill).  Returns the
    rebuilt pieces plus the count of tail ops replayed."""
    wal_path, mf_path = worker_paths(state_dir, name)
    wal = CycleWAL.resume(wal_path)
    store = {}
    for key, doc in ManifestJournal.load(mf_path).items():
        store[key] = m.from_manifest(doc)
    wal.replay_history(store)
    clock = VirtualClock(epoch_t if resume_t is None else resume_t)
    d = Driver(clock=clock, use_device_solver=False)
    worker_topology(remote_cqs, quota_m)(d)
    replayed = d.recover_from(store.values(), wal)
    journal = ManifestJournal(mf_path)
    return d, clock, wal, journal, replayed
