"""Distributed control plane: real OS processes under a seeded
supervisor.

The r16–r19 stack is crash-consistent but single-process — every soak
kills and recovers a driver *inside* one interpreter.  This package
splits the control plane the way PAPER.md's Kueue deployment does
(controller-manager processes whose durable state lives outside the
process) and proves the same zero-lost/zero-duplicated guarantees when
the processes are actually SIGKILLed:

- ``supervisor``: spawn/monitor/SIGKILL child processes under a
  deterministic schedule (chaos site ``dist.kill``), with bound-port
  handoff and readiness polling instead of sleeps;
- ``proxy``: a listen-and-forward socket proxy injecting transport
  faults at the wire (chaos site ``dist.proxy_fault``: connection
  resets, added latency, truncated writes, blackholes);
- ``serving``: LocalQueue-sharded front-end helpers — shard routing,
  the shard HTTP client, and shard-process recovery from its
  IngestJournal + CycleWAL;
- ``worker``: federation-worker process recovery from its
  ManifestJournal + CycleWAL full-history replay;
- ``child``: the ``python -m kueue_tpu.dist.child`` entry point every
  supervised process runs (roles: shard, worker, submitter).
"""
