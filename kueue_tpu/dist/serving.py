"""LocalQueue-sharded multi-process serving helpers.

The distributed front-end splits the admission service by its natural
partition key — the heap-per-ClusterQueue (PAPER.md L3): every
LocalQueue routes to exactly one shard process, whole cohorts stay
together (quota borrowing never crosses a shard), and each shard runs
a full ``AdmissionService`` over its own ``IngestJournal`` +
``CycleWAL``.  Because CQs outside a shard's cohorts receive no
submissions and an empty CQ admits nothing, the union of per-shard
decisions equals the single-process control bit for bit — the
dist-soak's parity arms enforce exactly that.

This module holds everything both ends need: the shard router, the
cluster topology builder (shared with the single-process control so
parity is by construction), shard-process build/recover, and the
parent-side :class:`ShardClient` that submits and drives lockstep
steps over HTTP.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
import zlib
from typing import Optional

from ..api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from ..controller.driver import Driver
from ..serving import AdmissionService, ServiceConfig, recover_service
from ..utils.journal import CycleWAL, IngestJournal

#: cohort width of the soak topology (cluster_spec groups cq-q into
#: cohort co-(q//4)); the shard router keys on it so borrowing repos
#: never straddle shards
COHORT_WIDTH = 4


class VirtualClock:
    """The soaks' mutable virtual clock (shared shape with
    scripts/serve_soak.py so services built either side tick alike)."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def cluster_spec(n_cqs: int):
    """The serve-soak topology: cohorts of 4, 4000m cpu each,
    BEST_EFFORT_FIFO, lq-N → cq-N.  Defined here so shard children and
    the single-process control build identical clusters from the same
    function."""
    def fn(d):
        d.apply_resource_flavor(ResourceFlavor(name="default"))
        for q in range(n_cqs):
            name = f"cq-{q}"
            d.apply_cluster_queue(ClusterQueue(
                name=name, cohort=f"co-{q // COHORT_WIDTH}",
                queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                preemption=PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources={
                        "cpu": ResourceQuota(nominal=4000)})])]))
            d.apply_local_queue(LocalQueue(name=f"lq-{q}",
                                           cluster_queue=name))
    return fn


def shard_of(queue_name: str, n_shards: int) -> int:
    """Route a LocalQueue to its front-end shard.

    ``lq-q`` routes by cohort (``(q // COHORT_WIDTH) % n_shards``) so
    every CQ that can borrow from a cohort-mate lands on the same
    shard; non-numeric names fall back to a stable content hash."""
    if n_shards <= 1:
        return 0
    if queue_name.startswith("lq-"):
        try:
            q = int(queue_name[3:])
            return (q // COHORT_WIDTH) % n_shards
        except ValueError:
            pass
    return zlib.crc32(queue_name.encode()) % n_shards


def workload_of(payload: dict) -> Workload:
    """Rebuild the exact workload a shard ingested from its journaled
    accept payload (the same construction as
    ``AdmissionService._workload_of``, timestamps included)."""
    return Workload(
        name=payload["name"], namespace=payload["namespace"],
        queue_name=payload["queue_name"], priority=payload["priority"],
        creation_time=payload["creation_time"],
        pod_sets=[PodSet(name="main", count=payload["count"],
                         requests=dict(payload["requests"]))])


def shard_paths(state_dir: str, shard_id: int) -> tuple[str, str]:
    return (f"{state_dir}/shard{shard_id}.wal",
            f"{state_dir}/shard{shard_id}.ingest")


def _shard_config(dt_s: float, epoch_t: float, journal_path: str,
                  high_water: int) -> ServiceConfig:
    # k_max=1 pins the deterministic lockstep arms, exactly like the
    # serve-soak kill arms; compaction stays off WAL-side so a killed
    # shard can replay its full decision history
    return ServiceConfig(dt_s=dt_s, k_max=1, journal_path=journal_path,
                         high_water=high_water, epoch_t=epoch_t)


def build_shard_service(shard_id: int, n_cqs: int, state_dir: str,
                        dt_s: float = 1.0, epoch_t: float = 1000.0,
                        high_water: int = 1 << 20
                        ) -> tuple[AdmissionService, VirtualClock]:
    """Fresh shard process: full topology (parity by construction — CQs
    of other shards stay empty), durable per-shard WAL + ingest
    journal."""
    wal_path, journal_path = shard_paths(state_dir, shard_id)
    clock = VirtualClock(epoch_t)
    d = Driver(clock=clock, use_device_solver=True)
    cluster_spec(n_cqs)(d)
    wal = CycleWAL(wal_path, compact_every=0)
    d.attach_wal(wal)
    svc = AdmissionService(
        d, config=_shard_config(dt_s, epoch_t, journal_path, high_water),
        wal=wal)
    return svc, clock


def recover_shard_service(shard_id: int, n_cqs: int, state_dir: str,
                          resume_cycle: int, dt_s: float = 1.0,
                          epoch_t: float = 1000.0,
                          high_water: int = 1 << 20
                          ) -> tuple[AdmissionService, VirtualClock]:
    """Rebuild a SIGKILLed shard from its durable journals alone.

    The initial store is every applied, non-shed accept payload from
    the ingest journal; the WAL's committed history replays every
    decision since onto it (``replay_history``), then
    ``recover_service`` rolls the uncommitted tail forward and
    re-enqueues the accepted-but-unapplied suffix.  ``resume_cycle``
    (the step count at kill, known to the lockstep parent) positions
    the virtual clock so cycle accounting continues where the dead
    process stopped."""
    wal_path, journal_path = shard_paths(state_dir, shard_id)
    wal = CycleWAL.resume(wal_path)
    jr = IngestJournal.load(journal_path)
    store: dict[str, Workload] = {}
    for rec in jr.accepted:
        if rec["seq"] in jr.shed_seqs or rec["seq"] > jr.applied_upto:
            continue
        wl = workload_of(rec["wl"])
        store[wl.key] = wl
    wal.replay_history(store)
    clock = VirtualClock(epoch_t + resume_cycle * dt_s)
    d = Driver(clock=clock, use_device_solver=True)
    cluster_spec(n_cqs)(d)
    svc = recover_service(
        d, list(store.values()), wal,
        config=_shard_config(dt_s, epoch_t, journal_path, high_water),
        journal_path=journal_path)
    return svc, clock


def step_payloads(step: int, submitter_id: int, n_submitters: int,
                  per_step: int, n_cqs: int,
                  runtime_s: float = 3.0) -> list[dict]:
    """The deterministic submission schedule: the payloads submitter
    ``submitter_id`` sends at lockstep barrier ``step``.

    Both sides of every parity check call this — the submitter child
    processes and the single-process control — so the distributed run
    and its control receive byte-identical workloads by construction.
    Global index = ``(step * n_submitters + submitter_id) * per_step +
    i`` keeps names unique across submitters and steps; queues
    round-robin over all LocalQueues so every shard sees traffic."""
    out = []
    for i in range(per_step):
        idx = (step * n_submitters + submitter_id) * per_step + i
        name = f"wl-{idx}"
        out.append({"name": name, "namespace": "default",
                    "queue_name": f"lq-{idx % n_cqs}", "priority": 0,
                    "requests": {"cpu": 1000}, "count": 1,
                    "runtime_s": runtime_s,
                    "token": f"default/{name}"})
    return out


class ShardClient:
    """Parent-side HTTP client for one shard (or service) process:
    submits through the public serving API and drives the lockstep
    ``/admin`` barriers.  Submissions retry through connect-refused
    and reset windows (a shard mid-restart) under a wall deadline —
    idempotent tokens make the retry safe and the dedupe observable."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 10.0):
        self.base_url = f"http://{host}:{port}"
        self.timeout = timeout
        self.stats = {"requests": 0, "retries": 0}

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              retry_deadline_s: float = 0.0):
        deadline = time.monotonic() + retry_deadline_s
        while True:
            self.stats["requests"] += 1
            data = None if body is None else json.dumps(body).encode()
            req = urllib.request.Request(
                self.base_url + path, data=data, method=method,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout) as resp:
                    payload = resp.read()
                return json.loads(payload) if payload else None
            except urllib.error.HTTPError as e:
                # 429/503 are application outcomes, not transport faults
                payload = e.read()
                try:
                    return json.loads(payload) if payload else None
                except json.JSONDecodeError:
                    return None
            except Exception as e:
                import http.client
                transient = isinstance(
                    e, (OSError, http.client.HTTPException))
                if not transient or time.monotonic() >= deadline:
                    raise
                self.stats["retries"] += 1
                time.sleep(0.05)

    # -- public serving API --

    def submit(self, body: dict, retry_deadline_s: float = 0.0) -> dict:
        return self._call("POST", "/apis/serving/v1/submit", body,
                          retry_deadline_s=retry_deadline_s)

    def svc_stats(self) -> dict:
        return self._call("GET", "/apis/serving/v1/stats")

    def position(self, token: str) -> dict:
        from urllib.parse import quote
        return self._call(
            "GET", f"/apis/serving/v1/position?token={quote(token, safe='')}")

    # -- lockstep barriers --

    def step(self, retry_deadline_s: float = 0.0) -> dict:
        return self._call("POST", "/admin/step", {},
                          retry_deadline_s=retry_deadline_s)

    def drain(self) -> dict:
        return self._call("POST", "/admin/drain", {})

    def digest(self) -> dict:
        return self._call("GET", "/admin/digest")

    def ready(self) -> bool:
        try:
            return self._call("GET", "/readyz") is not None
        except (urllib.error.URLError, OSError, ConnectionError):
            return False
