"""``python -m kueue_tpu.dist.child`` — the supervised process body.

Every child of a distributed run executes this module with a
``--role``:

- ``shard``: one LocalQueue-sharded admission front-end — a full
  ``AdmissionService`` over its own ``IngestJournal`` + ``CycleWAL``,
  served by ``VisibilityServer`` with the lockstep ``/admin``
  endpoints enabled.  ``--recover --resume-cycle N`` rebuilds the
  state a SIGKILLed predecessor left in ``--state-dir``.
- ``worker``: one federation worker — a Driver with the worker
  topology behind a ``WorkerServer`` (manifest journal + WAL make it
  recoverable the same way).
- ``submitter``: a lockstep traffic source driven over stdin
  (``step S`` / ``resync S`` / ``blast N`` / ``stats`` / ``exit``),
  submitting the deterministic :func:`~.serving.step_payloads`
  schedule through each shard's public HTTP API with idempotent
  tokens.

Port handoff: servers write their bound port to ``--port-file``
*after* bind (atomic rename), which is what the supervisor's
``wait_ready`` polls — no guessed sleeps anywhere.  ``--crash-site``
arms this process's own chaos injector; an ``InjectedCrash`` escaping
the wrapped step turns into ``os._exit(17)`` — a real mid-cycle
process death, not an exception a handler could swallow.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Optional

#: exit code a chaos-crashed child dies with (distinguishes an armed
#: InjectedCrash from a genuine fault in soak triage)
CRASH_EXIT = 17


def _write_port_file(path: str, port: int) -> None:
    """Atomic bound-port handoff: the supervisor never reads a torn
    write."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(str(port))
    os.replace(tmp, path)


def _arm_crash(site: str, at: int) -> None:
    """Install this process's own injector with one armed crash."""
    from ..chaos import injector as chaos
    inj = chaos.ChaosInjector(seed=0)
    inj.arm(site, at=at)
    chaos.install(inj)


def _dying(fn):
    """Wrap a step function so an armed InjectedCrash kills the whole
    process (SIGKILL-equivalent: no cleanup, no flush)."""
    from ..chaos.injector import InjectedCrash

    def wrapper(*a, **kw):
        try:
            return fn(*a, **kw)
        except InjectedCrash:
            os._exit(CRASH_EXIT)
    return wrapper


def _serve_forever() -> None:
    threading.Event().wait()


# ---------------------------------------------------------------------------
# Roles
# ---------------------------------------------------------------------------

def run_shard(args) -> int:
    from ..visibility import VisibilityServer
    from .serving import build_shard_service, recover_shard_service
    if args.recover:
        svc, _clock = recover_shard_service(
            args.shard_id, args.n_cqs, args.state_dir,
            resume_cycle=args.resume_cycle, dt_s=args.dt_s,
            epoch_t=args.epoch_t, high_water=args.high_water)
    else:
        svc, _clock = build_shard_service(
            args.shard_id, args.n_cqs, args.state_dir, dt_s=args.dt_s,
            epoch_t=args.epoch_t, high_water=args.high_water)
    if args.crash_site:
        _arm_crash(args.crash_site, args.crash_at)
        svc.step = _dying(svc.step)
    server = VisibilityServer(svc.driver, port=args.port,
                              admission=svc, admin=True)
    port = server.start()
    if args.port_file:
        _write_port_file(args.port_file, port)
    _serve_forever()
    return 0


def run_worker(args) -> int:
    from ..remote import WorkerServer
    from .worker import build_worker, recover_worker
    if args.recover:
        d, clock, _wal, journal, _n = recover_worker(
            args.name, args.remote_cqs, args.state_dir,
            quota_m=args.quota_m, epoch_t=args.epoch_t,
            resume_t=args.resume_t)
    else:
        d, clock, _wal, journal = build_worker(
            args.name, args.remote_cqs, args.state_dir,
            quota_m=args.quota_m, epoch_t=args.epoch_t)
    if args.crash_site:
        _arm_crash(args.crash_site, args.crash_at)
        d.schedule_once = _dying(d.schedule_once)
    server = WorkerServer(d, port=args.port, journal=journal,
                          admin=True, clock=clock)
    server.start()
    if args.port_file:
        _write_port_file(args.port_file, server.port)
    _serve_forever()
    return 0


def run_submitter(args) -> int:
    from .serving import ShardClient, shard_of, step_payloads
    ports = [int(p) for p in args.shard_ports.split(",") if p]
    clients = [ShardClient(p, timeout=args.timeout) for p in ports]
    n_shards = len(clients)
    counts = {"submitted": 0, "accepted": 0, "duplicates": 0,
              "rejected": 0, "blasted": 0}
    blast_seq = 0

    def submit_one(body: dict) -> None:
        shard = shard_of(body["queue_name"], n_shards)
        res = clients[shard].submit(
            body, retry_deadline_s=args.retry_deadline) or {}
        counts["submitted"] += 1
        status = res.get("status")
        if res.get("duplicate"):
            counts["duplicates"] += 1
        elif status == "accepted":
            counts["accepted"] += 1
        else:
            counts["rejected"] += 1

    def submit_step(step: int) -> None:
        for body in step_payloads(step, args.submitter_id,
                                  args.n_submitters, args.per_step,
                                  args.n_cqs, runtime_s=args.runtime_s):
            submit_one(body)

    print("ready", flush=True)
    for line in sys.stdin:
        parts = line.split()
        if not parts:
            continue
        cmd = parts[0]
        if cmd == "step":
            step = int(parts[1])
            before = counts["accepted"]
            submit_step(step)
            print(f"done {step} {counts['accepted'] - before} "
                  f"{counts['duplicates']}", flush=True)
        elif cmd == "resync":
            # resubmit every payload of steps 0..S-1; idempotent
            # tokens turn the replays into observable dedupes
            upto = int(parts[1])
            before_dup = counts["duplicates"]
            for step in range(upto):
                submit_step(step)
            print(f"resynced {upto} "
                  f"{counts['duplicates'] - before_dup}", flush=True)
        elif cmd == "blast":
            # wall-clock saturation lane: n uniquely-named submissions
            # round-robin over every queue, as fast as the wire allows
            n = int(parts[1])
            t0 = time.monotonic()
            before = counts["accepted"]
            for _ in range(n):
                idx = blast_seq
                blast_seq += 1
                name = f"bl-{args.submitter_id}-{idx}"
                submit_one({
                    "name": name, "namespace": "default",
                    "queue_name": f"lq-{idx % args.n_cqs}",
                    "priority": 0, "requests": {"cpu": 1000},
                    "count": 1, "runtime_s": args.runtime_s,
                    "token": f"default/{name}"})
            counts["blasted"] += n
            print(f"blasted {n} {counts['accepted'] - before} "
                  f"{time.monotonic() - t0:.6f}", flush=True)
        elif cmd == "stats":
            out = dict(counts)
            out["requests"] = sum(c.stats["requests"] for c in clients)
            out["retries"] = sum(c.stats["retries"] for c in clients)
            print(json.dumps(out), flush=True)
        elif cmd == "exit":
            print("bye", flush=True)
            return 0
        else:
            print(f"err unknown command {cmd!r}", flush=True)
    return 0


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------

def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(prog="kueue_tpu.dist.child")
    ap.add_argument("--role", required=True,
                    choices=["shard", "worker", "submitter"])
    # common / servers
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--port-file", default="")
    ap.add_argument("--state-dir", default=".")
    ap.add_argument("--recover", type=int, default=0)
    ap.add_argument("--crash-site", default="")
    ap.add_argument("--crash-at", type=int, default=1)
    # shard
    ap.add_argument("--shard-id", type=int, default=0)
    ap.add_argument("--n-cqs", type=int, default=8)
    ap.add_argument("--dt-s", type=float, default=1.0)
    ap.add_argument("--epoch-t", type=float, default=1000.0)
    ap.add_argument("--high-water", type=int, default=1 << 20)
    ap.add_argument("--resume-cycle", type=int, default=0)
    # worker
    ap.add_argument("--name", default="w0")
    ap.add_argument("--remote-cqs", type=int, default=4)
    ap.add_argument("--quota-m", type=int, default=4000)
    ap.add_argument("--resume-t", type=float, default=None)
    # submitter
    ap.add_argument("--submitter-id", type=int, default=0)
    ap.add_argument("--n-submitters", type=int, default=1)
    ap.add_argument("--per-step", type=int, default=4)
    ap.add_argument("--shard-ports", default="")
    ap.add_argument("--runtime-s", type=float, default=3.0)
    ap.add_argument("--retry-deadline", type=float, default=10.0)
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    if args.role == "shard":
        return run_shard(args)
    if args.role == "worker":
        return run_worker(args)
    return run_submitter(args)


if __name__ == "__main__":
    sys.exit(main())
