"""Feature gates (reference pkg/features/kube_features.go:31-255).

Versioned defaults mirroring the reference at its snapshot (≈ v0.11):
each gate carries (default, stage, lock_to_default).  ``enabled(name)``
is the runtime check; ``set_feature_gate_during_test`` is the test
override (kube_features.go:257 SetFeatureGateDuringTest).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str                # Alpha | Beta | GA | Deprecated
    lock_to_default: bool = False


# Defaults as of the reference snapshot (kube_features.go:179-255, the
# highest-version entry of each VersionedSpecs list).
DEFAULT_FEATURE_GATES: dict[str, FeatureSpec] = {
    "PartialAdmission": FeatureSpec(True, "Beta"),
    "QueueVisibility": FeatureSpec(False, "Deprecated"),
    "FlavorFungibility": FeatureSpec(True, "Beta"),
    "ProvisioningACC": FeatureSpec(True, "Beta"),
    "VisibilityOnDemand": FeatureSpec(True, "Beta"),
    "PrioritySortingWithinCohort": FeatureSpec(True, "Beta"),
    "MultiKueue": FeatureSpec(True, "Beta"),
    "LendingLimit": FeatureSpec(True, "Beta"),
    "MultiKueueBatchJobWithManagedBy": FeatureSpec(False, "Alpha"),
    "MultiplePreemptions": FeatureSpec(True, "GA", lock_to_default=True),
    "TopologyAwareScheduling": FeatureSpec(False, "Alpha"),
    "ConfigurableResourceTransformations": FeatureSpec(True, "Beta"),
    "WorkloadResourceRequestsSummary": FeatureSpec(True, "GA",
                                                   lock_to_default=True),
    "ExposeFlavorsInLocalQueue": FeatureSpec(True, "Beta"),
    "AdmissionCheckValidationRules": FeatureSpec(False, "Deprecated"),
    "KeepQuotaForProvReqRetry": FeatureSpec(False, "Deprecated"),
    "ManagedJobsNamespaceSelector": FeatureSpec(True, "Beta"),
    "LocalQueueMetrics": FeatureSpec(False, "Alpha"),
    "LocalQueueDefaulting": FeatureSpec(False, "Alpha"),
    "TASProfileMostFreeCapacity": FeatureSpec(False, "Alpha"),
    "TASProfileLeastFreeCapacity": FeatureSpec(False, "Alpha"),
    "TASProfileMixed": FeatureSpec(False, "Alpha"),
    # kueue-tpu extension: route find_topology_assignment through the
    # batched segment-tree kernel (ops/tas_kernel) — implements all
    # three TAS profiles, bit-matching the scalar tree walk
    "TASDeviceKernel": FeatureSpec(True, "Beta"),
}

_overrides: dict[str, bool] = {}


class UnknownFeatureError(KeyError):
    pass


def enabled(name: str) -> bool:
    if name in _overrides:
        return _overrides[name]
    spec = DEFAULT_FEATURE_GATES.get(name)
    if spec is None:
        raise UnknownFeatureError(name)
    return spec.default


def set_feature_gates(gates: dict[str, bool]) -> None:
    """Apply --feature-gates style overrides (cmd/kueue/main.go:129-144)."""
    for name, value in gates.items():
        spec = DEFAULT_FEATURE_GATES.get(name)
        if spec is None:
            raise UnknownFeatureError(name)
        if spec.lock_to_default and value != spec.default:
            raise ValueError(
                f"cannot set feature gate {name} to {value}: locked to "
                f"{spec.default} ({spec.stage})")
        _overrides[name] = value


def reset_feature_gates() -> None:
    _overrides.clear()


@contextlib.contextmanager
def set_feature_gate_during_test(name: str, value: bool):
    """reference kube_features.go:257."""
    had = name in _overrides
    prev = _overrides.get(name)
    set_feature_gates({name: value})
    try:
        yield
    finally:
        if had:
            _overrides[name] = prev
        else:
            _overrides.pop(name, None)
