"""Feature gates and the environment-flag registry.

Feature gates (reference pkg/features/kube_features.go:31-255):
versioned defaults mirroring the reference at its snapshot (≈ v0.11);
each gate carries (default, stage, lock_to_default).  ``enabled(name)``
is the runtime check; ``set_feature_gate_during_test`` is the test
override (kube_features.go:257 SetFeatureGateDuringTest).

``ENV_FLAGS`` is the single declared registry of every ``KUEUE_TPU_*``
environment variable the stack reads.  All reads go through
:func:`env_value` / :func:`env_int`, which refuse names missing from
the registry — the static-analysis env pass (``analysis/env_flags.py``)
flags any ad-hoc ``os.environ`` read of a ``KUEUE_TPU_*`` name and any
drift between this table and the README flag table.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str                # Alpha | Beta | GA | Deprecated
    lock_to_default: bool = False


# Defaults as of the reference snapshot (kube_features.go:179-255, the
# highest-version entry of each VersionedSpecs list).
DEFAULT_FEATURE_GATES: dict[str, FeatureSpec] = {
    "PartialAdmission": FeatureSpec(True, "Beta"),
    "QueueVisibility": FeatureSpec(False, "Deprecated"),
    "FlavorFungibility": FeatureSpec(True, "Beta"),
    "ProvisioningACC": FeatureSpec(True, "Beta"),
    "VisibilityOnDemand": FeatureSpec(True, "Beta"),
    "PrioritySortingWithinCohort": FeatureSpec(True, "Beta"),
    "MultiKueue": FeatureSpec(True, "Beta"),
    "LendingLimit": FeatureSpec(True, "Beta"),
    "MultiKueueBatchJobWithManagedBy": FeatureSpec(False, "Alpha"),
    "MultiplePreemptions": FeatureSpec(True, "GA", lock_to_default=True),
    "TopologyAwareScheduling": FeatureSpec(False, "Alpha"),
    "ConfigurableResourceTransformations": FeatureSpec(True, "Beta"),
    "WorkloadResourceRequestsSummary": FeatureSpec(True, "GA",
                                                   lock_to_default=True),
    "ExposeFlavorsInLocalQueue": FeatureSpec(True, "Beta"),
    "AdmissionCheckValidationRules": FeatureSpec(False, "Deprecated"),
    "KeepQuotaForProvReqRetry": FeatureSpec(False, "Deprecated"),
    "ManagedJobsNamespaceSelector": FeatureSpec(True, "Beta"),
    "LocalQueueMetrics": FeatureSpec(False, "Alpha"),
    "LocalQueueDefaulting": FeatureSpec(False, "Alpha"),
    "TASProfileMostFreeCapacity": FeatureSpec(False, "Alpha"),
    "TASProfileLeastFreeCapacity": FeatureSpec(False, "Alpha"),
    "TASProfileMixed": FeatureSpec(False, "Alpha"),
    # kueue-tpu extension: route find_topology_assignment through the
    # batched segment-tree kernel (ops/tas_kernel) — implements all
    # three TAS profiles, bit-matching the scalar tree walk
    "TASDeviceKernel": FeatureSpec(True, "Beta"),
}

_overrides: dict[str, bool] = {}


class UnknownFeatureError(KeyError):
    pass


def enabled(name: str) -> bool:
    if name in _overrides:
        return _overrides[name]
    spec = DEFAULT_FEATURE_GATES.get(name)
    if spec is None:
        raise UnknownFeatureError(name)
    return spec.default


def set_feature_gates(gates: dict[str, bool]) -> None:
    """Apply --feature-gates style overrides (cmd/kueue/main.go:129-144)."""
    for name, value in gates.items():
        spec = DEFAULT_FEATURE_GATES.get(name)
        if spec is None:
            raise UnknownFeatureError(name)
        if spec.lock_to_default and value != spec.default:
            raise ValueError(
                f"cannot set feature gate {name} to {value}: locked to "
                f"{spec.default} ({spec.stage})")
        _overrides[name] = value


def reset_feature_gates() -> None:
    _overrides.clear()


# ---------------------------------------------------------------------------
# Environment-flag registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class EnvFlag:
    """One declared ``KUEUE_TPU_*`` environment variable.

    ``default`` is the *raw string* handed back when the variable is
    unset — call sites keep their own parse/compare idiom (``!= "0"``,
    ``int(...)``, truthiness) so centralizing the read cannot change
    semantics.  ``type`` is documentation for the README table."""
    name: str
    default: str
    type: str                 # bool | int | str | path
    doc: str


# Every KUEUE_TPU_* variable the stack reads, in one place.  The env
# pass fails the lint if a read bypasses this table or if the README
# "Environment flags" table disagrees with it.
ENV_FLAGS: dict[str, EnvFlag] = {f.name: f for f in (
    EnvFlag("KUEUE_TPU_STATE", ".kueue-tpu", "path",
            "CLI state directory (durable store + WAL)."),
    EnvFlag("KUEUE_TPU_SHARDS", "0", "int",
            "Shard count for the (\"cq\",) mesh; 0 = serial path."),
    EnvFlag("KUEUE_TPU_ACCEL_MIN_HEADS", "512", "int",
            "Min solver heads before dispatching to the accelerator."),
    EnvFlag("KUEUE_TPU_REQUIRE_ACCEL", "0", "bool",
            "Die rather than fall back to CPU (perf harness guard)."),
    EnvFlag("KUEUE_TPU_STREAM_PACK", "1", "bool",
            "Streaming delta-pack of the persistent packed universe."),
    EnvFlag("KUEUE_TPU_PACK_TIGHTEN", "1", "bool",
            "Dtype-tighten launch planes (int32 -> int16/int8)."),
    EnvFlag("KUEUE_TPU_RESIDENT", "1", "bool",
            "Shard-resident burst state planes on the device mesh."),
    EnvFlag("KUEUE_TPU_RESIDENT_VERIFY", "", "bool",
            "Cross-check resident planes against host scatter."),
    EnvFlag("KUEUE_TPU_SNAP_INCREMENTAL", "1", "bool",
            "Incremental O(dirty) snapshot maintenance in the cache."),
    EnvFlag("KUEUE_TPU_COMPILE_CACHE", "", "path",
            "XLA compile-cache dir; \"0\" disables, empty = default."),
    EnvFlag("KUEUE_TPU_WAL_COMMIT_EVERY", "1", "int",
            "CycleWAL group-commit interval (ops per fsync)."),
    EnvFlag("KUEUE_TPU_CHAOS_SEED", "", "int",
            "Seed the process-default chaos injector; empty = off."),
    EnvFlag("KUEUE_TPU_SCALE_SEED", "1307", "int",
            "Seed for the scale-soak scenario generator."),
    EnvFlag("KUEUE_TPU_TRAFFIC_SEED", "1109", "int",
            "Seed for the open-loop traffic soak."),
    EnvFlag("KUEUE_TPU_FED_SEED", "1511", "int",
            "Seed for the federation soak."),
    EnvFlag("KUEUE_TPU_REMOTE_RETRIES", "2", "int",
            "Per-request retry budget for HttpWorkerClient."),
    EnvFlag("KUEUE_TPU_REMOTE_DEADLINE_S", "15", "int",
            "Total per-request deadline (attempts + backoff sleeps) "
            "for HttpWorkerClient, seconds."),
    EnvFlag("KUEUE_TPU_OBS_TRACE", "0", "bool",
            "Enable hot-path span tracing at driver construction."),
    EnvFlag("KUEUE_TPU_OBS_EVENTS", "4096", "int",
            "Event-stream ring capacity (admit/evict/preempt/...)."),
    EnvFlag("KUEUE_TPU_FLIGHT_CYCLES", "256", "int",
            "Flight-recorder ring capacity, in cycles."),
    EnvFlag("KUEUE_TPU_SVC_HIGH_WATER", "4096", "int",
            "Serving ingest-queue depth past which backpressure "
            "rejects/sheds submissions."),
    EnvFlag("KUEUE_TPU_SVC_SLO_P99_S", "8.0", "str",
            "Serving p99 admission-latency SLO target, seconds."),
    EnvFlag("KUEUE_TPU_SVC_DRAIN_TIMEOUT_S", "30", "int",
            "Graceful-drain deadline after SIGTERM, wall seconds."),
    EnvFlag("KUEUE_TPU_SVC_INGEST_JOURNAL", "", "path",
            "Durable ingest-journal path; empty = in-memory only."),
    EnvFlag("KUEUE_TPU_SVC_SEED", "1709", "int",
            "Seed for the serving soak."),
    EnvFlag("KUEUE_TPU_AGG_PLANES", "1", "bool",
            "Cohort-forest compression: keep admitted rows of "
            "non-preempting forests out of the packed planes and track "
            "them in per-CQ aggregates instead."),
    EnvFlag("KUEUE_TPU_LAZY_HEAP", "1", "bool",
            "Lazy heap repair: buffer pushes/updates and settle with "
            "one amortized sift pass at the next ordered read."),
    EnvFlag("KUEUE_TPU_CYCLE_BULK_APPLY", "1", "bool",
            "Batch each burst cycle's decision patches into one "
            "requeue-wakeup pass and one deferred cache rebuild."),
    EnvFlag("KUEUE_TPU_WAL_SHARDS", "1", "int",
            "CycleWAL segment count; >1 stripes group-commit across "
            "that many journal files with merged total-order replay."),
    EnvFlag("KUEUE_TPU_HEAD_PACK", "1", "bool",
            "Head-only packing: charge the kernel's 2^19 composite-key "
            "row budget (uid rank + poison gates) only to rows of "
            "forests that can preempt; pending rows of never-preempting "
            "forests ride along as rank context outside the budget."),
    EnvFlag("KUEUE_TPU_HOST_WORKERS", "0", "int",
            "Worker threads for the parallel host apply/pack plane "
            "(cache rebuild fan-out, dirty-CQ pack walk, requeue "
            "wakeups, WAL shard appends); 0 or 1 = serial."),
    EnvFlag("KUEUE_TPU_DIST_SEED", "2003", "int",
            "Seed for the distributed soak: process-kill schedule and "
            "the socket-fault proxy's per-connection rolls."),
    EnvFlag("KUEUE_TPU_DIST_SHARDS", "2", "int",
            "Front-end shard processes in the distributed soak (the "
            "LocalQueue-sharded admission services)."),
    EnvFlag("KUEUE_TPU_DIST_SUBMITTERS", "2", "int",
            "Submitter processes hammering the serving API in the "
            "distributed soak."),
    EnvFlag("KUEUE_TPU_DIST_WORKERS", "2", "int",
            "Federation worker processes in the distributed soak."),
    EnvFlag("KUEUE_TPU_DIST_PROXY_RESET", "0.0", "str",
            "Socket-fault proxy: per-connection probability of a hard "
            "RST before the request reaches upstream."),
    EnvFlag("KUEUE_TPU_DIST_PROXY_LATENCY_S", "0.0", "str",
            "Socket-fault proxy: seconds of added latency before "
            "dialing upstream (0 disables the latency fault)."),
    EnvFlag("KUEUE_TPU_DIST_PROXY_TRUNCATE", "0.0", "str",
            "Socket-fault proxy: per-connection probability of "
            "truncating the response mid-body and resetting."),
    EnvFlag("KUEUE_TPU_DIST_PROXY_BLACKHOLE", "0.0", "str",
            "Socket-fault proxy: per-connection probability of "
            "swallowing the request and never answering."),
)}


class UnknownEnvFlagError(KeyError):
    pass


def env_value(name: str, default: str | None = None) -> str:
    """Read a registered ``KUEUE_TPU_*`` variable as a raw string.

    ``default`` overrides the registry default for call sites whose
    fallback is context-dependent (e.g. the soaks); it must still name
    a registered flag."""
    spec = ENV_FLAGS.get(name)
    if spec is None:
        raise UnknownEnvFlagError(name)
    return os.environ.get(name, spec.default if default is None else default)


def env_int(name: str, default: int | None = None) -> int:
    """Read a registered flag as an int; malformed values fall back to
    the (registry or caller) default instead of raising."""
    spec = ENV_FLAGS.get(name)
    if spec is None:
        raise UnknownEnvFlagError(name)
    fallback = spec.default if default is None else str(default)
    raw = os.environ.get(name, fallback) or fallback
    try:
        return int(raw)
    except ValueError:
        return int(fallback or 0)


@contextlib.contextmanager
def set_feature_gate_during_test(name: str, value: bool):
    """reference kube_features.go:257."""
    had = name in _overrides
    prev = _overrides.get(name)
    set_feature_gates({name: value})
    try:
        yield
    finally:
        if had:
            _overrides[name] = prev
        else:
            _overrides.pop(name, None)
