"""Persistent XLA compilation cache wiring.

The solver plane compiles one XLA program per (kernel, shape-bucket)
rung; a cold daemon at north-star scale paid ~3 minutes of compiles in
round 3 (BENCH_r03 warmup) and paid them again on every restart.  The
JAX persistent compilation cache makes those one-time: compiled
executables are serialized under a cache directory and reloaded by any
later process on the same machine (verified to cover the XLA:CPU backend
on jax 0.9 — a second cold process loads the fused burst kernel in ~0.4s
vs 2.4s to compile it).

Reference analog: the Go scheduler has no compile step at all
(minimalkueue starts in milliseconds — test/performance/scheduler/
minimalkueue/main.go), so amortizing ours across restarts is part of
matching its operational profile (verdict r3 item 7).

Enabled by default wherever a solver is constructed; opt out with
``KUEUE_TPU_COMPILE_CACHE=0`` or point the cache elsewhere with
``KUEUE_TPU_COMPILE_CACHE=/path``.

Note: loading an XLA:CPU AOT entry logs a noisy machine-feature warning
("+prefer-no-scatter is not supported") — those are XLA tuning
pseudo-features, not ISA bits; same-machine reuse is safe.
"""

from __future__ import annotations

import os

from .features import env_value

_enabled_dir: str | None = None


def enable(cache_dir: str | None = None,
           min_compile_secs: float = 0.3) -> str | None:
    """Idempotently point JAX at a persistent compilation cache.

    Returns the cache directory, or None when disabled via env."""
    global _enabled_dir
    env = env_value("KUEUE_TPU_COMPILE_CACHE")
    if env == "0":
        return None
    if _enabled_dir is not None:
        return _enabled_dir
    d = cache_dir or env or os.path.expanduser("~/.cache/kueue_tpu/xla")
    try:
        os.makedirs(d, exist_ok=True)
        # loading an XLA:CPU AOT cache entry logs two multi-KB ERROR
        # lines about tuning pseudo-features per load; silence XLA's
        # C++ logging for cache users (KUEUE_TPU_COMPILE_CACHE=0 to
        # debug with full logs)
        os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
        import jax
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        # cache small entries too: the solver's rungs are many small
        # programs, and a daemon restart pays all of them
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        return None
    _enabled_dir = d
    return d


def load_json(name: str, cache_dir: str | None = None):
    """Read a sidecar JSON artifact (e.g. the router calibration table)
    from the compile-cache directory; None when absent/disabled."""
    import json
    d = cache_dir or enable()
    if d is None:
        return None
    try:
        with open(os.path.join(d, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def save_json(name: str, obj, cache_dir: str | None = None) -> bool:
    """Write a sidecar JSON artifact next to the compile cache
    (atomic rename; best effort)."""
    import json
    d = cache_dir or enable()
    if d is None:
        return False
    try:
        tmp = os.path.join(d, f".{name}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, os.path.join(d, name))
        return True
    except OSError:
        return False
