"""GenericJob SPI and the integration registry.

Capability parity with reference pkg/controller/jobframework/interface.go
(GenericJob :41-65 and its optional sub-interfaces) and
integrationmanager.go (RegisterIntegration :248, ForEachIntegration :260).
"""

from __future__ import annotations

import abc
import enum
import hashlib
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from ..api.types import PodSet, Workload
from ..podset import PodSetInfo


class StopReason(enum.Enum):
    """reference jobframework/interface.go StopReason."""
    WORKLOAD_DELETED = "WorkloadDeleted"
    WORKLOAD_EVICTED = "WorkloadEvicted"
    NO_MATCHING_WORKLOAD = "NoMatchingWorkload"
    NOT_ADMITTED = "NotAdmitted"


class GenericJob(abc.ABC):
    """reference jobframework/interface.go:41 GenericJob.

    A 'job' is any externally-defined unit of work gated by the framework:
    it can be suspended (held) and resumed with admission-derived pod-set
    info injected.
    """

    # -- identity ------------------------------------------------------

    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @property
    def namespace(self) -> str:
        return "default"

    @property
    @abc.abstractmethod
    def gvk(self) -> str:
        """Kind string, e.g. "BatchJob"."""

    @property
    def key(self) -> str:
        return f"{self.gvk}/{self.namespace}/{self.name}"

    @property
    def queue_name(self) -> str:
        return getattr(self, "queue", "")

    @property
    def priority_class_name(self) -> str:
        return ""

    # -- gating --------------------------------------------------------

    @abc.abstractmethod
    def is_suspended(self) -> bool: ...

    @abc.abstractmethod
    def suspend(self) -> None: ...

    @abc.abstractmethod
    def run_with_podsets_info(self, infos: Sequence[PodSetInfo]) -> None:
        """Unsuspend, injecting node selectors/tolerations/counts
        (reference interface.go:49 RunWithPodSetsInfo)."""

    def restore_podsets_info(self, infos: Sequence[PodSetInfo]) -> bool:
        """Restore original pod templates on suspension (interface.go:53).
        Returns True if anything changed."""
        return False

    # -- observation ---------------------------------------------------

    @abc.abstractmethod
    def pod_sets(self) -> list[PodSet]:
        """The workload's pod sets (reference interface.go:57)."""

    @abc.abstractmethod
    def finished(self) -> tuple[str, bool, bool]:
        """(message, success, finished) — reference interface.go:55."""

    def is_active(self) -> bool:
        """Any pods are running (reference interface.go:59)."""
        return not self.is_suspended()

    def pods_ready(self) -> bool:
        """All pods running+ready (reference interface.go:61)."""
        return self.is_active()

    def sync_status_from(self, other: "GenericJob") -> None:
        """Copy execution status from a remote copy of this job
        (MultiKueue adapter copy-back, reference workload.go)."""


class JobWithReclaimablePods(abc.ABC):
    """reference interface.go:75."""

    @abc.abstractmethod
    def reclaimable_pods(self) -> dict[str, int]:
        """pod-set name → count of pods no longer needed."""


class JobWithCustomStop(abc.ABC):
    """reference interface.go:89."""

    @abc.abstractmethod
    def stop(self, infos: Sequence[PodSetInfo], reason: StopReason,
             message: str) -> bool: ...


class JobWithManagedBy(abc.ABC):
    """reference interface.go:158 — MultiKueue dispatch support."""

    @abc.abstractmethod
    def managed_by(self) -> Optional[str]: ...

    @abc.abstractmethod
    def set_managed_by(self, manager: Optional[str]) -> None: ...


class ComposableJob(abc.ABC):
    """A job composed from several objects, e.g. a pod group
    (reference interface.go:124)."""

    @abc.abstractmethod
    def construct_composable_workload(self) -> Workload: ...

    @abc.abstractmethod
    def list_members(self) -> list: ...


# ---------------------------------------------------------------------------
# Registry (reference integrationmanager.go)
# ---------------------------------------------------------------------------

@dataclass
class IntegrationCallbacks:
    """reference integrationmanager.go:40."""
    name: str
    gvk: str
    new_job: Callable[..., GenericJob]
    # frameworks that must also be enabled for this one to work
    depends_on: tuple[str, ...] = ()
    add_to_default: bool = True


_registry: dict[str, IntegrationCallbacks] = {}
_by_gvk: dict[str, IntegrationCallbacks] = {}


def register_integration(cb: IntegrationCallbacks) -> None:
    """reference integrationmanager.go:248 RegisterIntegration."""
    if cb.name in _registry:
        raise ValueError(f"integration {cb.name} already registered")
    _registry[cb.name] = cb
    _by_gvk[cb.gvk] = cb


def get_integration(name: str) -> Optional[IntegrationCallbacks]:
    return _registry.get(name) or _by_gvk.get(name)


def for_each_integration(fn: Callable[[IntegrationCallbacks], None],
                         enabled: Optional[set[str]] = None) -> None:
    """reference integrationmanager.go:260 ForEachIntegration."""
    for name in sorted(_registry):
        cb = _registry[name]
        if enabled is None or name in enabled:
            fn(cb)


def workload_name_for_job(gvk: str, job_name: str) -> str:
    """Deterministic workload naming (reference
    jobframework/workload_names.go): kind prefix + job name + short hash,
    bounded to DNS-label length."""
    prefix = gvk.lower()
    base = f"{prefix}-{job_name}"
    digest = hashlib.sha256(base.encode()).hexdigest()[:5]
    if len(base) > 57:
        base = base[:57]
    return f"{base}-{digest}"
