"""Job-level admission webhooks: defaulting + validation.

Mirrors the reference's jobframework validation layer
(pkg/controller/jobframework/validation.go:65-170, tas_validation.go:29-74)
and the per-kind webhooks built on it (pod_webhook.go:228-356,
kubeflowjob_controller.go:182-200).  Library-form, like
``kueue_tpu.webhooks.validation``: callers invoke
``validate_job_create`` / ``validate_job_update`` before handing a job
to the ``JobManager``; the manager also runs them on ``upsert``.
"""

from __future__ import annotations

import re
from typing import Optional

from ..webhooks.validation import ValidationError, valid_dns1123_subdomain
from .interface import GenericJob

MANAGED_LABEL = "kueue.x-k8s.io/managed"          # constants.go:45
MANAGED_LABEL_VALUE = "true"
RETRIABLE_IN_GROUP_ANNOTATION = "kueue.x-k8s.io/retriable-in-group"
PREBUILT_WORKLOAD_LABEL = "kueue.x-k8s.io/prebuilt-workload-name"

_LABEL_NAME = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")


def _valid_label_name(value: str) -> bool:
    """A qualified label name: optional DNS-subdomain prefix + name part
    (metavalidation.ValidateLabelName)."""
    if not value:
        return False
    if "/" in value:
        prefix, _, name = value.partition("/")
        if not valid_dns1123_subdomain(prefix):
            return False
    else:
        name = value
    return len(name) <= 63 and bool(_LABEL_NAME.match(name))


def validate_tas_podset_request(path: str, topology_request) -> list[str]:
    """At most one topology annotation per podset, each a valid label
    name (reference tas_validation.go:29-74)."""
    errors: list[str] = []
    if topology_request is None:
        return errors
    found = [bool(topology_request.required),
             bool(topology_request.preferred),
             bool(topology_request.unconstrained)]
    if sum(found) > 1:
        errors.append(
            f"{path}: must not contain more than one topology annotation "
            "(required / preferred / unconstrained)")
    for kind, value in (
            ("required", topology_request.required),
            ("preferred", topology_request.preferred),
            ("slice-required",
             getattr(topology_request, "slice_required_topology", None))):
        if value and not _valid_label_name(value):
            errors.append(
                f"{path}.{kind}-topology: {value!r} is not a valid label name")
    slice_size = getattr(topology_request, "slice_size", None)
    if slice_size is not None and slice_size <= 0:
        errors.append(f"{path}.slice-size: must be greater than 0")
    return errors


def _job_errors_create(job: GenericJob) -> list[str]:
    """ValidateJobOnCreate (validation.go:65-71) + TAS podset checks."""
    errors: list[str] = []
    queue = job.queue_name
    if queue and not valid_dns1123_subdomain(queue):
        errors.append(
            f"metadata.labels[kueue.x-k8s.io/queue-name]: {queue!r} "
            "must be a DNS-1123 subdomain")
    max_exec = getattr(job, "maximum_execution_time_seconds", None)
    if max_exec is not None and max_exec <= 0:
        errors.append(
            "metadata.labels[kueue.x-k8s.io/max-exec-time-seconds]: "
            "should be greater than 0")
    for ps in job.pod_sets():
        if ps.count < 0:
            errors.append(f"podSets[{ps.name}].count: must be >= 0")
        errors.extend(validate_tas_podset_request(
            f"podSets[{ps.name}]", ps.topology_request))
    # per-kind hook (KubeflowJob.ValidateOnCreate analog)
    hook = getattr(job, "validate_on_create", None)
    if hook is not None:
        errors.extend(hook())
    return errors


def validate_job_create(job: GenericJob) -> None:
    errors = _job_errors_create(job)
    if errors:
        raise ValidationError(errors)


def validate_job_update(old: GenericJob, new: GenericJob) -> None:
    """ValidateJobOnUpdate (validation.go:73-79): queue name and
    prebuilt workload are immutable while unsuspended; the workload
    priority class is always immutable; max-exec-time is immutable
    unless both versions are suspended."""
    errors = _job_errors_create(new)
    if new.queue_name != old.queue_name:
        # serving kinds freeze the queue on their own condition (e.g.
        # StatefulSet: once pods are Ready, statefulset_webhook.go:140)
        frozen = getattr(new, "queue_name_frozen", None)
        if (frozen(old) if frozen is not None
                else not new.is_suspended()):
            errors.append(
                "metadata.labels[kueue.x-k8s.io/queue-name]: "
                "field is immutable")
    if not new.is_suspended():
        old_pb = getattr(old, "prebuilt_workload", None)
        if getattr(new, "prebuilt_workload", None) != old_pb:
            errors.append(
                f"metadata.labels[{PREBUILT_WORKLOAD_LABEL}]: "
                "field is immutable while the job is not suspended")
    if new.priority_class_name != old.priority_class_name:
        errors.append(
            "metadata.labels[kueue.x-k8s.io/priority-class]: "
            "field is immutable")
    if not (new.is_suspended() and old.is_suspended()):
        new_met = getattr(new, "maximum_execution_time_seconds", None)
        old_met = getattr(old, "maximum_execution_time_seconds", None)
        if new_met != old_met:
            errors.append(
                "metadata.labels[kueue.x-k8s.io/max-exec-time-seconds]: "
                "field is immutable")
    hook = getattr(new, "validate_on_update", None)
    if hook is not None:
        errors.extend(hook(old))
    if errors:
        raise ValidationError(errors)
