"""The generic job reconciler: the job↔workload state machine.

Capability parity with reference
pkg/controller/jobframework/reconciler.go:233 ReconcileGenericJob:

- a managed job must be suspended until its workload is admitted;
- admission injects pod-set info (flavor node selectors, topology,
  admission-check updates) and unsuspends;
- losing quota (eviction/preemption/deactivation) stops the job and
  restores the original pod templates;
- job completion finishes the workload; pod-set equivalence changes
  recreate it (ensureOneWorkload, reconciler.go:642).
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..api.types import Workload
from ..podset import (
    PodSetInfo,
    merge_podset_infos,
    podset_infos_from_admission,
)
from .interface import (
    ComposableJob,
    GenericJob,
    JobWithCustomStop,
    JobWithManagedBy,
    JobWithReclaimablePods,
    StopReason,
    workload_name_for_job,
)

MANAGER_NAME = "kueue-tpu.x-k8s.io/controller"


class JobReconciler:
    """reference jobframework/reconciler.go JobReconciler."""

    def __init__(self, driver, manager_name: str = MANAGER_NAME):
        self.driver = driver
        self.manager_name = manager_name

    # ------------------------------------------------------------------

    def workload_key_for(self, job: GenericJob) -> str:
        return f"{job.namespace}/{workload_name_for_job(job.gvk, job.name)}"

    def reconcile(self, job: GenericJob) -> None:
        driver = self.driver
        wl_key = self.workload_key_for(job)
        wl = driver.workload(wl_key)

        # MultiKueue: a job managed by another controller stays suspended
        # here (reference JobWithManagedBy, interface.go:158)
        if isinstance(job, JobWithManagedBy):
            mb = job.managed_by()
            if mb is not None and mb != self.manager_name:
                return

        message, success, finished = job.finished()
        if finished:
            if wl is not None and not wl.is_finished:
                driver.finish_workload(wl_key, message or "Job finished")
            return

        if not job.queue_name and wl is None:
            return  # not managed (reference manageability checks)

        if wl is None:
            if not job.is_suspended():
                # job started without admission — gate it
                self._stop(job, None, StopReason.NO_MATCHING_WORKLOAD,
                           "No matching Workload; suspending")
                return
            driver.create_workload(self._construct_workload(job))
            return

        if not wl.is_admitted and not self._equivalent(job, wl):
            # pod sets changed under us: recreate (ensureOneWorkload)
            driver.delete_workload(wl_key)
            driver.create_workload(self._construct_workload(job))
            return

        if wl.is_admitted and job.is_suspended():
            self._start(job, wl)
            return

        if not wl.has_quota_reservation and not job.is_suspended():
            self._stop(job, wl, StopReason.NOT_ADMITTED,
                       "Not admitted; suspending")
            return

        if wl.is_admitted and not job.is_suspended():
            # PodsReady condition sync from the running job (reference
            # workload_controller.go PodsReady handling; feeds the
            # WaitForPodsReady blockAdmission gate + timeout countdown)
            driver.set_pods_ready(wl_key, job.pods_ready())

        if isinstance(job, JobWithReclaimablePods) and wl.has_quota_reservation:
            rp = job.reclaimable_pods()
            if rp:
                driver.update_reclaimable_pods(wl_key, rp)

    # ------------------------------------------------------------------

    def _construct_workload(self, job: GenericJob) -> Workload:
        """reference interface.go:209 NewWorkload / ConstructWorkload."""
        if isinstance(job, ComposableJob):
            wl = job.construct_composable_workload()
        else:
            wl = Workload(
                name=workload_name_for_job(job.gvk, job.name),
                namespace=job.namespace,
                queue_name=job.queue_name,
                pod_sets=job.pod_sets())
        pc = job.priority_class_name
        if pc:
            resolved = self.driver.resolve_priority_class(pc)
            if resolved is not None:
                wl.priority = resolved.value
                wl.priority_class_name = resolved.name
                wl.priority_class_source = "kueue.x-k8s.io/workloadpriorityclass"
        if not wl.creation_time:
            wl.creation_time = self.driver.clock()
        return wl

    def _equivalent(self, job: GenericJob, wl: Workload) -> bool:
        """Pod-set equivalence (reference reconciler.go equivalentToWorkload)."""
        job_ps = (job.construct_composable_workload().pod_sets
                  if isinstance(job, ComposableJob) else job.pod_sets())
        if len(job_ps) != len(wl.pod_sets):
            return False
        for a, b in zip(job_ps, wl.pod_sets):
            if (a.name, a.count, dict(a.requests)) != (
                    b.name, b.count, dict(b.requests)):
                return False
        return True

    def _podset_infos(self, wl: Workload) -> list[PodSetInfo]:
        flavors = self.driver.cache.resource_flavors
        infos = podset_infos_from_admission(
            wl.pod_sets, wl.admission.pod_set_assignments, flavors)
        updates = [PodSetInfo.from_update(u)
                   for st in wl.admission_check_states.values()
                   for u in st.pod_set_updates]
        if updates:
            merge_podset_infos(infos, updates)
        return infos

    def _start(self, job: GenericJob, wl: Workload) -> None:
        """reference reconciler.go startJob."""
        job.run_with_podsets_info(self._podset_infos(wl))
        self.driver.events.append(("Started", job.key, wl.key))

    def _stop(self, job: GenericJob, wl: Optional[Workload],
              reason: StopReason, message: str) -> None:
        """reference reconciler.go stopJob."""
        infos: Sequence[PodSetInfo] = ()
        if wl is not None and wl.admission is not None:
            infos = self._podset_infos(wl)
        if isinstance(job, JobWithCustomStop):
            job.stop(infos, reason, message)
        else:
            job.suspend()
            job.restore_podsets_info(infos)
        self.driver.events.append(("Stopped", job.key, reason.value))


class JobManager:
    """Holds live jobs and drives reconciliation rounds against the
    driver (the in-process stand-in for controller-runtime watches)."""

    def __init__(self, driver, manager_name: str = MANAGER_NAME):
        self.driver = driver
        self.reconciler = JobReconciler(driver, manager_name)
        self.jobs: dict[str, GenericJob] = {}

    def upsert(self, job: GenericJob) -> None:
        """Admit a job object through the webhook chain, then reconcile
        (the controller-runtime webhook → watch → reconcile path)."""
        from .webhook import validate_job_create, validate_job_update
        old = self.jobs.get(job.key)
        if old is None or old is job:
            validate_job_create(job)
        else:
            validate_job_update(old, job)
        self.jobs[job.key] = job
        self.reconciler.reconcile(job)

    def delete(self, job_key: str) -> None:
        job = self.jobs.pop(job_key, None)
        if job is not None:
            self.driver.delete_workload(
                self.reconciler.workload_key_for(job))

    def sync(self) -> None:
        for job in list(self.jobs.values()):
            self.reconciler.reconcile(job)

    def run(self, max_rounds: int = 25) -> None:
        """Reconcile + schedule until a fixed point."""
        for _ in range(max_rounds):
            self.sync()
            self.driver.run_until_settled()
            self.sync()
            before = self._fingerprint()
            self.driver.run_until_settled()
            self.sync()
            if self._fingerprint() == before:
                return

    def _fingerprint(self):
        return (tuple(sorted(self.driver.admitted_keys())),
                tuple((k, j.is_suspended(), j.finished()[2])
                      for k, j in sorted(self.jobs.items())))
