"""The job-integration framework (reference pkg/controller/jobframework).

A GenericJob SPI + generic reconciler driving the job↔workload state
machine, and a registry of integrations.  Concrete integrations live in
``kueue_tpu.jobs``.
"""

from .interface import (
    ComposableJob,
    GenericJob,
    IntegrationCallbacks,
    JobWithCustomStop,
    JobWithManagedBy,
    JobWithReclaimablePods,
    StopReason,
    for_each_integration,
    get_integration,
    register_integration,
    workload_name_for_job,
)
from .reconciler import JobManager, JobReconciler

__all__ = [
    "ComposableJob", "GenericJob", "IntegrationCallbacks",
    "JobWithCustomStop", "JobWithManagedBy", "JobWithReclaimablePods",
    "StopReason", "JobManager", "JobReconciler",
    "for_each_integration", "get_integration", "register_integration",
    "workload_name_for_job",
]
