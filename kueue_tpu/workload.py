"""Workload runtime info: computed requests, status transitions, ordering.

Capability parity with reference pkg/workload/workload.go: ``Info`` wraps a
Workload with computed per-PodSet total requests (reclaimable pods,
resource transformations, excluded prefixes — workload.go:163-382), flavor
usage (usage.go), queue-order timestamps (workload.go:723), requeue backoff
(workload.go:514-539), and the status setters the scheduler/controllers use.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

from .api.types import (
    Admission,
    AdmissionCheckState,
    AdmissionCheckStatus,
    Condition,
    ConditionStatus,
    PodSet,
    PodSetAssignment,
    RequeueState,
    Workload,
    EVICTED_BY_ADMISSION_CHECK,
    EVICTED_BY_PODS_READY_TIMEOUT,
    IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
    WL_ADMITTED,
    WL_EVICTED,
    WL_FINISHED,
    WL_PREEMPTED,
    WL_QUOTA_RESERVED,
    WL_REQUEUED,
)
from .resources import FlavorResource, FlavorResourceQuantities, Requests


# ---------------------------------------------------------------------------
# Resource transformations / exclusions (apis/config/v1beta1 Resources)
# ---------------------------------------------------------------------------

@dataclass
class ResourceTransformation:
    input: str
    strategy: str = "Retain"  # Retain | Replace
    outputs: dict[str, int] = field(default_factory=dict)  # per input unit


@dataclass
class InfoOptions:
    excluded_prefixes: list[str] = field(default_factory=list)
    transformations: dict[str, ResourceTransformation] = field(default_factory=dict)


def _apply_transformations(requests: Requests, opts: InfoOptions) -> Requests:
    """Reference workload.go:320 (applyResourceTransformations) +
    dropExcludedResources (workload.go:267)."""
    out = Requests()
    for name, value in requests.items():
        tr = opts.transformations.get(name)
        if tr is not None:
            for oname, per_unit in tr.outputs.items():
                out[oname] = out.get(oname, 0) + value * per_unit
            if tr.strategy == "Retain":
                out[name] = out.get(name, 0) + value
        else:
            out[name] = out.get(name, 0) + value
    for name in list(out):
        if any(name == p or name.startswith(p) for p in opts.excluded_prefixes):
            del out[name]
    return out


# ---------------------------------------------------------------------------
# Info
# ---------------------------------------------------------------------------

@dataclass
class PodSetResources:
    name: str
    requests: Requests           # total for the podset (per-pod × count)
    count: int                   # pods actually counted (after reclaim)
    flavors: dict[str, str] = field(default_factory=dict)  # resource → flavor
    topology_request: object = None

    def scaled_to(self, new_count: int) -> "PodSetResources":
        """Scale requests to a different pod count (partial admission;
        reference workload.go ScaledTo)."""
        if self.count == new_count or self.count == 0:
            return dataclasses.replace(self, count=new_count,
                                       requests=self.requests.clone(),
                                       flavors=dict(self.flavors))
        per_pod = {k: v // self.count for k, v in self.requests.items()}
        return PodSetResources(
            name=self.name,
            requests=Requests({k: v * new_count for k, v in per_pod.items()}),
            count=new_count,
            flavors=dict(self.flavors),
            topology_request=self.topology_request,
        )


class Info:
    """A Workload plus computed TotalRequests (reference workload.go:153)."""

    def __init__(self, wl: Workload, opts: InfoOptions | None = None):
        self.obj = wl
        self.opts = opts or InfoOptions()
        # plain attribute, not a property: info.key is on several hot
        # paths (heap comparators, dict routing) and the workload's key
        # is immutable per Info instance
        self.key: str = wl.key
        self.cluster_queue: str = wl.admission.cluster_queue if wl.admission else ""
        self.total_requests: list[PodSetResources] = self._compute_total_requests()
        # Flavor-assignment resume state (reference workload.go:82
        # AssignmentClusterQueueState) — attached by the scheduler.
        self.last_assignment = None

    # -- requests --

    def _reclaim_count(self, ps_name: str) -> int:
        for rp in self.obj.reclaimable_pods:
            if rp.name == ps_name:
                return rp.count
        return 0

    def _compute_total_requests(self) -> list[PodSetResources]:
        wl = self.obj
        out = []
        if wl.admission is not None:
            assignments = {a.name: a for a in wl.admission.pod_set_assignments}
        else:
            assignments = {}
        for ps in wl.pod_sets:
            asg = assignments.get(ps.name)
            if asg is not None and asg.resource_usage:
                # admitted: the admission's per-PodSet resource usage is
                # authoritative (reference workload.go
                # totalRequestsFromAdmission) — it already carries the
                # implicit "pods" resource for CQs that cover it
                count = asg.count if asg.count else ps.count
                psr = PodSetResources(
                    name=ps.name, requests=Requests(asg.resource_usage),
                    count=count, flavors=dict(asg.flavors),
                    topology_request=ps.topology_request)
                target = max(0, count - self._reclaim_count(ps.name))
                if target != count:
                    psr = psr.scaled_to(target)
                out.append(psr)
                continue
            count = max(0, ps.count - self._reclaim_count(ps.name))
            per_pod = _apply_transformations(Requests(ps.requests), self.opts)
            total = Requests({k: v * count for k, v in per_pod.items()})
            # implicit pods resource (reference workload.go
            # totalRequestsFromPodSets); the flavor assigner drops it for
            # CQs that don't cover "pods"
            total["pods"] = count
            out.append(PodSetResources(
                name=ps.name, requests=total, count=count, flavors={},
                topology_request=ps.topology_request))
        return out

    def usage(self) -> FlavorResourceQuantities:
        """Quota usage by (flavor, resource) (reference usage.go / workload.go:244)."""
        usage = FlavorResourceQuantities()
        for psr in self.total_requests:
            for rname, qty in psr.requests.items():
                flavor = psr.flavors.get(rname, "")
                fr = FlavorResource(flavor, rname)
                usage[fr] = usage.get(fr, 0) + qty
        return usage

    def sum_requests(self) -> Requests:
        total = Requests()
        for psr in self.total_requests:
            total.add(psr.requests)
        return total

    @property
    def priority(self) -> int:
        return self.obj.priority

    def update_from(self, wl: Workload) -> None:
        self.obj = wl
        self.key = wl.key
        self.cluster_queue = wl.admission.cluster_queue if wl.admission else self.cluster_queue
        self.total_requests = self._compute_total_requests()

    def clone(self) -> "Info":
        info = Info(self.obj.clone(), self.opts)
        info.cluster_queue = self.cluster_queue
        info.last_assignment = self.last_assignment
        return info


# ---------------------------------------------------------------------------
# Status transitions (reference workload.go:588-721)
# ---------------------------------------------------------------------------

def set_quota_reservation(wl: Workload, admission: Admission, now: float) -> None:
    """Reference workload.go:588 SetQuotaReservation."""
    wl.admission = admission
    wl.set_condition(WL_QUOTA_RESERVED, ConditionStatus.TRUE,
                     reason="QuotaReserved",
                     message=f"Quota reserved in ClusterQueue {admission.cluster_queue}",
                     now=now)
    # Eviction/preemption history is cleared on fresh reservation.
    for cond_type in (WL_EVICTED, WL_PREEMPTED):
        c = wl.conditions.get(cond_type)
        if c is not None and c.status == ConditionStatus.TRUE:
            wl.set_condition(cond_type, ConditionStatus.FALSE,
                             reason="QuotaReserved", message="Previous eviction cleared",
                             now=now)


def unset_quota_reservation(wl: Workload, reason: str, message: str, now: float) -> None:
    """Reference workload.go:490 UnsetQuotaReservationWithCondition."""
    wl.set_condition(WL_QUOTA_RESERVED, ConditionStatus.FALSE, reason=reason,
                     message=message, now=now)
    wl.admission = None
    sync_admitted_condition(wl, now)


def sync_admitted_condition(wl: Workload, now: float) -> bool:
    """Admitted = QuotaReserved AND all admission checks Ready
    (reference workload.go SyncAdmittedCondition)."""
    reserved = wl.condition_true(WL_QUOTA_RESERVED)
    checks_ready = all(
        st.state == AdmissionCheckState.READY
        for st in wl.admission_check_states.values())
    admitted = reserved and checks_ready
    was = wl.is_admitted
    if admitted and not was:
        wl.set_condition(WL_ADMITTED, ConditionStatus.TRUE, reason="Admitted",
                         message="The workload is admitted", now=now)
    elif not admitted and was:
        reason = "NoReservation" if not reserved else "UnsatisfiedChecks"
        wl.set_condition(WL_ADMITTED, ConditionStatus.FALSE, reason=reason, now=now)
    return admitted != was


def set_pods_ready_condition(wl: Workload, ready: bool, now: float) -> bool:
    """PodsReady condition sync (reference workload_controller.go
    syncs it from the job's PodsReady()).  Returns True on transition."""
    from .api.types import WL_PODS_READY
    was = wl.condition_true(WL_PODS_READY)
    if ready == was and WL_PODS_READY in wl.conditions:
        return False
    wl.set_condition(WL_PODS_READY,
                     ConditionStatus.TRUE if ready else ConditionStatus.FALSE,
                     reason="PodsReady" if ready else "PodsNotReady",
                     message=("All pods were ready or succeeded" if ready
                              else "Not all pods are ready or succeeded"),
                     now=now)
    return ready != was


def set_evicted_condition(wl: Workload, reason: str, message: str, now: float) -> None:
    """Reference workload.go:637 SetEvictedCondition."""
    wl.set_condition(WL_EVICTED, ConditionStatus.TRUE, reason=reason,
                     message=message, now=now)
    key = reason
    wl.scheduling_stats_evictions[key] = wl.scheduling_stats_evictions.get(key, 0) + 1


def set_preempted_condition(wl: Workload, reason: str, message: str, now: float) -> None:
    wl.set_condition(WL_PREEMPTED, ConditionStatus.TRUE, reason=reason,
                     message=message, now=now)


def set_requeued_condition(wl: Workload, reason: str, message: str,
                           status: bool, now: float) -> None:
    wl.set_condition(WL_REQUEUED,
                     ConditionStatus.TRUE if status else ConditionStatus.FALSE,
                     reason=reason, message=message, now=now)


def set_finished_condition(wl: Workload, reason: str, message: str, now: float) -> None:
    wl.set_condition(WL_FINISHED, ConditionStatus.TRUE, reason=reason,
                     message=message, now=now)


def _jitter_fraction(key: str, count: int) -> float:
    """Deterministic per-(workload, attempt) fraction in [0, 1] — stable
    across processes (hash() is salted; crc32 is not) so journal replay
    and A/B parity runs compute identical backoff deadlines."""
    import zlib
    return zlib.crc32(f"{key}/{count}".encode()) / 0xFFFFFFFF


def next_requeue_state(wl: Workload, backoff_base_seconds: int,
                       backoff_max_seconds: int, now: float,
                       jitter: float = 0.0) -> tuple[int, float]:
    """The ``(count, requeue_at)`` that ``update_requeue_state`` would
    apply, computed without mutating the workload — so the WAL can
    journal the decision before the store write (the journal-append-
    dominates-mutation discipline that ``analysis/wal_order.py``
    enforces over the driver)."""
    count = (0 if wl.requeue_state is None else wl.requeue_state.count) + 1
    if backoff_base_seconds <= 0:
        wait_s = 0
    elif count - 1 >= (backoff_max_seconds // backoff_base_seconds).bit_length():
        wait_s = backoff_max_seconds
    else:
        wait_s = min(backoff_base_seconds * (2 ** (count - 1)),
                     backoff_max_seconds)
    if jitter:
        wait_s += wait_s * jitter * _jitter_fraction(wl.key, count)
    return count, now + wait_s


def update_requeue_state(wl: Workload, backoff_base_seconds: int,
                         backoff_max_seconds: int, now: float,
                         jitter: float = 0.0) -> None:
    """Exponential requeue backoff: base·2^(n−1) capped at max
    (reference workload.go:514 UpdateRequeueState).

    The exponent is clamped before the power is taken: a workload
    evicted thousands of times must not materialize a thousand-bit
    integer just for ``min`` to discard it.  ``jitter`` > 0 stretches
    each deadline by a per-workload fraction of up to that much, so a
    cohort evicted en masse fans back in instead of requeuing in
    lockstep — deterministic, so parity arms agree."""
    count, requeue_at = next_requeue_state(
        wl, backoff_base_seconds, backoff_max_seconds, now, jitter)
    if wl.requeue_state is None:
        wl.requeue_state = RequeueState()
    wl.requeue_state.requeue_at = requeue_at
    wl.requeue_state.count = count


# ---------------------------------------------------------------------------
# Queue ordering (reference workload.go:723-769)
# ---------------------------------------------------------------------------

@dataclass
class Ordering:
    """Timestamp policy for queue ordering (reference workload.go:723)."""
    pods_ready_requeuing_timestamp: str = "Eviction"  # Eviction | Creation
    priority_sorting_within_cohort: bool = True       # feature gate

    def queue_order_timestamp(self, wl: Workload) -> float:
        evicted = wl.conditions.get(WL_EVICTED)
        if (self.pods_ready_requeuing_timestamp == "Eviction"
                and evicted is not None and evicted.status == ConditionStatus.TRUE
                and evicted.reason == EVICTED_BY_PODS_READY_TIMEOUT):
            return evicted.last_transition_time
        if (evicted is not None and evicted.status == ConditionStatus.TRUE
                and evicted.reason == EVICTED_BY_ADMISSION_CHECK):
            return evicted.last_transition_time
        if not self.priority_sorting_within_cohort:
            preempted = wl.conditions.get(WL_PREEMPTED)
            if (preempted is not None and preempted.status == ConditionStatus.TRUE
                    and preempted.reason == IN_COHORT_RECLAIM_WHILE_BORROWING_REASON):
                return preempted.last_transition_time + 0.001
        return wl.creation_time


def queued_wait_time(wl: Workload, now: float) -> float:
    """Reference workload.go QueuedWaitTime."""
    queued = wl.creation_time
    c = wl.conditions.get(WL_REQUEUED)
    if c is not None:
        queued = c.last_transition_time
    return now - queued


def admission_status_patch(wl: Workload) -> dict:
    """SSA-shaped decision record the driver emits (reference
    ApplyAdmissionStatus, workload.go:711). Pure data: applied by the store."""
    return {
        "key": wl.key,
        "admission": wl.admission,
        "conditions": dict(wl.conditions),
        "requeue_state": wl.requeue_state,
        "admission_check_states": dict(wl.admission_check_states),
    }
