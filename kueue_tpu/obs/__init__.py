"""Live telemetry plane: span tracing, flight recorder, event stream.

The operator-facing observability layer the reference ships as
``pkg/metrics`` + ``pkg/visibility`` + ``pkg/debugger`` + Events,
reproduced for the solver stack:

- :mod:`trace`  — structured span tracer over the admission hot path
  (schedule phases, burst pack/dispatch/fetch/apply, WAL, federation
  sync), off by default and zero-allocation when off;
- :mod:`flight` — ring-buffer flight recorder of the last N cycles
  (decision digests, spans, chaos hits), dumpable on demand, over
  HTTP, and on SIGUSR2;
- :mod:`events` — bounded subscribable admit/evict/preempt/requeue/
  eject stream feeding the recorder and every soak artifact's ``obs``
  block.

:class:`ObsPlane` is the per-driver composition: the driver owns one,
emits events through it, records each applied cycle into it, and the
telemetry endpoints (``visibility.VisibilityServer``) and the SIGUSR2
dumper (``debugger``) read from it.  Guarantees, test-enforced:
decisions are bit-identical with tracing on vs off, and the traced
north-star p50 stays within 5% of untraced (OBS artifact).
"""

from __future__ import annotations

from typing import Optional

from . import events as _events
from . import flight as _flight
from . import trace as _trace
from .events import Event, EventStream            # noqa: F401
from .flight import CycleRecord, FlightRecorder   # noqa: F401
from .trace import (                               # noqa: F401
    HOT_PATH_PHASES,
    SPAN_BUCKETS,
    SpanRecord,
    Tracer,
    span,
    to_chrome_trace,
)


class ObsPlane:
    """One driver's observability state: event stream + flight recorder
    + (optional) tracing enablement.  Always attached — emitting an
    event or recording a cycle is a deque append and never reads state
    the scheduler writes mid-cycle — while tracing stays opt-in."""

    def __init__(self, driver, flight_cycles: int = 256,
                 event_capacity: int = 4096):
        self.driver = driver
        self.events = EventStream(capacity=event_capacity)
        self.flight = FlightRecorder(capacity=flight_cycles)
        self.tracer: Optional[Tracer] = None   # last tracer enabled here
        self._last_recorded = None   # identity of the last CycleStats

    @classmethod
    def from_env(cls, driver) -> "ObsPlane":
        from ..features import env_int, env_value
        plane = cls(driver,
                    flight_cycles=env_int("KUEUE_TPU_FLIGHT_CYCLES"),
                    event_capacity=env_int("KUEUE_TPU_OBS_EVENTS"))
        if env_value("KUEUE_TPU_OBS_TRACE") not in ("", "0"):
            plane.enable_tracing()
        return plane

    # -- tracing lifecycle ---------------------------------------------

    def enable_tracing(self) -> Tracer:
        """Install the process tracer bound to this driver's registry
        and (virtual) clock.  Idempotent per driver."""
        t = _trace.ACTIVE
        if t is None or t.registry is not self.driver.metrics:
            t = _trace.install(Tracer(registry=self.driver.metrics,
                                      vclock=self.driver.clock))
        self.tracer = t
        return t

    def disable_tracing(self) -> None:
        _trace.clear()

    @property
    def tracing(self) -> bool:
        return _trace.ACTIVE is not None

    # -- emission ------------------------------------------------------

    def emit(self, kind: str, key: str, cluster_queue: str = "",
             reason: str = "", note: str = "") -> Event:
        d = self.driver
        return self.events.emit(
            kind, key, cluster_queue=cluster_queue, reason=reason,
            note=note, cycle=d.scheduler.scheduling_cycle,
            vt=d.clock())

    def record_cycle(self, stats) -> None:
        """Record one applied cycle into the flight recorder.  Deduped
        by stats identity: the burst path funnels both normal and
        modeled cycles through ``finish_cycle`` while the normal path
        records inside ``schedule_once`` — the same batch must land in
        the ring exactly once."""
        if stats is self._last_recorded:
            return
        self._last_recorded = stats
        t = _trace.ACTIVE
        spans = t.drain_cycle() if t is not None else ()
        self.flight.record(stats, vt=self.driver.clock(), spans=spans,
                           events_total=self.events.total)

    # -- reporting -----------------------------------------------------

    def _tracer_view(self) -> Optional[Tracer]:
        """The tracer whose data belongs to this driver: the installed
        one when it is ours, else the last one enabled here — so the
        endpoints keep serving spans after a harness uninstalls the
        process-global between cycles."""
        t = _trace.ACTIVE
        if t is not None and t.registry is self.driver.metrics:
            return t
        return self.tracer

    def spans_chrome_trace(self) -> dict:
        t = self._tracer_view()
        return to_chrome_trace(t.trace_spans if t is not None else ())

    def report(self) -> dict:
        """The ``obs`` block every soak artifact carries from r16 on."""
        out = {
            "events": self.events.report(),
            "flight": {
                "capacity": self.flight.capacity,
                "recorded_total": self.flight.recorded_total,
                "buffered": len(self.flight.ring),
                "dumps": self.flight.dumps,
            },
            "tracing": self.tracing,
        }
        t = self._tracer_view()
        if t is not None:
            out["spans"] = t.roster()
        return out
