"""Bounded, subscribable event stream: the Kubernetes Events analog.

The reference emits Events (``record.EventRecorder``) for every
workload transition; here :class:`EventStream` is the in-process
equivalent: the driver pushes one :class:`Event` per admit / evict /
preempt / requeue / eject, with the reason and the object refs, into a
bounded ring.  Consumers either subscribe (the flight recorder does)
or read the tail (``/debug/flightrecorder``, soak artifacts).

The stream is deliberately decision-free: pushing an event reads no
clock and mutates nothing outside the ring, so an attached stream can
never perturb scheduling.  Overflow drops the *oldest* event and
counts the drop — the per-kind totals keep counting regardless, so
artifact counts stay exact even past capacity.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

#: Every kind the driver/federation layer emits.
EVENT_KINDS = ("admit", "evict", "preempt", "requeue", "eject")


@dataclass
class Event:
    kind: str           # one of EVENT_KINDS
    key: str            # workload key ("ns/name")
    cluster_queue: str  # CQ involved ("" when unknown)
    reason: str         # reason string (eviction reason, check name, …)
    note: str = ""      # free-form detail
    cycle: int = 0      # scheduling cycle at emission (0 = outside one)
    vt: float = 0.0     # virtual-clock reading at emission


class EventStream:
    """Bounded ring of :class:`Event` + per-kind running totals."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, int(capacity))
        self.ring: deque[Event] = deque(maxlen=self.capacity)
        self.counts: dict[str, int] = {k: 0 for k in EVENT_KINDS}
        self.dropped = 0
        self.total = 0
        self._subscribers: list[Callable[[Event], None]] = []

    def emit(self, kind: str, key: str, cluster_queue: str = "",
             reason: str = "", note: str = "", cycle: int = 0,
             vt: float = 0.0) -> Event:
        ev = Event(kind=kind, key=key, cluster_queue=cluster_queue,
                   reason=reason, note=note, cycle=cycle, vt=vt)
        if len(self.ring) == self.capacity:
            self.dropped += 1
        self.ring.append(ev)
        self.counts[kind] = self.counts.get(kind, 0) + 1
        self.total += 1
        for fn in self._subscribers:
            fn(ev)
        return ev

    def subscribe(self, fn: Callable[[Event], None]) -> None:
        self._subscribers.append(fn)

    def tail(self, n: Optional[int] = None) -> list[Event]:
        evs = list(self.ring)
        return evs if n is None else evs[-n:]

    def report(self) -> dict:
        """The ``events`` block for artifacts and dumps."""
        return {
            "counts": {k: v for k, v in sorted(self.counts.items()) if v},
            "total": self.total,
            "dropped": self.dropped,
            "capacity": self.capacity,
            "buffered": len(self.ring),
        }
