"""Cycle flight recorder: the last N cycles, dumpable at any instant.

The ``pkg/debugger`` analog, upgraded from "print the queue heads" to a
bounded ring of :class:`CycleRecord` — one per applied scheduling cycle,
carrying the cycle's decision digest (what was admitted / preempted /
evicted, hashed and listed), the spans the tracer finished during the
cycle, the chaos hit counters, and both clocks.  Every debugging war
story so far was reconstructed after the fact from artifacts; the
recorder makes the same reconstruction available live, mid-soak, from
``/debug/flightrecorder`` or ``kill -USR2``.

Dump discipline: ``dump()`` renders from a shallow snapshot of the ring
taken up front, and the ``obs.dump`` chaos crashpoint sits *after* the
snapshot but *before* serialization — a crash mid-dump can therefore
never leave the recorder half-mutated (recording appends are the only
writes, and dump never writes).  The chaos suite proves a re-dump after
an injected mid-dump crash is identical to an undisturbed dump.
"""

from __future__ import annotations

import hashlib
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ..chaos import injector as _chaos


def decision_digest(stats) -> str:
    """Stable short hash of one cycle's decision batch (CycleStats)."""
    h = hashlib.sha256()
    for part in (stats.admitted, stats.preempting, stats.skipped,
                 stats.inadmissible, stats.preempted_targets):
        h.update(("|".join(part) + ";").encode())
    return h.hexdigest()[:16]


@dataclass
class CycleRecord:
    cycle: int                    # scheduler.scheduling_cycle
    digest: str                   # decision_digest(stats)
    admitted: list[str]
    preempting: list[str]
    evicted: list[str]            # preempted targets this cycle
    duration_s: float
    vt: float                     # virtual clock at record time
    spans: list = field(default_factory=list)        # SpanRecord list
    chaos_hits: dict = field(default_factory=dict)   # site -> hit count
    events: int = 0               # event-stream total at record time


class FlightRecorder:
    """Bounded ring of the last ``capacity`` cycle records."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self.ring: deque[CycleRecord] = deque(maxlen=self.capacity)
        self.recorded_total = 0
        self.dumps = 0

    def record(self, stats, vt: float = 0.0, spans=None,
               events_total: int = 0) -> CycleRecord:
        """Append one applied cycle.  ``spans`` is the tracer's drained
        cycle buffer (empty when tracing is off)."""
        chaos_hits = (dict(_chaos.ACTIVE.counts)
                      if _chaos.ACTIVE is not None else {})
        rec = CycleRecord(
            cycle=stats.cycle,
            digest=decision_digest(stats),
            admitted=list(stats.admitted),
            preempting=list(stats.preempting),
            evicted=list(stats.preempted_targets),
            duration_s=stats.duration_s,
            vt=vt,
            spans=list(spans or ()),
            chaos_hits=chaos_hits,
            events=events_total)
        self.ring.append(rec)
        self.recorded_total += 1
        return rec

    def last(self) -> Optional[CycleRecord]:
        return self.ring[-1] if self.ring else None

    def dump(self, tail: Optional[int] = None) -> dict:
        """Serialize the ring (newest last).  Reads a snapshot first;
        the ``obs.dump`` crashpoint then models a crash mid-dump —
        after the snapshot, before serialization — so the chaos suite
        can prove dumping never corrupts the recorder."""
        snapshot = list(self.ring)
        if tail is not None:
            snapshot = snapshot[-tail:]
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("obs.dump")
        self.dumps += 1
        return {
            "capacity": self.capacity,
            "recorded_total": self.recorded_total,
            "buffered": len(snapshot),
            "cycles": [{
                "cycle": r.cycle,
                "digest": r.digest,
                "admitted": r.admitted,
                "preempting": r.preempting,
                "evicted": r.evicted,
                "duration_s": r.duration_s,
                "virtual_time": r.vt,
                "events_total": r.events,
                "chaos_hits": r.chaos_hits,
                "spans": [{"name": s.name, "dur_s": s.dur,
                           "depth": s.depth, "parent": s.parent}
                          for s in r.spans],
            } for r in snapshot],
        }
