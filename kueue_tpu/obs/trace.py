"""Structured span tracer for the admission hot path.

The tracer follows the chaos-injector pattern: a module-global
``ACTIVE`` that every instrumented site consults.  Tracing off
(``ACTIVE is None``) costs one module-attribute read and a ``with`` on
a shared no-op singleton — no allocation, no clock read, no branch into
tracer code.  Tracing on, each ``span(name)``:

- reads the wall clock (``time.perf_counter``) at entry and exit,
- reads the *virtual* clock (the driver's ``clock``) once at entry when
  one is attached — a pure read, never a tick, so traced and untraced
  runs make bit-identical decisions,
- feeds the duration into the registry's per-phase exponential-bucket
  histogram (``kueue_span_duration_seconds{phase=...}``),
- appends a finished-span record to the current cycle buffer, which the
  flight recorder drains at each cycle boundary (``counted=True``
  leaves skip the record and keep histogram-only timing — see
  :func:`span`).

Spans nest via an explicit stack; ``Span.__exit__`` enforces LIFO
pairing (a span may close exactly once, and only when it is the
innermost open span), so malformed instrumentation fails loudly in
tests instead of producing silently garbled traces.

``to_chrome_trace`` renders finished spans as Chrome trace-event JSON
(``ph: "X"`` complete events, microsecond timestamps) so ``/debug/spans``
output opens in Perfetto next to ``jax.profiler`` traces from
``profiling.py``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..metrics import Histogram, Registry, exponential_buckets

#: Exponential buckets for per-phase span durations: 1µs .. ~4s.
SPAN_BUCKETS = exponential_buckets(1e-6, 2, 22)

#: Every phase the hot path is instrumented with, in call order.  The
#: OBS artifact's span roster and the SIGUSR2 dump are checked against
#: this list; adding an instrumentation site means adding its name here.
HOT_PATH_PHASES = (
    "cycle",            # one whole scheduling cycle (schedule_once path)
    "cycle.snapshot",   # cache snapshot build / incremental reuse
    "cycle.nominate",   # validation + flavor assignment + preempt targets
    "cycle.order",      # classical sort or fair-sharing tournament setup
    "cycle.admit",      # sequential admit loop (assume/apply/requeue)
    "burst.pack",       # burst-window pack (streaming or classic delta)
    "burst.dispatch",   # fused-kernel launch incl. sharded shard launches
    "burst.fetch",      # decision-plane fetch (flags + full planes)
    "burst.apply",      # host apply of one modeled burst cycle
    "wal.append",       # one journal op append
    "wal.commit",       # cycle-boundary commit (group commit included)
    "wal.compact",      # checkpoint + tail rewrite
    "fed.sync",         # one federation reconcile/sync step
    "svc.cycle",        # one whole service step (drain + K inner cycles)
    "svc.ingest",       # cycle-boundary drain of the service ingest queue
    "svc.shutdown",     # graceful-drain epilogue (final WAL/journal flush)
)


@dataclass(slots=True)
class SpanRecord:
    """One finished span."""
    name: str
    t0: float           # wall clock at entry (perf_counter seconds)
    dur: float          # wall-clock duration, seconds
    depth: int          # nesting depth at entry (0 = top level)
    parent: str         # name of the enclosing span ("" at top level)
    vt: float           # virtual-clock reading at entry (0.0 if none)


class Span:
    """A single open span; re-usable only after it closed.

    The tracer pools one instance per nesting depth — LIFO pairing
    means the slot for the current depth is always closed when
    ``span()`` hands it out again, so the steady-state hot path
    allocates no span objects at all."""

    __slots__ = ("tracer", "name", "t0", "depth", "parent", "vt",
                 "_open")

    def __init__(self, tracer: "Tracer", name: str = ""):
        self.tracer = tracer
        self.name = name
        self._open = False

    def __enter__(self) -> "Span":
        if self._open:
            raise RuntimeError(f"span {self.name!r} entered twice")
        st = self.tracer._stack
        self.depth = len(st)
        self.parent = st[-1].name if st else ""
        self.vt = self.tracer.vclock() if self.tracer.vclock else 0.0
        st.append(self)
        self._open = True
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        st = self.tracer._stack
        if not self._open or not st or st[-1] is not self:
            raise RuntimeError(
                f"span {self.name!r} closed out of order "
                f"(stack: {[s.name for s in st]})")
        dur = time.perf_counter() - self.t0
        st.pop()
        self._open = False
        self.tracer._finish(self, dur)
        return False            # never swallow the exception


class _CountedSpan:
    """Histogram-only leaf span: times every entry into the phase
    histogram but skips the stack, parent/depth bookkeeping, the
    virtual-clock read, and the retained record.  By contract counted
    spans are leaves and must not nest inside one another (each tracer
    reuses a single instance per depth-free site)."""

    __slots__ = ("tracer", "name", "t0")

    def __init__(self, tracer: "Tracer"):
        self.tracer = tracer
        self.name = ""

    def __enter__(self) -> "_CountedSpan":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self.t0
        tr = self.tracer
        tr.finished_total += 1
        h = tr._hists.get(self.name)
        if h is None:
            h = tr._hist_for(self.name)
        h.observe(dur)
        return False            # never swallow the exception


class _NoopSpan:
    """Shared do-nothing span: what ``span(...)`` hands out when
    tracing is off.  A single module-level instance — entering it
    allocates nothing and touches no clock."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP = _NoopSpan()


class Tracer:
    """Collects spans into per-phase histograms + a per-cycle buffer.

    ``registry`` receives the ``kueue_span_duration_seconds`` series;
    ``vclock`` is an optional side-effect-free callable returning the
    scenario's virtual time (the driver's ``clock``).  The tracer keeps
    every finished span of the *current* cycle in ``cycle_spans`` until
    the flight recorder drains it; total counts survive draining."""

    def __init__(self, registry: Optional[Registry] = None,
                 vclock: Optional[Callable[[], float]] = None):
        self.registry = registry if registry is not None else Registry()
        self.vclock = vclock
        # the tracer's span stack belongs to the thread that built it
        # (the driver coordinator); spans opened from host-pool worker
        # threads are handed the no-op — their work is timed inside
        # the coordinator's enclosing span, and a shared LIFO stack
        # cannot absorb concurrent closes
        self._owner = threading.get_ident()
        self._stack: list[Span] = []
        self._pool: list[Span] = []      # one reusable span per depth
        self._counted = _CountedSpan(self)   # shared histogram-only leaf
        self._hists: dict[str, Histogram] = {}   # phase -> registry hist
        self.cycle_spans: list[SpanRecord] = []
        self.finished_total = 0
        self.opened_total = 0
        # retained finished spans for /debug/spans (bounded)
        self.trace_spans: list[SpanRecord] = []
        self.trace_capacity = 4096

    def span(self, name: str, counted: bool = False):
        self.opened_total += 1
        if counted:
            s = self._counted
            s.name = name
            return s
        pool = self._pool
        d = len(self._stack)
        if d >= len(pool):
            pool.append(Span(self))
        s = pool[d]
        if s._open:     # a held handle mid-misuse: never rename it
            s = Span(self)
        s.name = name
        return s

    def _hist_for(self, name: str) -> Histogram:
        # same series/key shape Registry.observe would create, the
        # dict probes amortised away from the per-span path; the
        # first-insert holds the registry lock so a concurrent
        # /metrics render never sees the dict resize mid-iteration
        key = ("kueue_span_duration_seconds", name)
        with self.registry._lock:
            h = self.registry.histograms.get(key)
            if h is None:
                h = Histogram(buckets=SPAN_BUCKETS)
                self.registry.histograms[key] = h
        self._hists[name] = h
        return h

    def _finish(self, s: Span, dur: float) -> None:
        self.finished_total += 1
        rec = SpanRecord(s.name, s.t0, dur, s.depth, s.parent, s.vt)
        self.cycle_spans.append(rec)
        if len(self.trace_spans) < self.trace_capacity:
            self.trace_spans.append(rec)
        h = self._hists.get(s.name)
        if h is None:
            h = self._hist_for(s.name)
        h.observe(dur)

    def drain_cycle(self) -> list[SpanRecord]:
        out, self.cycle_spans = self.cycle_spans, []
        return out

    def open_spans(self) -> list[str]:
        return [s.name for s in self._stack]

    # -- reporting -----------------------------------------------------

    def roster(self) -> dict[str, dict]:
        """Per-phase count/p50/p99 from the registry histograms, for
        artifacts and the flight-recorder dump."""
        out: dict[str, dict] = {}
        for key, h in sorted(self.registry.histograms.items()):
            if key[0] != "kueue_span_duration_seconds":
                continue
            phase = key[1]
            out[phase] = {
                "count": h.n,
                "p50_ms": h.quantile(0.5) * 1000.0,
                "p99_ms": h.quantile(0.99) * 1000.0,
                "total_s": h.total,
            }
        return out


#: The process-wide tracer every span site consults.  None = off.
ACTIVE: Optional[Tracer] = None


def install(tracer: Optional[Tracer]) -> Optional[Tracer]:
    global ACTIVE
    ACTIVE = tracer
    return tracer


def clear() -> None:
    install(None)


def span(name: str, counted: bool = False):
    """The one instrumentation entry point: a context manager that is
    a real span when tracing is on and the shared no-op otherwise.

    ``counted=True`` marks an ultra-hot leaf (per-op WAL appends: the
    operation itself is ~2µs, so a retained record would out-cost it):
    every entry is still timed into the phase histogram — roster
    counts and percentiles stay exact — but no SpanRecord lands in the
    cycle buffer or the Chrome trace.

    Calls from a thread other than the tracer's owner (host-pool
    workers fanning WAL segment commits or pack-walk partitions) get
    the no-op: the shared LIFO span stack is single-threaded by
    design, and pooled work is already timed by the coordinator's
    enclosing span."""
    t = ACTIVE
    if t is None or threading.get_ident() != t._owner:
        return _NOOP
    return t.span(name, counted)


def to_chrome_trace(spans) -> dict:
    """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto
    format): one complete ("X") event per finished span, microsecond
    wall-clock timestamps, virtual time and depth in ``args``."""
    events = []
    for s in spans:
        events.append({
            "name": s.name,
            "ph": "X",
            "ts": s.t0 * 1e6,
            "dur": s.dur * 1e6,
            "pid": 1,
            "tid": 1,
            "args": {"virtual_time": s.vt, "depth": s.depth,
                     "parent": s.parent},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
