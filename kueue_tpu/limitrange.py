"""LimitRange support (reference pkg/util/limitrange).

Namespace LimitRanges contribute container defaults and min/max bounds;
``Summary.total_bounds`` validates a workload's per-pod requests the way
the reference's scheduler nominate step does (scheduler.go:336
validateResources via limitrange.Summarize)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class LimitRangeItem:
    type: str = "Container"          # Container | Pod
    default: dict[str, int] = field(default_factory=dict)
    min: dict[str, int] = field(default_factory=dict)
    max: dict[str, int] = field(default_factory=dict)


@dataclass
class LimitRange:
    name: str
    namespace: str = "default"
    items: list[LimitRangeItem] = field(default_factory=list)


@dataclass
class Summary:
    """Combined per-pod bounds.  Pod sets here are single-container
    (requests are per pod), so Container- and Pod-type items both bound
    the same per-pod requests; defaults are honored only from
    Container-type items (the reference forbids Pod-type defaults)."""
    default: dict[str, int] = field(default_factory=dict)
    min: dict[str, int] = field(default_factory=dict)    # per pod
    max: dict[str, int] = field(default_factory=dict)


def summarize(ranges: list[LimitRange]) -> Summary:
    s = Summary()
    for lr in ranges:
        for item in lr.items:
            if item.type not in ("Container", "Pod"):
                continue
            if item.type == "Container":
                for r, v in item.default.items():
                    s.default.setdefault(r, v)
            for r, v in item.min.items():
                # the tightest (largest) min wins
                s.min[r] = max(s.min.get(r, v), v)
            for r, v in item.max.items():
                s.max[r] = min(s.max.get(r, v), v)
    return s


def apply_defaults(requests: dict[str, int], summary: Summary) -> dict[str, int]:
    """Fill unset resources from LimitRange defaults (reference
    jobframework AdjustResources path)."""
    out = dict(requests)
    for r, v in summary.default.items():
        out.setdefault(r, v)
    return out


def validate(requests: dict[str, int], summary: Summary) -> list[str]:
    """Per-pod request bounds (reference limitrange.ValidatePodSpec)."""
    errors = []
    for r, lo in summary.min.items():
        if r in requests and requests[r] < lo:
            errors.append(
                f"request {r}={requests[r]} below LimitRange min {lo}")
    for r, hi in summary.max.items():
        if r in requests and requests[r] > hi:
            errors.append(
                f"request {r}={requests[r]} above LimitRange max {hi}")
    return errors
