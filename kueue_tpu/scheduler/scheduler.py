"""The admission loop: one scheduling cycle over (heads, snapshot).

Capability parity with reference pkg/scheduler/scheduler.go:176 schedule():
① pop queue heads ② snapshot the cache ③ nominate (validate + flavor
assignment + preemption targets, :336) ④ order entries — classical sort
(:567) or fair-sharing tournament (fair_sharing_iterator.go) ⑤ sequential
admit loop with within-cycle usage mutation, capacity reservation for
preempt-with-no-targets (:383), overlapping-preemption skips, fits re-check
⑥ requeue the rest.

The cycle is a pure function of (snapshot, heads) plus the assume/apply
side effects — exactly the boundary the batched TPU solver
(kueue_tpu.ops.cycle) reproduces.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import (
    Admission,
    AdmissionCheckState,
    AdmissionCheckStatus,
    Workload,
)
from ..cache.cache import Cache
from ..cache.snapshot import Snapshot
from ..cache.state import CQState, dominant_resource_share
from ..queue.cluster_queue import RequeueReason
from ..queue.manager import Manager as QueueManager
from ..resources import FlavorResourceQuantities
from ..workload import (
    Info,
    Ordering,
    set_quota_reservation,
    sync_admitted_condition,
)
from .flavorassigner import (
    Assignment,
    FlavorAssigner,
    Mode,
    PodSetReducer,
)
from .preemption import Preemptor, PreemptionOracle, Target


class EntryStatus:
    NOT_NOMINATED = ""
    NOMINATED = "nominated"
    SKIPPED = "skipped"
    ASSUMED = "assumed"


@dataclass
class Entry:
    """reference scheduler.go:318 entry."""
    info: Info
    assignment: Assignment = field(default_factory=Assignment)
    status: str = EntryStatus.NOT_NOMINATED
    inadmissible_msg: str = ""
    requeue_reason: RequeueReason = RequeueReason.GENERIC
    preemption_targets: list[Target] = field(default_factory=list)
    cq_snapshot: Optional[CQState] = None
    prepped: Optional[tuple] = None   # (new_wl, new_info) built pre-assume

    @property
    def obj(self) -> Workload:
        return self.info.obj


@dataclass
class CycleStats:
    cycle: int = 0
    admitted: list[str] = field(default_factory=list)
    preempting: list[str] = field(default_factory=list)
    skipped: list[str] = field(default_factory=list)
    inadmissible: list[str] = field(default_factory=list)
    preempted_targets: list[str] = field(default_factory=list)
    duration_s: float = 0.0
    finish_s: float = 0.0     # workload-finish application (burst mode)


class Scheduler:
    """reference scheduler.go:64."""

    def __init__(self, queues: QueueManager, cache: Cache,
                 fair_sharing: bool = False,
                 fs_preemption_strategies: list[str] | None = None,
                 ordering: Ordering | None = None,
                 clock: Callable[[], float] = time.time,
                 namespaces: Optional[dict[str, dict[str, str]]] = None,
                 solver: Optional[object] = None):
        self.queues = queues
        self.cache = cache
        self.fair_sharing = fair_sharing
        self.ordering = ordering or Ordering()
        self.clock = clock
        self.namespaces = namespaces  # namespace -> labels (None: match all)
        self.preemptor = Preemptor(
            enable_fair_sharing=fair_sharing,
            fs_strategies=fs_preemption_strategies,
            ordering=self.ordering, clock=clock)
        self.scheduling_cycle = 0
        # Hook applied after assume; returns True on success (reference
        # applyAdmission / admissionRoutineWrapper, scheduler.go:80,156).
        self.apply_admission: Callable[[Workload], bool] = lambda wl: True
        # Decision-record sink for requeue/update patches.
        self.on_requeue: Callable[[Entry], None] = lambda e: None
        # Optional batched device solver (kueue_tpu.ops.solver.CycleSolver).
        self.solver = solver
        # Fair-sharing tournament backend: batched TournamentDRS (default)
        # vs the scalar per-entry computeDRS (parity oracle).
        self.fs_batched = True
        self._fs_tracker = None
        # visibility for the batched-tournament fallback (a production
        # FS workload silently running the O(entries²) scalar oracle was
        # round-3 weak #8): counts cycles where the tracker couldn't
        # represent an entry and rounds that used the scalar path
        self.fs_stats = {"tracker_unavailable_cycles": 0,
                         "scalar_drs_rounds": 0}
        # fingerprinted reuse of the last no-op FS cycle's per-head
        # host walks (VERDICT r5: an FS cycle that admits nothing still
        # paid ~1.5 s of _assign_entry walks at north-star scale)
        self._fs_noop_cache = None
        # WaitForPodsReady blockAdmission gate (reference scheduler.go
        # :268-279): True → hold admissions this cycle.  Evaluated once
        # at cycle start; held entries requeue with the waiting message
        # and the PodsReady transition wakes them (instead of the
        # reference's in-cycle cond wait).
        self.admission_blocked: Callable[[], bool] = lambda: False
        self._cycle_blocked = False
        # True while entries the gate held are parked somewhere —
        # gate-opening events only need to wake when this is set
        self.gate_parked = False
        # Optional metrics registry (set by the driver).
        self.metrics = None
        # Namespace → limitrange.Summary (set by the driver).
        self.limit_range_summaries: dict[str, object] = {}

    # ------------------------------------------------------------------
    # One cycle — reference scheduler.go:176
    # ------------------------------------------------------------------

    def schedule(self, heads: Optional[list[Info]] = None) -> CycleStats:
        self.scheduling_cycle += 1
        stats = CycleStats(cycle=self.scheduling_cycle)
        start = self.clock()

        if heads is None:
            heads = self.queues.heads_nonblocking()
        if not heads:
            return stats
        from ..obs.trace import span as _span
        from ..profiling import cycle_step
        with cycle_step(self.scheduling_cycle), _span("cycle"):
            return self._run_cycle(heads, stats, start)

    def _run_cycle(self, heads: list[Info], stats: CycleStats,
                   start: float) -> CycleStats:
        from ..obs.trace import span as _span
        self._cycle_blocked = self.admission_blocked()
        with _span("cycle.snapshot"):
            snapshot = self.cache.snapshot()
        with _span("cycle.nominate"):
            entries = self.nominate(heads, snapshot)
            device_final = self._maybe_solve_on_device(entries, snapshot)
        if device_final is not None:
            with _span("cycle.admit"):
                self._admit_device_cycle(device_final, snapshot, stats)
                for e in entries:
                    if e.status != EntryStatus.ASSUMED:
                        self._requeue_and_update(e)
                        if e.status == EntryStatus.SKIPPED:
                            stats.skipped.append(e.info.key)
                        else:
                            stats.inadmissible.append(e.info.key)
            self._rewake_if_gate_opened()
            stats.duration_s = self.clock() - start
            return stats
        with _span("cycle.order"):
            iterator = self._make_iterator(entries, snapshot)

        preempted_workloads: dict[str, Info] = {}
        with _span("cycle.admit"):
            for e in iterator:
                cq = snapshot.cq(e.info.cluster_queue)
                mode = e.assignment.representative_mode()
                if mode == Mode.NO_FIT:
                    continue

                if mode == Mode.PREEMPT and not e.preemption_targets:
                    # reserve capacity so lower-priority entries can't jump ahead
                    if cq is not None:
                        usage = self._resources_to_reserve(e, cq)
                        cq.simulate_usage_addition(usage)  # revert discarded: snapshot-local
                        self._note_fs_usage(e.info.cluster_queue, usage)
                    continue

                if any(t.info.key in preempted_workloads
                       for t in e.preemption_targets):
                    self._set_skipped(e, "Workload has overlapping preemption "
                                         "targets with another workload")
                    if self.metrics is not None:
                        self.metrics.cycle_preemption_skip()
                    continue

                usage = e.assignment.usage
                if not self._fits(cq, usage, preempted_workloads,
                                  e.preemption_targets):
                    self._set_skipped(e, "Workload no longer fits after "
                                         "processing another workload")
                    continue
                for t in e.preemption_targets:
                    preempted_workloads[t.info.key] = t.info
                cq.simulate_usage_addition(usage)
                self._note_fs_usage(e.info.cluster_queue, usage)

                if e.assignment.representative_mode() == Mode.PREEMPT:
                    e.info.last_assignment = None  # retry all flavors next time
                    preempted = self.preemptor.issue_preemptions(
                        e.info, e.preemption_targets)
                    if preempted:
                        e.inadmissible_msg += (f". Pending the preemption of "
                                               f"{preempted} workload(s)")
                        e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                    stats.preempting.append(e.info.key)
                    stats.preempted_targets.extend(
                        t.info.key for t in e.preemption_targets)
                    continue

                if self._cycle_blocked:
                    # blockAdmission: usage stays consumed for this cycle
                    # (the reference would wait-then-admit here); the entry
                    # requeues and the PodsReady transition wakes it
                    e.inadmissible_msg = ("Waiting for all admitted workloads "
                                          "to be in the PodsReady condition")
                    self.gate_parked = True
                    continue
                e.status = EntryStatus.NOMINATED
                if self._admit(e, cq):
                    stats.admitted.append(e.info.key)
                    # re-check per admission: the workload just admitted is
                    # itself not PodsReady yet, so with blockAdmission at
                    # most one admission lands per cycle (scheduler.go:268
                    # checks PodsReadyForAllAdmittedWorkloads per entry)
                    self._cycle_blocked = self.admission_blocked()
                else:
                    e.inadmissible_msg = "Failed to admit workload"

            for e in entries:
                if e.status != EntryStatus.ASSUMED:
                    self._requeue_and_update(e)
                    if e.status == EntryStatus.SKIPPED:
                        stats.skipped.append(e.info.key)
                    else:
                        stats.inadmissible.append(e.info.key)
        self._rewake_if_gate_opened()
        stats.duration_s = self.clock() - start
        return stats

    def _rewake_if_gate_opened(self) -> None:
        """Close the missed-wakeup race on the blockAdmission gate: the
        gate was sampled at cycle start, but a concurrent PodsReady
        transition may have fired its wake BEFORE this cycle parked the
        held entries.  If the gate is open now, re-wake what we just
        parked."""
        if self._cycle_blocked and not self.admission_blocked():
            self.gate_parked = False
            self.queues.queue_inadmissible_workloads(
                list(self.queues.cluster_queue_names()))
            self.queues.broadcast()

    # ------------------------------------------------------------------
    # Daemon loop — reference scheduler.go:143 Start + util/wait/backoff.go
    # ------------------------------------------------------------------

    def run(self, stop_event, heads_timeout: float = 0.2,
            on_cycle: Optional[Callable[[CycleStats], None]] = None,
            on_tick: Optional[Callable[[], object]] = None) -> None:
        """Long-running admission loop: block on ``queues.heads`` until
        work exists, run a cycle, and pace reruns with the speed-signal
        backoff — KeepGoing after a successful admission, SlowDown
        otherwise (scheduler.go:176,299-301).

        Returns when ``stop_event`` is set or the queue manager stops.
        ``heads_timeout`` bounds each blocking wait so stop is honored
        promptly even with an empty queue.  ``on_tick`` runs every loop
        iteration, heads or not — deadline enforcement (WaitForPodsReady
        timeouts) hangs off it."""
        from ..wait import until_with_backoff

        def cycle() -> bool:
            if self.queues.stopped:
                stop_event.set()
                return True
            if on_tick is not None:
                on_tick()
            heads = self.queues.heads(timeout=heads_timeout)
            if not heads:
                return True  # nothing pending: heads() blocked, no backoff
            stats = self.schedule(heads=heads)
            if on_cycle is not None:
                on_cycle(stats)
            return bool(stats.admitted)

        until_with_backoff(cycle, stop_event)

    # ------------------------------------------------------------------
    # Nomination — reference scheduler.go:336
    # ------------------------------------------------------------------

    def nominate(self, heads: list[Info], snapshot: Snapshot) -> list[Entry]:
        entries = []
        for info in heads:
            lq = self.queues.local_queues.get(
                f"{info.obj.namespace}/{info.obj.queue_name}")
            cq_name = lq.cluster_queue if lq else ""
            info.cluster_queue = cq_name
            e = Entry(info=info)
            e.cq_snapshot = snapshot.cq(cq_name)
            if info.key in self.cache.assumed_workloads or info.obj.is_admitted:
                continue
            if self._has_retry_or_rejected_checks(info.obj):
                e.inadmissible_msg = "The workload has failed admission checks"
            elif cq_name in snapshot.inactive_cluster_queues:
                e.inadmissible_msg = f"ClusterQueue {cq_name} is inactive"
            elif e.cq_snapshot is None:
                e.inadmissible_msg = f"ClusterQueue {cq_name} not found"
            elif not self._namespace_matches(e.cq_snapshot, info.obj.namespace):
                e.inadmissible_msg = ("Workload namespace doesn't match "
                                      "ClusterQueue selector")
                e.requeue_reason = RequeueReason.NAMESPACE_MISMATCH
            elif not self._validate_resources(info):
                e.inadmissible_msg = "resource validation failed"
            elif self.solver is not None:
                e.status = EntryStatus.NOT_NOMINATED
                e.inadmissible_msg = "__deferred__"  # batched assignment below
            else:
                self._assign_entry(e, snapshot)
            entries.append(e)
        return entries

    def _assign_entry(self, e: Entry, snapshot: Snapshot) -> None:
        e.assignment, e.preemption_targets = self._get_assignments(
            e.info, snapshot)
        e.inadmissible_msg = e.assignment.message()
        e.info.last_assignment = e.assignment.last_state

    def _maybe_solve_on_device(self, entries: list[Entry],
                               snapshot: Snapshot):
        """Batched nominate + (when possible) a fully device-decided cycle.

        Two device modes (kueue_tpu.ops.solver):
        - FULL: no preempt-classified head has preemption candidates — the
          admit scan runs as one jitted program and every decision is
          final; returns (deferred, cls, final) for _admit_device_cycle.
        - CLASSIFY: some head needs a real preemption search — the device
          classification replaces per-head flavor assignment for Fit heads
          and the host admit loop runs; returns None.
        """
        import numpy as np
        deferred = [e for e in entries if e.inadmissible_msg == "__deferred__"]
        if not deferred:
            return None
        solver = self.solver
        cls = (solver.classify(snapshot, [e.info for e in deferred])
               if solver is not None else None)
        if cls is None:
            if solver is not None:
                solver.stats["host_cycles"] += 1
            for e in deferred:
                e.inadmissible_msg = ""
                self._assign_entry(e, snapshot)
            return None
        if self.fair_sharing:
            # fair-sharing cycles: the tournament + admit loop runs as
            # one device scan (ops/fs_scan.py) when every head is
            # vector-classified and nothing needs preemption searches;
            # otherwise device classification still replaces the
            # per-head flavor walk and the host tournament decides
            n = cls.n
            if not (cls.fit_slot0[:n] >= 0).any():
                # nothing can admit: fs_admit_scan's can_admit requires
                # a fit slot, and the dispatch gate below already
                # excludes preempt-capable heads — the tournament would
                # decide nothing, so skip the device round-trip
                solver.stats["fs_noop_skips"] += 1
                solver.stats["classify_cycles"] += 1
                # pure-NoFit cycles (no scalar or preempt-capable head)
                # are a function of (structure, usage, head identity):
                # when that fingerprint matches the last no-op cycle,
                # reuse its per-head walk results instead of re-running
                # C _assign_entry walks against an unchanged snapshot
                cacheable = (not cls.scalar_mask[:n].any()
                             and not cls.preempt0[:n].any())
                fp = None
                if cacheable:
                    fp = (cls.packed.structure.generation,
                          cls.packed.usage0.tobytes(),
                          tuple(e.info.key for e in deferred),
                          tuple(id(e.info) for e in deferred))
                    hit = self._fs_noop_cache
                    if hit is not None and hit[0] == fp:
                        for e, (a, tg, msg, last) in zip(deferred,
                                                         hit[1]):
                            e.assignment = a
                            e.preemption_targets = tg
                            e.inadmissible_msg = msg
                            e.info.last_assignment = last
                        solver.stats["fs_noop_reuses"] = (
                            solver.stats.get("fs_noop_reuses", 0) + 1)
                        return None
                self._assign_classified(deferred, cls, snapshot, set())
                if fp is not None and not any(
                        getattr(e.info.last_assignment,
                                "pending_flavors", False)
                        for e in deferred):
                    # resume-state outputs would make the next walk
                    # input-dependent; only a fixed point is cacheable
                    self._fs_noop_cache = (fp, [
                        (e.assignment, e.preemption_targets,
                         e.inadmissible_msg, e.info.last_assignment)
                        for e in deferred])
                return None
            fs_handle = None
            if (not self._cycle_blocked
                    and not cls.scalar_mask[:n].any()
                    and not cls.preempt0[:n].any()):
                fs_handle = solver.dispatch_fs(cls)
            if fs_handle is None:
                solver.stats["classify_cycles"] += 1
                self._assign_classified(deferred, cls, snapshot, set())
                return None
            solver.stats["full_cycles"] += 1
            solver.stats["fs_full_cycles"] += 1
            return deferred, cls, fs_handle, {}, {}, set()
        n = cls.n
        reserve = np.zeros(n, dtype=bool)
        full_ok = True
        targets_by_wi: dict[int, list] = {}
        assignments_by_wi: dict[int, Assignment] = {}
        walked: set[int] = set()
        self.preemptor.set_cycle_pack(snapshot, cls.packed)

        def scalar_walk(wi: int) -> bool:
            """Host FlavorAssigner walk for one head (nominate-time,
            snapshot state) — multi-RG/multi-PodSet/taints/fungibility/
            resume-state/partial-admission/TAS heads stay inside the
            device-decided cycle this way."""
            e = deferred[wi]
            e.inadmissible_msg = ""
            self._assign_entry(e, snapshot)
            walked.add(wi)
            if not cls.scalar_mask[wi]:
                # promoted post-classify (multi-preempt-slot head)
                cls.scalar_mask[wi] = True
                solver.stats["scalar_heads"] += 1
            a = e.assignment
            mode = a.representative_mode()
            if mode == Mode.NO_FIT:
                return True
            if not solver.attach_host_assignment(cls, wi, a):
                return False
            if mode == Mode.PREEMPT:
                if e.preemption_targets:
                    targets_by_wi[wi] = e.preemption_targets
                    assignments_by_wi[wi] = a
                else:
                    reserve[wi] = True
            return True

        for wi in np.nonzero(cls.scalar_mask[:n])[0]:
            if not scalar_walk(int(wi)):
                full_ok = False
                break

        if full_ok:
            batch_reqs: list[tuple[int, Assignment]] = []
            for wi in np.nonzero(cls.preempt0[:n])[0]:
                wi = int(wi)
                # A policy-stopped preempt choice is final; otherwise with
                # several preempt-capable slots the host walk's best-mode
                # pick depends on the reclaim oracle (flavorassigner.go:692
                # RECLAIM beats PREEMPT) — run the real walk for this head.
                if not (cls.preempt_stopped0[wi]
                        or cls.preempt_slot_count[wi] == 1):
                    if not scalar_walk(wi):
                        full_ok = False
                        break
                    continue
                batch_reqs.append(
                    (wi, solver.build_preempt_assignment(cls, wi)))
            if full_ok and batch_reqs:
                # all preempt heads' target searches in ONE batched
                # dispatch (preemption.go:127-191; candidate discovery
                # host-side, greedy+fillback searches vmapped)
                results = self.preemptor.get_targets_batch(
                    [(deferred[wi].info, a) for wi, a in batch_reqs],
                    snapshot)
                for (wi, assignment), targets in zip(batch_reqs, results):
                    if targets:
                        targets_by_wi[wi] = targets
                        assignments_by_wi[wi] = assignment
                    else:
                        reserve[wi] = True

        packed_targets = None
        if full_ok and targets_by_wi:
            packed_targets = solver.pack_targets(cls, targets_by_wi)
            if packed_targets is None:
                full_ok = False

        if not full_ok:
            solver.stats["classify_cycles"] += 1
            self._assign_classified(deferred, cls, snapshot, walked)
            return None

        handle = solver.dispatch(cls, reserve, packed_targets)
        solver.stats["full_cycles"] += 1
        return (deferred, cls, handle, assignments_by_wi, targets_by_wi,
                walked)

    def _assign_classified(self, deferred: list[Entry], cls, snapshot,
                           walked: set[int]) -> None:
        """Classify-mode assignment: device-classified Fit heads get the
        reconstructed assignment, everything else (scalar, preempt, NoFit)
        runs the host walk — the host admit loop takes over from here."""
        solver = self.solver
        for wi, e in enumerate(deferred):
            if wi in walked:
                continue  # the host walk already ran for this head
            e.inadmissible_msg = ""
            if not cls.scalar_mask[wi] and cls.fit_slot0[wi] >= 0:
                e.assignment = solver.build_fit_assignment(cls, wi)
                e.info.last_assignment = e.assignment.last_state
            else:
                # preempt/nofit/scalar heads need the host walk (targets,
                # exact reasons, resume state)
                self._assign_entry(e, snapshot)

    def _admit_device_cycle(self, device, snapshot: Snapshot,
                            stats: CycleStats) -> None:
        """Apply a fully device-decided cycle: admit in cycle order, mark
        in-scan losers skipped, reserve-and-requeue candidate-less preempt
        heads (decision-identical to the host admit loop).

        The scan is still in flight when this starts — all per-head host
        work whose outcome doesn't depend on the scan (fit assignments,
        reserve messages, NoFit walks, speculative admit objects) runs
        FIRST, overlapped with the device execution; ``solver.fetch`` then
        blocks only for whatever latency is left."""
        deferred, cls, handle, assignments_by_wi, targets_by_wi, walked = device
        solver = self.solver
        n = cls.n
        for wi in range(n):
            e = deferred[wi]
            if wi in walked:
                # scalar head: the host walk already produced the
                # assignment, message, resume state, and targets
                continue
            if cls.fit_slot0[wi] >= 0:
                e.assignment = solver.build_fit_assignment(cls, wi)
                e.info.last_assignment = e.assignment.last_state
                e.inadmissible_msg = ""
            elif wi in assignments_by_wi:
                e.assignment = assignments_by_wi[wi]
                e.inadmissible_msg = e.assignment.message()
                e.info.last_assignment = e.assignment.last_state
                e.preemption_targets = targets_by_wi[wi]
            elif handle.rmask[wi]:
                e.assignment, e.inadmissible_msg = solver.reserve_details(
                    cls, wi)
                e.info.last_assignment = e.assignment.last_state
            else:
                # NoFit: the host walk produces the exact reasons and
                # resume state
                e.inadmissible_msg = ""
                self._assign_entry(e, snapshot)
        if handle.route == "accel":
            # the round trip dwarfs per-head prep: speculatively build the
            # admission objects for every fit head while the chip works
            for wi in range(n):
                e = deferred[wi]
                if handle.fit_mask[wi]:
                    cq = snapshot.cq(e.info.cluster_queue)
                    if cq is not None:
                        self._prepare_admit(e, cq)

        final = solver.fetch(handle)
        for wi in final.order:
            wi = int(wi)
            e = deferred[wi]
            cq = snapshot.cq(e.info.cluster_queue)
            if final.admitted[wi]:
                if self._cycle_blocked:
                    e.inadmissible_msg = (
                        "Waiting for all admitted workloads to be in the "
                        "PodsReady condition")
                    self.gate_parked = True
                    continue
                e.status = EntryStatus.NOMINATED
                if self._admit(e, cq):
                    stats.admitted.append(e.info.key)
                    # per-admission re-check (see host loop): at most one
                    # not-yet-ready admission per cycle under the gate
                    self._cycle_blocked = self.admission_blocked()
                else:
                    e.inadmissible_msg = "Failed to admit workload"
            elif final.preempting is not None and final.preempting[wi]:
                # in-scan preemption winner: issue the evictions
                # (scheduler.go:176-284 preempt branch)
                e.info.last_assignment = None
                preempted = self.preemptor.issue_preemptions(
                    e.info, e.preemption_targets)
                if preempted:
                    e.inadmissible_msg += (f". Pending the preemption of "
                                           f"{preempted} workload(s)")
                    e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                stats.preempting.append(e.info.key)
                stats.preempted_targets.extend(
                    t.info.key for t in e.preemption_targets)
            elif final.overlap_skip is not None and final.overlap_skip[wi]:
                self._set_skipped(e, "Workload has overlapping preemption "
                                     "targets with another workload")
                if self.metrics is not None:
                    self.metrics.cycle_preemption_skip()
            elif wi in assignments_by_wi:
                # preempt entry that no longer fits after earlier entries
                self._set_skipped(e, "Workload no longer fits after "
                                     "processing another workload")
            elif handle.fit_mask[wi]:
                # fit at nominate, lost capacity in-scan (scheduler.go:245)
                self._set_skipped(e, "Workload no longer fits after "
                                     "processing another workload")

    # ------------------------------------------------------------------
    # Burst application — fused multi-cycle decisions (ops/burst.py)
    # ------------------------------------------------------------------

    def apply_burst_cycle(self, heads: list[Info],
                          modeled: dict) -> Optional[CycleStats]:
        """Apply one fused-burst cycle's decisions to the real state.

        ``modeled``: {workload key: (kind, slot, borrows, targets)} from
        the burst kernel, where kind ∈ "admit"|"skip"|"park"|"preempt"|
        "reserve"|"overlap_skip"|"pre_nofit" and ``targets`` (preempt
        only) is [(target key, target cq name), ...].  The caller has
        already validated that ``heads`` matches the modeled head set
        exactly; this applies the same mutations the normal admit loop
        would — assume + apply for admissions, eviction issuance for
        preemptions, skip/park/reserve requeues — without re-deciding
        anything (reference scheduler.go:211-284 with the decisions
        precomputed).

        Returns None — with NO state mutated, not even the cycle
        counter — when a modeled preempt target has no live admitted
        Info: the kernel's model of admitted capacity diverged from the
        real cache, so every decision in the cycle is suspect and the
        caller must re-decide on the host path."""
        from ..ops.solver import build_slot_assignment
        from ..api.types import (
            IN_CLUSTER_QUEUE_REASON,
            IN_COHORT_RECLAMATION_REASON,
        )
        # pre-resolve every modeled eviction target BEFORE mutating
        # anything: a missing target means the modeled admitted set is
        # stale, which taints the whole cycle, not just one eviction
        for _kind, _slot, _borrows, _targets in modeled.values():
            if _kind == "preempt":
                for tkey, tcq_name in _targets:
                    if self._live_admitted_info(tcq_name, tkey) is None:
                        return None
        self.scheduling_cycle += 1
        stats = CycleStats(cycle=self.scheduling_cycle)
        start = self.clock()
        for info in heads:
            lq = self.queues.local_queues.get(
                f"{info.obj.namespace}/{info.obj.queue_name}")
            info.cluster_queue = lq.cluster_queue if lq else ""
            e = Entry(info=info)
            kind, slot, borrows, targets = modeled[info.key]
            cq = self.cache.cluster_queue(info.cluster_queue)
            if kind == "admit":
                e.assignment = build_slot_assignment(
                    info, cq, slot, Mode.FIT, borrows)
                e.info.last_assignment = e.assignment.last_state
                e.status = EntryStatus.NOMINATED
                if self._admit(e, cq):
                    stats.admitted.append(info.key)
                    continue
                # mirror the normal path's failure handling
                # (scheduler.go:490): _admit already requeued an ASSUMED
                # entry whose async apply failed
                e.inadmissible_msg = "Failed to admit workload"
                if e.status != EntryStatus.ASSUMED:
                    stats.inadmissible.append(info.key)
                    self._requeue_and_update(e)
                continue
            if kind == "skip":
                e.assignment = build_slot_assignment(
                    info, cq, slot, Mode.FIT, borrows)
                e.info.last_assignment = e.assignment.last_state
                self._set_skipped(e, "Workload no longer fits after "
                                     "processing another workload")
                stats.skipped.append(info.key)
            elif kind == "preempt":
                # in-kernel preemption winner: issue the evictions
                # (scheduler.go:176-284 preempt branch; targets were
                # selected by the kernel's greedy+fillback search)
                e.assignment = build_slot_assignment(
                    info, cq, slot, Mode.PREEMPT, borrows)
                e.inadmissible_msg = e.assignment.message()
                e.info.last_assignment = None
                tgt_objs = []
                for tkey, tcq_name in targets:
                    t_info = self._live_admitted_info(tcq_name, tkey)
                    if t_info is None:
                        continue
                    reason = (IN_CLUSTER_QUEUE_REASON
                              if tcq_name == info.cluster_queue
                              else IN_COHORT_RECLAMATION_REASON)
                    tgt_objs.append(Target(info=t_info, reason=reason))
                preempted = self.preemptor.issue_preemptions(e.info,
                                                             tgt_objs)
                if preempted:
                    e.inadmissible_msg += (
                        f". Pending the preemption of {preempted} "
                        f"workload(s)")
                    e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                stats.preempting.append(info.key)
                stats.preempted_targets.extend(t.info.key
                                               for t in tgt_objs)
                # the entry itself requeues un-assumed: the host cycle
                # counts it inadmissible as well (scheduler.py loop tail)
                stats.inadmissible.append(info.key)
            elif kind == "reserve":
                # preempt-classified, no targets: capacity was reserved
                # in-kernel; the entry requeues not-nominated
                e.assignment = build_slot_assignment(
                    info, cq, slot, Mode.PREEMPT, borrows)
                e.info.last_assignment = e.assignment.last_state
                e.inadmissible_msg = e.assignment.message()
                stats.inadmissible.append(info.key)
            elif kind == "overlap_skip":
                e.assignment = build_slot_assignment(
                    info, cq, slot, Mode.PREEMPT, borrows)
                e.info.last_assignment = e.assignment.last_state
                self._set_skipped(e, "Workload has overlapping "
                                     "preemption targets with another "
                                     "workload")
                if self.metrics is not None:
                    self.metrics.cycle_preemption_skip()
                stats.skipped.append(info.key)
            elif kind == "pre_nofit":
                e.assignment = build_slot_assignment(
                    info, cq, slot, Mode.PREEMPT, borrows)
                e.info.last_assignment = e.assignment.last_state
                self._set_skipped(e, "Workload no longer fits after "
                                     "processing another workload")
                stats.skipped.append(info.key)
            else:  # park: NoFit at nominate (BestEffortFIFO parks it)
                e.info.last_assignment = None
                e.inadmissible_msg = ("couldn't assign flavors to pod "
                                      "set: insufficient quota")
                stats.inadmissible.append(info.key)
            self._requeue_and_update(e)
        stats.duration_s = self.clock() - start
        return stats

    def _live_admitted_info(self, cq_name: str, key: str) -> Optional[Info]:
        """The live cache Info of an admitted workload (eviction target)."""
        cq = self.cache.cluster_queue(cq_name)
        if cq is None:
            return None
        return cq.workloads.get(key)

    @staticmethod
    def _has_retry_or_rejected_checks(wl: Workload) -> bool:
        return any(st.state in (AdmissionCheckState.RETRY, AdmissionCheckState.REJECTED)
                   for st in wl.admission_check_states.values())

    def _namespace_matches(self, cq: CQState, namespace: str) -> bool:
        selector = cq.spec.namespace_selector
        if selector is None or not selector:
            return True
        if self.namespaces is None:
            return True
        labels = self.namespaces.get(namespace, {})
        return all(labels.get(k) == v for k, v in selector.items())

    def _validate_resources(self, info: Info) -> bool:
        """Non-negative totals + namespace LimitRange bounds (reference
        scheduler.go:336 validateResources via pkg/util/limitrange)."""
        if not all(v >= 0 for psr in info.total_requests
                   for v in psr.requests.values()):
            return False
        # requests must not exceed the pod's own limits
        # (workload.go RequestsMustNotExceedLimitMessage,
        # scheduler_test.go:2613)
        for ps in info.obj.pod_sets:
            for res, req in ps.requests.items():
                lim = ps.limits.get(res)
                if lim is not None and req > lim:
                    return False
        summary = self.limit_range_summaries.get(info.obj.namespace)
        if summary is not None:
            from ..limitrange import validate as lr_validate
            for ps in info.obj.pod_sets:
                if lr_validate(ps.requests, summary):
                    return False
        return True

    def _get_assignments(self, wl: Info, snapshot: Snapshot
                         ) -> tuple[Assignment, list[Target]]:
        """reference scheduler.go:415 getAssignments."""
        cq = snapshot.cq(wl.cluster_queue)
        oracle = PreemptionOracle(self.preemptor, snapshot)
        from .. import features
        assigner = FlavorAssigner(
            wl, cq, snapshot.resource_flavors,
            enable_fair_sharing=self.fair_sharing, oracle=oracle,
            tas_flavors=snapshot.tas_flavors,
            tas_enabled=features.enabled("TopologyAwareScheduling"))
        full = assigner.assign(None)
        mode = full.representative_mode()
        if mode == Mode.FIT:
            return full, []
        if mode == Mode.PREEMPT:
            targets = self.preemptor.get_targets(wl, full, snapshot)
            if targets:
                return full, targets
        if (features.enabled("PartialAdmission")
                and self._can_be_partially_admitted(wl)):
            def fits(counts: list[int]):
                assignment = assigner.assign(counts)
                m = assignment.representative_mode()
                if m == Mode.FIT:
                    return (assignment, []), True
                if m == Mode.PREEMPT:
                    targets = self.preemptor.get_targets(wl, assignment, snapshot)
                    if targets:
                        return (assignment, targets), True
                return None, False
            reducer = PodSetReducer(wl.obj.pod_sets, fits)
            result, found = reducer.search()
            if found and result is not None:
                return result
        return full, []

    @staticmethod
    def _can_be_partially_admitted(wl: Info) -> bool:
        return any(ps.min_count is not None and ps.min_count < ps.count
                   for ps in wl.obj.pod_sets)

    # ------------------------------------------------------------------
    # Iterators — reference scheduler.go:567-600 + fair_sharing_iterator.go
    # ------------------------------------------------------------------

    def _make_iterator(self, entries: list[Entry], snapshot: Snapshot):
        if self.fair_sharing:
            return self._fair_sharing_iterator(entries, snapshot)
        return self._classical_iterator(entries)

    def _classical_iterator(self, entries: list[Entry]):
        def sort_key(e: Entry):
            return (1 if e.assignment.borrows() else 0,
                    -e.obj.priority,
                    self.ordering.queue_order_timestamp(e.obj))
        return iter(sorted(entries, key=sort_key))

    def _fs_less(self, a: Entry, b: Entry, parent: str, drs_values) -> bool:
        """entryComparer.less (fair_sharing_iterator.go:167)."""
        a_drs = drs_values.get((parent, a.info.key), 0)
        b_drs = drs_values.get((parent, b.info.key), 0)
        if a_drs != b_drs:
            return a_drs < b_drs
        if a.obj.priority != b.obj.priority:
            return a.obj.priority > b.obj.priority
        return (self.ordering.queue_order_timestamp(a.obj)
                < self.ordering.queue_order_timestamp(b.obj))

    def _fs_tournament(self, cohort, remaining: dict[str, Entry],
                       drs_values) -> Optional[Entry]:
        """runTournament (fair_sharing_iterator.go:121)."""
        candidates = []
        for child in cohort.child_cohorts:
            cand = self._fs_tournament(child, remaining, drs_values)
            if cand is not None:
                candidates.append(cand)
        for cq in cohort.child_cqs:
            cand = remaining.get(cq.name)
            if cand is not None and cand.cq_snapshot is cq:
                candidates.append(cand)
        if not candidates:
            return None
        best = candidates[0]
        for cur in candidates[1:]:
            if self._fs_less(cur, best, cohort.name, drs_values):
                best = cur
        return best

    def _fs_drs_values_ref(self, remaining: dict[str, Entry]
                           ) -> dict[tuple[str, str], int]:
        """Scalar per-entry computeDRS (simulate + revert per entry) —
        the oracle the batched TournamentDRS is parity-tested against,
        and the fallback when the tracker can't represent an entry."""
        drs_values: dict[tuple[str, str], int] = {}
        for cq_name, e in remaining.items():
            cq = e.cq_snapshot
            revert = cq.simulate_usage_addition(e.assignment.usage)
            drs_values[(getattr(cq.parent, "name", ""), e.info.key)] = (
                dominant_resource_share(cq)[0])
            cohort = cq.parent
            while cohort is not None and cohort.parent is not None:
                drs_values[(cohort.parent.name, e.info.key)] = (
                    dominant_resource_share(cohort)[0])
                cohort = cohort.parent
            revert()
        return drs_values

    def _fair_sharing_iterator(self, entries: list[Entry], snapshot: Snapshot):
        """Per-cohort tournament minimizing post-admission DRS
        (reference fair_sharing_iterator.go:121).

        Per round the DRS values for ALL remaining entries come from one
        batched TournamentDRS pass over packed int64 tensors; the admit
        loop's usage mutations are mirrored in via ``_note_fs_usage`` so
        no per-round repack or per-entry simulate/revert happens.  Falls
        back to the scalar computeDRS when an entry's usage can't be
        packed (unseen flavor-resource)."""
        import numpy as np
        from ..ops.fairsharing_kernel import TournamentDRS

        remaining: dict[str, Entry] = {
            e.info.cluster_queue: e for e in entries if e.cq_snapshot is not None}
        no_cq = [e for e in entries if e.cq_snapshot is None]
        yield from no_cq

        tracker = TournamentDRS(snapshot) if self.fs_batched else None
        vecs: dict[str, np.ndarray] = {}
        if tracker is not None:
            for cq_name, e in remaining.items():
                if tracker.cq_index.get(cq_name) is None:
                    tracker = None
                    break
                vec = tracker.u_vec(e.assignment.usage)
                if vec is None:
                    tracker = None
                    break
                vecs[cq_name] = vec
        if tracker is None and self.fs_batched:
            self.fs_stats["tracker_unavailable_cycles"] += 1
        self._fs_tracker = tracker
        try:
            while remaining:
                cq_name = next(iter(remaining))
                cq = remaining[cq_name].cq_snapshot
                if cq.parent is None:
                    yield remaining.pop(cq_name)
                    continue
                if tracker is not None and not tracker.stale:
                    keys = list(remaining)
                    cq_is = np.array([tracker.cq_index[k] for k in keys],
                                     dtype=np.int64)
                    u_es = np.stack([vecs[k] for k in keys])
                    paths, drs = tracker.drs_for(cq_is, u_es)
                    drs_values: dict[tuple[str, str], int] = {}
                    for j, k in enumerate(keys):
                        wl_key = remaining[k].info.key
                        for level in range(paths.shape[1]):
                            node = int(paths[j, level])
                            if node < 0:
                                break
                            par = int(tracker.parent[node])
                            if par < 0:
                                break
                            drs_values[(tracker.names[par], wl_key)] = int(
                                drs[j, level])
                else:
                    self.fs_stats["scalar_drs_rounds"] += 1
                    drs_values = self._fs_drs_values_ref(remaining)
                winner = self._fs_tournament(cq.parent.root(), remaining,
                                             drs_values)
                if winner is None:
                    yield remaining.pop(cq_name)
                    continue
                del remaining[winner.info.cluster_queue]
                yield winner
        finally:
            self._fs_tracker = None

    def _note_fs_usage(self, cq_name: str, usage) -> None:
        """Mirror an admit-loop usage mutation into the tournament's
        packed tensor (called after simulate_usage_addition)."""
        t = self._fs_tracker
        if t is not None:
            t.note_add(cq_name, usage)

    # ------------------------------------------------------------------
    # Admission mechanics
    # ------------------------------------------------------------------

    @staticmethod
    def _fits(cq: CQState, usage: FlavorResourceQuantities,
              preempted: dict[str, Info], new_targets: list[Target]) -> bool:
        """reference scheduler.go:372 fits."""
        workloads = list(preempted.values()) + [t.info for t in new_targets]
        seen, unique = set(), []
        for w in workloads:  # a target may already be in preempted
            if w.key not in seen:
                seen.add(w.key)
                unique.append(w)
        return _fits_with_removal(cq, usage, unique)

    def _resources_to_reserve(self, e: Entry, cq: CQState) -> FlavorResourceQuantities:
        """reference scheduler.go:383-408 resourcesToReserve."""
        if e.assignment.representative_mode() != Mode.PREEMPT:
            return e.assignment.usage
        reserved = FlavorResourceQuantities()
        for fr, usage in e.assignment.usage.items():
            quota = cq.resource_node.quotas.get(fr)
            nominal = quota.nominal if quota else 0
            b_limit = quota.borrowing_limit if quota else None
            cur = cq.resource_node.usage.get(fr, 0)
            if e.assignment.borrowing:
                if b_limit is None:
                    reserved[fr] = usage
                else:
                    reserved[fr] = min(usage, nominal + b_limit - cur)
            else:
                reserved[fr] = max(0, min(usage, nominal - cur))
        return reserved

    @staticmethod
    def _set_skipped(e: Entry, message: str) -> None:
        e.status = EntryStatus.SKIPPED
        e.inadmissible_msg = message
        e.requeue_reason = RequeueReason.GENERIC

    def _prepare_admit(self, e: Entry, cq: CQState) -> tuple:
        """Build the admission objects for an entry (reference
        scheduler.go:490 admit, the pure part before assume/apply).  Safe
        to run speculatively — nothing is committed; the device path calls
        this while the admit scan is still in flight."""
        now = self.clock()
        new_wl = e.obj.clone()
        admission = Admission(cluster_queue=e.info.cluster_queue,
                              pod_set_assignments=e.assignment.to_api())
        set_quota_reservation(new_wl, admission, now)
        # initialize admission-check states required by the CQ
        for check_name in self._checks_for(cq, e.assignment):
            if check_name not in new_wl.admission_check_states:
                new_wl.admission_check_states[check_name] = AdmissionCheckStatus(
                    name=check_name, state=AdmissionCheckState.PENDING,
                    last_transition_time=now)
        sync_admitted_condition(new_wl, now)
        new_info = Info(new_wl, self.cache.info_options)
        new_info.cluster_queue = e.info.cluster_queue
        e.prepped = (new_wl, new_info)
        return e.prepped

    def _admit(self, e: Entry, cq: CQState) -> bool:
        """reference scheduler.go:490 admit."""
        new_wl, new_info = e.prepped or self._prepare_admit(e, cq)
        if not self.cache.assume_workload(new_info):
            return False
        e.status = EntryStatus.ASSUMED
        if not self.apply_admission(new_wl):
            self.cache.forget_workload(new_info)
            self._requeue_and_update(e)
            return False
        return True

    def _checks_for(self, cq: CQState, assignment: Assignment) -> list[str]:
        """AdmissionChecks + per-flavor strategy rules (reference
        workload.AdmissionChecksForWorkload)."""
        if not cq.spec.admission_checks and \
                not cq.spec.admission_checks_strategy:
            return []
        checks = list(cq.spec.admission_checks)
        used_flavors = {fa.name for ps in assignment.pod_sets
                        for fa in ps.flavors.values()}
        for rule in cq.spec.admission_checks_strategy:
            if not rule.on_flavors or used_flavors & set(rule.on_flavors):
                if rule.name not in checks:
                    checks.append(rule.name)
        return checks

    def _requeue_and_update(self, e: Entry) -> None:
        """reference scheduler.go:636 requeueAndUpdate."""
        if (e.status != EntryStatus.NOT_NOMINATED
                and e.requeue_reason == RequeueReason.GENERIC):
            e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
        self.queues.requeue_workload(e.info, e.requeue_reason)
        self.on_requeue(e)


def _fits_with_removal(cq: CQState, usage: FlavorResourceQuantities,
                       to_remove: list[Info]) -> bool:
    """Simulate removing preempted workloads anywhere in the cohort tree,
    then check Fits (reference scheduler.go:372-381)."""
    if cq is None:
        return False
    # Find each workload's CQ within the same snapshot (walk the tree root).
    removed: list[tuple[CQState, Info]] = []

    def find_cq(info: Info) -> Optional[CQState]:
        if cq.parent is not None:
            for c in cq.parent.root().subtree_cqs():
                if info.key in c.workloads:
                    return c
        if info.key in cq.workloads:
            return cq
        return None

    for info in to_remove:
        owner = find_cq(info)
        if owner is not None:
            owner.remove_workload(owner.workloads[info.key])
            removed.append((owner, info))
    fits = cq.fits(usage)
    for owner, info in removed:
        owner.add_workload(info)
    return fits
