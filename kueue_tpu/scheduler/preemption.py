"""Preemption target selection and eviction issuance.

Capability parity with reference pkg/scheduler/preemption/preemption.go:
candidate discovery honoring withinClusterQueue / reclaimWithinCohort /
borrowWithinCohort policies (findCandidates :480), candidate ordering
(:591), greedy minimal-preemption simulation with fill-back (:275-342),
fair-sharing preemption with S2-a/S2-b strategies (:372-442), and the
reclaim oracle used by the flavor assigner (preemption_oracle.go:40).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import (
    BorrowWithinCohortPolicy,
    ConditionStatus,
    ReclaimWithinCohort,
    WithinClusterQueue,
    IN_CLUSTER_QUEUE_REASON,
    IN_COHORT_FAIR_SHARING_REASON,
    IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
    IN_COHORT_RECLAMATION_REASON,
    WL_EVICTED,
    WL_QUOTA_RESERVED,
)
from ..cache.snapshot import Snapshot
from ..cache.state import CQState
from ..resources import FlavorResource, FlavorResourceQuantities
from ..workload import Info, Ordering
from . import fairsharing
from .flavorassigner import Assignment, Mode


@dataclass
class Target:
    info: Info
    reason: str


@dataclass
class _PreemptionCtx:
    preemptor: Info
    preemptor_cq: CQState
    snapshot: Snapshot
    frs_need_preemption: set[FlavorResource]
    workload_usage: FlavorResourceQuantities
    tas_requests: object = None


HUMAN_READABLE_REASONS = {
    IN_CLUSTER_QUEUE_REASON: "prioritization in the ClusterQueue",
    IN_COHORT_RECLAMATION_REASON: "reclamation within the cohort",
    IN_COHORT_FAIR_SHARING_REASON: "Fair Sharing within the cohort",
    IN_COHORT_RECLAIM_WHILE_BORROWING_REASON:
        "reclamation within the cohort while borrowing",
}


def _quota_reservation_time(info: Info, now: float) -> float:
    c = info.obj.conditions.get(WL_QUOTA_RESERVED)
    if c is None or c.status != ConditionStatus.TRUE:
        return now
    return c.last_transition_time


def candidates_ordering_key(cq_name: str, now: float):
    """reference preemption.go:591 candidatesOrdering: evicted first, then
    other-CQ borrowers, then lower priority, then later admission."""
    def key(info: Info):
        evicted = 0 if info.obj.condition_true(WL_EVICTED) else 1
        in_cq = 1 if info.cluster_queue == cq_name else 0
        return (evicted, in_cq, info.obj.priority,
                -_quota_reservation_time(info, now), info.obj.uid)
    return key


def flavor_resources_need_preemption(assignment: Assignment) -> set[FlavorResource]:
    """reference preemption.go:466."""
    out = set()
    for ps in assignment.pod_sets:
        for res, fa in ps.flavors.items():
            if fa.mode == Mode.PREEMPT:
                out.add(FlavorResource(fa.name, res))
    return out


def _workload_uses_resources(info: Info, frs: set[FlavorResource]) -> bool:
    for psr in info.total_requests:
        for res, flavor in psr.flavors.items():
            if FlavorResource(flavor, res) in frs:
                return True
    return False


def _cq_is_borrowing(cq: CQState, frs: set[FlavorResource]) -> bool:
    if not cq.has_parent():
        return False
    return any(cq.borrowing(fr) for fr in frs)


class Preemptor:
    """reference preemption.go Preemptor."""

    def __init__(self, enable_fair_sharing: bool = False,
                 fs_strategies: list[str] | None = None,
                 ordering: Ordering | None = None,
                 clock: Callable[[], float] = time.time):
        self.enable_fair_sharing = enable_fair_sharing
        self.fs_strategies = fairsharing.parse_strategies(fs_strategies)
        self.ordering = ordering or Ordering()
        self.clock = clock
        # Pluggable apply hook (reference OverrideApply, preemption.go:96):
        # called with (target Info, reason, message) when issuing evictions.
        self.apply_preemption: Optional[Callable[[Info, str, str], None]] = None
        # Run the minimal-preemptions search on device.  "auto" (default):
        # device whenever the scheduler threaded the cycle's cached pack
        # for the current snapshot (O(candidates) per search, no re-pack);
        # True: always (re-packs the snapshot when no pack is cached);
        # False: host greedy+fillback only.  All three are
        # decision-identical (tests/test_preemption_kernel.py).
        self.device_search: object = "auto"
        self._cycle_pack = None   # (weakref to snapshot, PackedCycle)
        self.stats = {"device_searches": 0, "host_searches": 0}

    def set_cycle_pack(self, snapshot: Snapshot, packed) -> None:
        """Thread the admission solver's cached pack for this cycle's
        snapshot so nominate-time searches skip the O(cluster) re-pack.
        Only valid for searches against the same (unmutated) snapshot —
        nominate runs before any admit-loop usage mutation."""
        import weakref
        self._cycle_pack = (weakref.ref(snapshot), packed)

    def _pack_for(self, snapshot: Snapshot):
        if self._cycle_pack is not None and self._cycle_pack[0]() is snapshot:
            return self._cycle_pack[1]
        return None

    # ------------------------------------------------------------------
    # Target selection — reference preemption.go:127-191
    # ------------------------------------------------------------------

    def get_targets(self, wl: Info, assignment: Assignment,
                    snapshot: Snapshot) -> list[Target]:
        cq = snapshot.cq(wl.cluster_queue)
        ctx = _PreemptionCtx(
            preemptor=wl,
            preemptor_cq=cq,
            snapshot=snapshot,
            frs_need_preemption=flavor_resources_need_preemption(assignment),
            workload_usage=assignment.total_requests_for(wl),
        )
        return self._get_targets(ctx)

    def _get_targets(self, ctx: _PreemptionCtx) -> list[Target]:
        candidates = self._find_candidates(ctx)
        if not candidates:
            return []
        candidates.sort(key=candidates_ordering_key(ctx.preemptor_cq.name,
                                                    self.clock()))
        if self.enable_fair_sharing:
            return self._fair_preemptions(ctx, candidates)

        specs, staged = self.plan_searches(ctx, candidates)
        cands, ab, thr = specs[0]
        first = self._minimal_preemptions(ctx, cands, ab, thr)
        if not staged or first:
            return first
        cands, ab, thr = specs[1]  # queue-under-nominal retry
        return self._minimal_preemptions(ctx, cands, ab, thr)

    def plan_searches(self, ctx: _PreemptionCtx, candidates: list[Info]
                      ) -> tuple[list[tuple[list[Info], bool, Optional[int]]],
                                 bool]:
        """The minimalPreemptions calls _get_targets will issue, computed
        UPFRONT (every branch condition is snapshot-state only) so a
        cycle's searches can run as one batched dispatch.

        Returns (specs, staged): specs = [(candidates, allow_borrowing,
        threshold)]; staged=True → use spec 0's result if it fitted,
        else spec 1's (the queue-under-nominal retry,
        preemption.go:144-191)."""
        same_queue = [c for c in candidates
                      if c.cluster_queue == ctx.preemptor_cq.name]

        if len(same_queue) == len(candidates):
            # no cross-queue candidates: try borrowing
            return [(candidates, True, None)], False

        borrow_ok, threshold = self._can_borrow_within_cohort(ctx)
        if borrow_ok:
            if not self._queue_under_nominal(ctx):
                candidates = [c for c in candidates
                              if c.cluster_queue == ctx.preemptor_cq.name
                              or c.obj.priority < threshold]
            return [(candidates, True, threshold)], False

        if self._queue_under_nominal(ctx):
            return [(candidates, False, None),
                    (same_queue, True, None)], True

        return [(same_queue, True, None)], False

    def get_targets_batch(self, requests: list[tuple[Info, Assignment]],
                          snapshot: Snapshot) -> list[list[Target]]:
        """Target searches for ALL of a cycle's preempt heads in one
        batched device dispatch (ops/preemption_kernel
        minimal_preemptions_batch) — candidate discovery and ordering
        stay host-side, the greedy+fillback searches vmap.  Falls back
        to per-head get_targets for fair sharing, a missing cycle pack,
        or an unpackable spec (decision-identical either way)."""
        packed = self._pack_for(snapshot)
        if (self.enable_fair_sharing or packed is None
                or self.device_search is False or not requests):
            return [self.get_targets(wl, a, snapshot) for wl, a in requests]

        flat_specs: list[tuple] = []
        plans: list[tuple[list[int], bool]] = []
        for wl, assignment in requests:
            ctx = _PreemptionCtx(
                preemptor=wl,
                preemptor_cq=snapshot.cq(wl.cluster_queue),
                snapshot=snapshot,
                frs_need_preemption=flavor_resources_need_preemption(
                    assignment),
                workload_usage=assignment.total_requests_for(wl))
            candidates = self._find_candidates(ctx)
            if not candidates:
                plans.append(([], False))
                continue
            candidates.sort(key=candidates_ordering_key(
                ctx.preemptor_cq.name, self.clock()))
            specs, staged = self.plan_searches(ctx, candidates)
            idxs = []
            for cands, ab, thr in specs:
                idxs.append(len(flat_specs))
                flat_specs.append((ctx, cands, ab, thr))
            plans.append((idxs, staged))

        results = None
        if flat_specs:
            from ..ops.preemption_solver import (
                device_minimal_preemptions_batch)
            results = device_minimal_preemptions_batch(flat_specs, packed)
            if results is None:
                # unpackable spec: per-head host path
                return [self.get_targets(wl, a, snapshot)
                        for wl, a in requests]
            self.stats["device_searches"] += len(flat_specs)

        out: list[list[Target]] = []
        for idxs, staged in plans:
            if not idxs:
                out.append([])
            elif staged and results[idxs[0]]:
                out.append(results[idxs[0]])
            else:
                out.append(results[idxs[-1]])
        return out

    def _can_borrow_within_cohort(self, ctx: _PreemptionCtx
                                  ) -> tuple[bool, Optional[int]]:
        """reference preemption.go:194 canBorrowWithinCohort."""
        bwc = ctx.preemptor_cq.preemption.borrow_within_cohort
        if bwc.policy == BorrowWithinCohortPolicy.NEVER:
            return False, None
        threshold = ctx.preemptor.obj.priority
        if (bwc.max_priority_threshold is not None
                and bwc.max_priority_threshold < threshold):
            threshold = bwc.max_priority_threshold + 1
        return True, threshold

    def _queue_under_nominal(self, ctx: _PreemptionCtx) -> bool:
        """reference preemption.go queueUnderNominalInResourcesNeedingPreemption."""
        cq = ctx.preemptor_cq
        for fr in ctx.frs_need_preemption:
            quota = cq.resource_node.quotas.get(fr)
            nominal = quota.nominal if quota else 0
            if cq.resource_node.usage.get(fr, 0) >= nominal:
                return False
        return True

    # ------------------------------------------------------------------
    # Candidates — reference preemption.go:480 findCandidates
    # ------------------------------------------------------------------

    def _find_candidates(self, ctx: _PreemptionCtx) -> list[Info]:
        cq = ctx.preemptor_cq
        wl = ctx.preemptor
        candidates: list[Info] = []
        wl_priority = wl.obj.priority

        if cq.preemption.within_cluster_queue != WithinClusterQueue.NEVER:
            consider_same_prio = (cq.preemption.within_cluster_queue
                                  == WithinClusterQueue.LOWER_OR_NEWER_EQUAL_PRIORITY)
            preemptor_ts = self.ordering.queue_order_timestamp(wl.obj)
            for cand in cq.workloads.values():
                if cand.obj.priority > wl_priority:
                    continue
                if cand.obj.priority == wl_priority and not (
                        consider_same_prio and preemptor_ts
                        < self.ordering.queue_order_timestamp(cand.obj)):
                    continue
                if not _workload_uses_resources(cand, ctx.frs_need_preemption):
                    continue
                candidates.append(cand)

        if cq.has_parent() and cq.preemption.reclaim_within_cohort != ReclaimWithinCohort.NEVER:
            only_lower = cq.preemption.reclaim_within_cohort != ReclaimWithinCohort.ANY
            for cohort_cq in cq.parent.root().subtree_cqs():
                if cohort_cq is cq or not _cq_is_borrowing(cohort_cq, ctx.frs_need_preemption):
                    continue
                for cand in cohort_cq.workloads.values():
                    if only_lower and cand.obj.priority >= wl_priority:
                        continue
                    if not _workload_uses_resources(cand, ctx.frs_need_preemption):
                        continue
                    candidates.append(cand)
        return candidates

    # ------------------------------------------------------------------
    # Minimal preemptions — reference preemption.go:275-342
    # ------------------------------------------------------------------

    def _workload_fits(self, ctx: _PreemptionCtx, allow_borrowing: bool) -> bool:
        """reference preemption.go:552 workloadFits."""
        for fr, v in ctx.workload_usage.items():
            if not allow_borrowing and ctx.preemptor_cq.borrowing_with(fr, v):
                return False
            if v > ctx.preemptor_cq.available(fr):
                return False
        return True

    def _workload_fits_for_fair_sharing(self, ctx: _PreemptionCtx) -> bool:
        revert = ctx.preemptor_cq.simulate_usage_removal(ctx.workload_usage)
        res = self._workload_fits(ctx, True)
        revert()
        return res

    def _minimal_preemptions(self, ctx: _PreemptionCtx, candidates: list[Info],
                             allow_borrowing: bool,
                             allow_borrowing_below_priority: Optional[int]
                             ) -> list[Target]:
        packed = self._pack_for(ctx.snapshot)
        if self.device_search is True or (
                self.device_search == "auto" and packed is not None):
            from ..ops.preemption_solver import device_minimal_preemptions
            result = device_minimal_preemptions(
                ctx, candidates, allow_borrowing,
                allow_borrowing_below_priority, packed=packed)
            if result is not None:
                self.stats["device_searches"] += 1
                return result
        self.stats["host_searches"] += 1
        targets: list[Target] = []
        fits = False
        for cand in candidates:
            cand_cq = ctx.snapshot.cq(cand.cluster_queue)
            reason = IN_CLUSTER_QUEUE_REASON
            if cand_cq is not ctx.preemptor_cq:
                if not _cq_is_borrowing(cand_cq, ctx.frs_need_preemption):
                    continue
                reason = IN_COHORT_RECLAMATION_REASON
                if allow_borrowing_below_priority is not None:
                    if cand.obj.priority >= allow_borrowing_below_priority:
                        # a target above the threshold disables borrowing;
                        # safe because candidates are priority-ordered and
                        # the last-added target survives fill-back
                        allow_borrowing = False
                    else:
                        reason = IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
            ctx.snapshot.remove_workload(cand)
            targets.append(Target(info=cand, reason=reason))
            if self._workload_fits(ctx, allow_borrowing):
                fits = True
                break
        if not fits:
            self._restore(ctx.snapshot, targets)
            return []
        targets = self._fill_back(ctx, targets, allow_borrowing)
        self._restore(ctx.snapshot, targets)
        return targets

    def _fill_back(self, ctx: _PreemptionCtx, targets: list[Target],
                   allow_borrowing: bool) -> list[Target]:
        """reference preemption.go:329 fillBackWorkloads."""
        i = len(targets) - 2
        while i >= 0:
            ctx.snapshot.add_workload(targets[i].info)
            if self._workload_fits(ctx, allow_borrowing):
                targets[i] = targets[-1]
                targets.pop()
            else:
                ctx.snapshot.remove_workload(targets[i].info)
            i -= 1
        return targets

    @staticmethod
    def _restore(snapshot: Snapshot, targets: list[Target]) -> None:
        for t in targets:
            snapshot.add_workload(t.info)

    # ------------------------------------------------------------------
    # Fair-sharing preemptions — reference preemption.go:372-460
    # ------------------------------------------------------------------

    def _fair_preemptions(self, ctx: _PreemptionCtx,
                          candidates: list[Info]) -> list[Target]:
        revert = ctx.preemptor_cq.simulate_usage_addition(ctx.workload_usage)
        fits, targets, retry = self._run_first_fs_strategy(
            ctx, candidates, self.fs_strategies[0])
        if not fits and len(self.fs_strategies) > 1:
            fits, targets = self._run_second_fs_strategy(retry, ctx, targets)
        revert()
        if not fits:
            self._restore(ctx.snapshot, targets)
            return []
        targets = self._fill_back(ctx, targets, True)
        self._restore(ctx.snapshot, targets)
        return targets

    def _run_first_fs_strategy(self, ctx: _PreemptionCtx, candidates: list[Info],
                               strategy) -> tuple[bool, list[Target], list[Info]]:
        ordering = fairsharing.TargetClusterQueueOrdering(
            ctx.preemptor_cq, candidates, ctx.snapshot.cluster_queues)
        targets: list[Target] = []
        retry_candidates: list[Info] = []
        for tcq in ordering.iterate():
            if tcq.in_cluster_queue_preemption():
                cand = tcq.pop_workload()
                ctx.snapshot.remove_workload(cand)
                targets.append(Target(info=cand, reason=IN_CLUSTER_QUEUE_REASON))
                if self._workload_fits_for_fair_sharing(ctx):
                    return True, targets, []
                continue
            preemptor_new, target_old = tcq.compute_shares()
            while tcq.has_workload():
                cand = tcq.pop_workload()
                target_new = tcq.compute_target_share_after_removal(cand)
                if strategy(preemptor_new, target_old, target_new):
                    ctx.snapshot.remove_workload(cand)
                    targets.append(Target(info=cand,
                                          reason=IN_COHORT_FAIR_SHARING_REASON))
                    if self._workload_fits_for_fair_sharing(ctx):
                        return True, targets, []
                    break  # re-pick CQ: shares changed
                retry_candidates.append(cand)
        return False, targets, retry_candidates

    def _run_second_fs_strategy(self, retry_candidates: list[Info],
                                ctx: _PreemptionCtx, targets: list[Target]
                                ) -> tuple[bool, list[Target]]:
        ordering = fairsharing.TargetClusterQueueOrdering(
            ctx.preemptor_cq, retry_candidates, ctx.snapshot.cluster_queues)
        for tcq in ordering.iterate():
            preemptor_new, target_old = tcq.compute_shares()
            if fairsharing.less_than_initial_share(preemptor_new, target_old, 0):
                cand = tcq.pop_workload()
                ctx.snapshot.remove_workload(cand)
                targets.append(Target(info=cand,
                                      reason=IN_COHORT_FAIR_SHARING_REASON))
                if self._workload_fits_for_fair_sharing(ctx):
                    return True, targets
            ordering.drop_queue(tcq)
        return False, targets

    # ------------------------------------------------------------------
    # Issuance — reference preemption.go:232-257
    # ------------------------------------------------------------------

    def issue_preemptions(self, preemptor: Info, targets: list[Target]) -> int:
        from ..workload import set_evicted_condition, set_preempted_condition
        from ..api.types import EVICTED_BY_PREEMPTION
        count = 0
        now = self.clock()
        for t in targets:
            if not t.info.obj.condition_true(WL_EVICTED):
                message = (f"Preempted to accommodate a workload (UID: "
                           f"{preemptor.obj.uid}) due to "
                           f"{HUMAN_READABLE_REASONS.get(t.reason, 'UNKNOWN')}")
                if self.apply_preemption is not None:
                    self.apply_preemption(t.info, t.reason, message)
                else:
                    set_evicted_condition(t.info.obj, EVICTED_BY_PREEMPTION,
                                          message, now)
                    set_preempted_condition(t.info.obj, t.reason, message, now)
            count += 1
        return count


class PreemptionOracle:
    """reference preemption_oracle.go:40."""

    def __init__(self, preemptor: Preemptor, snapshot: Snapshot):
        self.preemptor = preemptor
        self.snapshot = snapshot

    def is_reclaim_possible(self, cq: CQState, wl: Info,
                            fr: FlavorResource, quantity: int) -> bool:
        if cq.borrowing_with(fr, quantity):
            return False
        ctx = _PreemptionCtx(
            preemptor=wl,
            preemptor_cq=self.snapshot.cq(wl.cluster_queue) or cq,
            snapshot=self.snapshot,
            frs_need_preemption={fr},
            workload_usage=FlavorResourceQuantities({fr: quantity}),
        )
        for target in self.preemptor._get_targets(ctx):
            if target.info.cluster_queue == cq.name:
                return False
        return True
