"""Flavor assignment: map each PodSet resource to a flavor + mode.

Capability parity with reference pkg/scheduler/flavorassigner/flavorassigner.go:
walks each resource group's flavor list from the fungibility resume index,
filters by taints/tolerations and node-affinity against flavor node labels,
then classifies quota fit as Fit / Preempt(reclaim) / NoFit
(fitsResourceQuota, flavorassigner.go:692) under the FlavorFungibility
policy (shouldTryNextFlavor, :620).  Partial admission binary-searches pod
counts (podset_reducer.go).

This is the *scalar oracle* implementation; the batched TPU kernel with the
same semantics lives in kueue_tpu.ops.flavor_kernel and is verified against
this module.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol

from ..api.types import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
    PodSet,
    PodSetAssignment,
    PodSetTopologyRequest,
    ReclaimWithinCohort,
    ResourceFlavor,
    TopologyAssignment,
    taints_tolerated,
)
from ..cache.state import CQState
from ..resources import FlavorResource, FlavorResourceQuantities, Requests
from ..workload import Info, PodSetResources


class Mode(enum.IntEnum):
    """Public assignment mode, ordered worst→best (flavorassigner.go:277)."""
    NO_FIT = 0
    PREEMPT = 1
    FIT = 2


class GranularMode(enum.IntEnum):
    """Internal lattice distinguishing reclaim (flavorassigner.go:308)."""
    NO_FIT = 0
    PREEMPT = 1
    RECLAIM = 2
    FIT = 3

    def public(self) -> Mode:
        if self == GranularMode.FIT:
            return Mode.FIT
        if self in (GranularMode.PREEMPT, GranularMode.RECLAIM):
            return Mode.PREEMPT
        return Mode.NO_FIT

    @property
    def is_preempt(self) -> bool:
        return self in (GranularMode.PREEMPT, GranularMode.RECLAIM)


@dataclass
class FlavorAssignmentDecision:
    name: str                      # flavor
    mode: Mode
    tried_flavor_idx: int = -1
    borrow: bool = False


@dataclass
class AssignmentClusterQueueState:
    """Fungibility resume state (reference workload.go:82)."""
    last_tried_flavor_idx: list[dict[str, int]] = field(default_factory=list)
    cluster_queue_generation: int = -1

    def next_flavor_to_try(self, ps_idx: int, res: str) -> int:
        if ps_idx < len(self.last_tried_flavor_idx):
            return self.last_tried_flavor_idx[ps_idx].get(res, -1) + 1
        return 0

    @property
    def pending_flavors(self) -> bool:
        """True when some resource still has untried flavors."""
        return any(idx != -1 for per_ps in self.last_tried_flavor_idx
                   for idx in per_ps.values())


@dataclass
class PodSetAssignmentResult:
    name: str
    flavors: dict[str, FlavorAssignmentDecision] = field(default_factory=dict)
    requests: Requests = field(default_factory=Requests)
    count: int = 0
    reasons: list[str] = field(default_factory=list)
    error: Optional[str] = None
    topology_assignment: Optional[TopologyAssignment] = None

    def representative_mode(self) -> Mode:
        if self.error is not None:
            return Mode.NO_FIT
        if not self.flavors:
            return Mode.NO_FIT if self.requests else Mode.FIT
        return Mode(min(f.mode for f in self.flavors.values()))

    def update_mode(self, mode: Mode) -> None:
        for f in self.flavors.values():
            f.mode = mode


@dataclass
class Assignment:
    pod_sets: list[PodSetAssignmentResult] = field(default_factory=list)
    borrowing: bool = False
    usage: FlavorResourceQuantities = field(default_factory=FlavorResourceQuantities)
    last_state: AssignmentClusterQueueState = field(
        default_factory=AssignmentClusterQueueState)
    _representative: Optional[Mode] = None

    def representative_mode(self) -> Mode:
        if not self.pod_sets:
            return Mode.NO_FIT
        if self._representative is not None:
            return self._representative
        return Mode(min(ps.representative_mode() for ps in self.pod_sets))

    def set_representative_mode(self, mode: Mode) -> None:
        self._representative = mode

    def borrows(self) -> bool:
        return self.borrowing

    def message(self) -> str:
        parts = []
        for ps in self.pod_sets:
            if ps.error:
                return f"failed to assign flavors to pod set {ps.name}: {ps.error}"
            if ps.reasons:
                parts.append(
                    f"couldn't assign flavors to pod set {ps.name}: "
                    + ", ".join(ps.reasons))
        return "; ".join(parts)

    def to_api(self) -> list[PodSetAssignment]:
        out = []
        for ps in self.pod_sets:
            out.append(PodSetAssignment(
                name=ps.name,
                flavors={res: fa.name for res, fa in ps.flavors.items()},
                resource_usage=dict(ps.requests),
                count=ps.count,
                topology_assignment=ps.topology_assignment))
        return out

    def total_requests_for(self, wl: Info) -> FlavorResourceQuantities:
        usage = FlavorResourceQuantities()
        for psr, aps in zip(wl.total_requests, self.pod_sets):
            if aps.count != psr.count:
                psr = psr.scaled_to(aps.count)
            for res, qty in psr.requests.items():
                fa = aps.flavors.get(res)
                if fa is None:
                    continue
                fr = FlavorResource(fa.name, res)
                usage[fr] = usage.get(fr, 0) + qty
        return usage


class PreemptionOracle(Protocol):
    def is_reclaim_possible(self, cq: CQState, wl: Info,
                            fr: FlavorResource, quantity: int) -> bool: ...


class _NeverReclaimOracle:
    def is_reclaim_possible(self, cq, wl, fr, quantity) -> bool:
        return False


def rg_by_resource(cq: CQState, resource: str):
    for rg in cq.spec.resource_groups:
        if resource in rg.covered_resources:
            return rg
    return None


class FlavorAssigner:
    """reference flavorassigner.go:345."""

    def __init__(self, wl: Info, cq: CQState,
                 resource_flavors: dict[str, ResourceFlavor],
                 enable_fair_sharing: bool = False,
                 oracle: Optional[PreemptionOracle] = None,
                 tas_flavors: Optional[dict] = None,
                 flavor_fungibility_enabled: bool = True,
                 tas_enabled: bool = True):
        self.wl = wl
        self.cq = cq
        self.resource_flavors = resource_flavors
        self.enable_fair_sharing = enable_fair_sharing
        self.oracle = oracle or _NeverReclaimOracle()
        self.tas_flavors = tas_flavors or {}
        self.flavor_fungibility_enabled = flavor_fungibility_enabled
        self.tas_enabled = tas_enabled
        self._tas_only: Optional[bool] = None

    def _is_tas_only(self) -> bool:
        """Every flavor of the CQ is a TAS flavor (reference
        clusterqueue_snapshot.go:221 IsTASOnly): pod sets without a
        topology request then get TAS implied (unconstrained)."""
        if self._tas_only is None:
            names = [fq.name for rg in self.cq.spec.resource_groups
                     for fq in rg.flavors] if self.cq is not None else []
            # every flavor must be a TAS flavor AND have topology data
            # loaded — without snapshots, implying TAS would drive the
            # whole CQ to NO_FIT (gate off / topology not yet cached)
            self._tas_only = (self.tas_enabled and bool(names) and all(
                (f := self.resource_flavors.get(n)) is not None
                and f.topology_name and n in self.tas_flavors
                for n in names))
        return self._tas_only

    # ------------------------------------------------------------------

    def assign(self, counts: Optional[list[int]] = None) -> Assignment:
        """reference flavorassigner.go:367 Assign."""
        last = self.wl.last_assignment
        if last is not None and self.cq.allocatable_generation > last.cluster_queue_generation:
            self.wl.last_assignment = None  # outdated resume state
        return self._assign_flavors(counts)

    def _assign_flavors(self, counts: Optional[list[int]]) -> Assignment:
        if counts is None:
            requests = self.wl.total_requests
        else:
            requests = [psr.scaled_to(c)
                        for psr, c in zip(self.wl.total_requests, counts)]

        assignment = Assignment()
        assignment.last_state.cluster_queue_generation = self.cq.allocatable_generation

        for ps_idx, psr in enumerate(requests):
            reqs = Requests(psr.requests)
            if rg_by_resource(self.cq, "pods") is not None:
                reqs["pods"] = psr.count
            else:
                # implicit pods resource only participates when the CQ
                # covers it (reference flavorassigner.go:226)
                reqs.pop("pods", None)
            ps_result = PodSetAssignmentResult(
                name=psr.name, requests=reqs, count=psr.count)
            for res in sorted(reqs):
                if res in ps_result.flavors:
                    continue  # same resource group already assigned
                flavors, reasons, error = self._find_flavor_for_podset_resource(
                    ps_idx, reqs, res, assignment.usage)
                ps_result.reasons.extend(reasons)
                if error is not None or not flavors:
                    ps_result.flavors = {}
                    ps_result.error = error
                    break
                ps_result.flavors.update(flavors)
            self._append(assignment, reqs, ps_result)
            if ps_result.error is not None or (reqs and not ps_result.flavors):
                return assignment

        if assignment.representative_mode() == Mode.NO_FIT:
            return assignment

        if self.tas_enabled:
            self._apply_tas(assignment, requests)
        return assignment

    def _append(self, assignment: Assignment, reqs: Requests,
                ps_result: PodSetAssignmentResult) -> None:
        """reference flavorassigner.go:480 Assignment.append."""
        flavor_idx: dict[str, int] = {}
        assignment.pod_sets.append(ps_result)
        for res, fa in ps_result.flavors.items():
            if fa.borrow:
                assignment.borrowing = True
            fr = FlavorResource(fa.name, res)
            assignment.usage[fr] = assignment.usage.get(fr, 0) + reqs.get(res, 0)
            flavor_idx[res] = fa.tried_flavor_idx
        assignment.last_state.last_tried_flavor_idx.append(flavor_idx)

    # ------------------------------------------------------------------

    def _find_flavor_for_podset_resource(
            self, ps_idx: int, requests: Requests, res_name: str,
            assignment_usage: FlavorResourceQuantities,
    ) -> tuple[dict[str, FlavorAssignmentDecision], list[str], Optional[str]]:
        """reference flavorassigner.go:499."""
        rg = rg_by_resource(self.cq, res_name)
        if rg is None:
            return {}, [f"resource {res_name} unavailable in ClusterQueue"], None

        reqs = Requests({r: v for r, v in requests.items()
                         if r in rg.covered_resources})
        pod_set = self.wl.obj.pod_sets[ps_idx] if ps_idx < len(self.wl.obj.pod_sets) else PodSet()
        reasons: list[str] = []

        allowed_keys = {k for fq in rg.flavors
                        for k in self.resource_flavors.get(fq.name, ResourceFlavor(fq.name)).node_labels}

        best: dict[str, FlavorAssignmentDecision] = {}
        best_mode = GranularMode.NO_FIT
        attempted_idx = -1
        last = self.wl.last_assignment
        idx = last.next_flavor_to_try(ps_idx, res_name) if last is not None else 0

        flavor_names = [fq.name for fq in rg.flavors]
        while idx < len(flavor_names):
            attempted_idx = idx
            f_name = flavor_names[idx]
            flavor = self.resource_flavors.get(f_name)
            if flavor is None:
                reasons.append(f"flavor {f_name} not found")
                idx += 1
                continue
            if self.tas_enabled:
                msg = self._check_tas_match(pod_set, flavor)
                if msg is not None:
                    reasons.append(msg)
                    idx += 1
                    continue
            tolerations = list(pod_set.tolerations) + list(flavor.tolerations)
            if not taints_tolerated(flavor.node_taints, tolerations):
                reasons.append(f"untolerated taint in flavor {f_name}")
                idx += 1
                continue
            if not self._flavor_matches_affinity(pod_set, flavor, allowed_keys):
                reasons.append(f"flavor {f_name} doesn't match node affinity")
                idx += 1
                continue

            needs_borrowing = False
            assignments: dict[str, FlavorAssignmentDecision] = {}
            representative = GranularMode.FIT
            for r_name in sorted(reqs):
                val = reqs[r_name]
                fr = FlavorResource(f_name, r_name)
                mode, borrow, reason = self._fits_resource_quota(
                    fr, val + assignment_usage.get(fr, 0))
                if reason:
                    reasons.append(reason)
                if mode < representative:
                    representative = mode
                needs_borrowing = needs_borrowing or borrow
                if representative == GranularMode.NO_FIT:
                    break
                assignments[r_name] = FlavorAssignmentDecision(
                    name=f_name, mode=mode.public(), borrow=borrow)

            if self.flavor_fungibility_enabled:
                if not self._should_try_next_flavor(representative, needs_borrowing):
                    best = assignments
                    best_mode = representative
                    break
                if representative > best_mode:
                    best = assignments
                    best_mode = representative
            else:
                if representative > best_mode:
                    best = assignments
                    best_mode = representative
                    if best_mode == GranularMode.FIT:
                        return best, [], None
            idx += 1

        if self.flavor_fungibility_enabled:
            for fa in best.values():
                fa.tried_flavor_idx = (-1 if attempted_idx == len(flavor_names) - 1
                                       else attempted_idx)
            if best_mode == GranularMode.FIT:
                return best, [], None
        return best, reasons, None

    def _should_try_next_flavor(self, mode: GranularMode,
                                needs_borrowing: bool) -> bool:
        """reference flavorassigner.go:620 shouldTryNextFlavor."""
        ff = self.cq.flavor_fungibility
        if mode.is_preempt and ff.when_can_preempt == FlavorFungibilityPolicy.PREEMPT:
            if not needs_borrowing or ff.when_can_borrow == FlavorFungibilityPolicy.BORROW:
                return False
        if mode == GranularMode.FIT and needs_borrowing \
                and ff.when_can_borrow == FlavorFungibilityPolicy.BORROW:
            return False
        if mode == GranularMode.FIT and not needs_borrowing:
            return False
        return True

    def _flavor_matches_affinity(self, pod_set: PodSet, flavor: ResourceFlavor,
                                 allowed_keys: set[str]) -> bool:
        """reference flavorSelector (flavorassigner.go:640): only selector
        keys present on flavors in the group are enforced."""
        for key, want in pod_set.node_selector.items():
            if key in allowed_keys and flavor.node_labels.get(key) != want:
                return False
        for key, values in pod_set.required_node_affinity.items():
            if key in allowed_keys and flavor.node_labels.get(key) not in values:
                return False
        return True

    def _check_tas_match(self, pod_set: PodSet,
                         flavor: ResourceFlavor) -> Optional[str]:
        """reference tas_flavorassigner.go:95
        checkPodSetAndFlavorMatchForTAS."""
        req = pod_set.topology_request
        if req is not None:
            if not flavor.topology_name:
                return (f"Flavor {flavor.name} does not support "
                        f"TopologyAwareScheduling")
            snap = self.tas_flavors.get(flavor.name)
            if snap is None:
                return (f"Flavor {flavor.name} information missing in "
                        f"TAS cache")
            for level in (req.required, req.preferred):
                if level is not None and level not in snap.levels:
                    return (f"Flavor {flavor.name} does not contain the "
                            f"requested level")
            return None
        if self._is_tas_only():
            return None   # TAS implied (unconstrained) on a TAS-only CQ
        if flavor.topology_name:
            return (f"Flavor {flavor.name} supports only "
                    f"TopologyAwareScheduling")
        return None

    def _fits_resource_quota(self, fr: FlavorResource, val: int
                             ) -> tuple[GranularMode, bool, Optional[str]]:
        """reference flavorassigner.go:692 fitsResourceQuota."""
        cq = self.cq
        borrow = cq.borrowing_with(fr, val) and cq.has_parent()
        available = cq.available(fr)
        max_capacity = cq.potential_available(fr)

        if val > max_capacity:
            return (GranularMode.NO_FIT, False,
                    f"insufficient quota for {fr.resource} in flavor {fr.flavor}, "
                    f"request > maximum capacity ({val} > {max_capacity})")
        if val <= available:
            return GranularMode.FIT, borrow, None

        quota = cq.resource_node.quotas.get(fr)
        nominal = quota.nominal if quota else 0
        mode = GranularMode.NO_FIT
        if val <= nominal:
            mode = GranularMode.PREEMPT
            if self.oracle.is_reclaim_possible(cq, self.wl, fr, val):
                mode = GranularMode.RECLAIM
        elif self._can_preempt_while_borrowing():
            mode = GranularMode.PREEMPT
        return (mode, borrow,
                f"insufficient unused quota for {fr.resource} in flavor "
                f"{fr.flavor}, {val - available} more needed")

    def _can_preempt_while_borrowing(self) -> bool:
        """reference flavorassigner.go:744."""
        p = self.cq.preemption
        return (p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER
                or (self.enable_fair_sharing
                    and p.reclaim_within_cohort != ReclaimWithinCohort.NEVER))

    # ------------------------------------------------------------------
    # TAS hook — reference flavorassigner.go:438-465
    # ------------------------------------------------------------------

    def _apply_tas(self, assignment: Assignment,
                   requests: list[PodSetResources]) -> None:
        if (not any(psr.topology_request is not None for psr in requests)
                and not self._is_tas_only()):
            return
        if assignment.representative_mode() == Mode.FIT:
            ok = self._find_tas(assignment, requests, simulate_empty=False)
            if not ok:
                assignment.set_representative_mode(Mode.PREEMPT)
        if assignment.representative_mode() == Mode.PREEMPT:
            if not self._find_tas(assignment, requests, simulate_empty=True,
                                  record=False):
                assignment.set_representative_mode(Mode.NO_FIT)

    def _find_tas(self, assignment: Assignment,
                  requests: list[PodSetResources],
                  simulate_empty: bool, record: bool = True) -> bool:
        implied = self._is_tas_only()
        assumed: dict[str, dict[tuple, dict[str, int]]] = {}
        for psr, ps_result in zip(requests, assignment.pod_sets):
            request = psr.topology_request
            if request is None:
                if not implied:
                    continue
                # TAS-only CQ: implied unconstrained placement
                # (tas_flavorassigner.go:126 isTASImplied)
                request = PodSetTopologyRequest(unconstrained=True)
            flavor_names = {fa.name for fa in ps_result.flavors.values()}
            if not flavor_names:
                continue
            f_name = sorted(flavor_names)[0]
            snap = self.tas_flavors.get(f_name)
            if snap is None:
                ps_result.reasons.append(
                    f"no topology information for flavor {f_name}")
                return False
            per_pod = ({r: v // max(1, psr.count)
                        for r, v in psr.requests.items()})
            tas_assignment, reason = snap.find_topology_assignment(
                psr.count, per_pod, request,
                assumed=None if simulate_empty else assumed.get(f_name))
            if tas_assignment is None:
                ps_result.reasons.append(reason)
                return False
            if record:
                ps_result.topology_assignment = tas_assignment
                per_flavor = assumed.setdefault(f_name, {})
                for dom in tas_assignment.domains:
                    dom_id = tuple(dom.values)
                    slot = per_flavor.setdefault(dom_id, {})
                    for r, v in per_pod.items():
                        slot[r] = slot.get(r, 0) + v * dom.count
        return True


# ---------------------------------------------------------------------------
# Partial admission (reference podset_reducer.go, KEP 420)
# ---------------------------------------------------------------------------

class PodSetReducer:
    """Binary search over reduced pod counts (reference podset_reducer.go:37)."""

    def __init__(self, pod_sets: list[PodSet],
                 fits: Callable[[list[int]], tuple[object, bool]]):
        self.pod_sets = pod_sets
        self.fits = fits
        self.full_counts = [ps.count for ps in pod_sets]
        self.deltas = [ps.count - (ps.min_count if ps.min_count is not None else ps.count)
                       for ps in pod_sets]
        self.total_delta = sum(self.deltas)

    def _counts_for(self, up: int) -> list[int]:
        return [full - (d * up) // self.total_delta
                for full, d in zip(self.full_counts, self.deltas)]

    def search(self) -> tuple[object, bool]:
        """Find the largest counts that fit (smallest reduction index)."""
        if self.total_delta == 0:
            return None, False
        last_good = None
        last_good_idx = -1
        lo, hi = 0, self.total_delta  # search smallest i in [0, totalDelta] that fits
        while lo <= hi:
            mid = (lo + hi) // 2
            result, ok = self.fits(self._counts_for(mid))
            if ok:
                last_good, last_good_idx = result, mid
                hi = mid - 1
            else:
                lo = mid + 1
        return last_good, last_good_idx == lo and last_good is not None
