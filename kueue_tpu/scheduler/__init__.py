from .flavorassigner import (  # noqa: F401
    Assignment,
    AssignmentClusterQueueState,
    FlavorAssigner,
    Mode,
    PodSetReducer,
)
from .preemption import Preemptor, PreemptionOracle, Target  # noqa: F401
from .scheduler import CycleStats, Entry, EntryStatus, Scheduler  # noqa: F401
