"""Fair-sharing preemption ordering over the cohort tree.

Capability parity with reference pkg/scheduler/preemption/fairsharing/
(ordering.go, target.go, strategy.go, least_common_ancestor.go): a
tournament that repeatedly descends from the root cohort into the child
with the highest DominantResourceShare to pick the next preemption-target
ClusterQueue, with almost-LCA share comparisons for the S2 rules.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..cache.state import CohortState, CQState, dominant_resource_share
from ..workload import Info

# Strategy signature: (preemptor_new_share, target_old_share, target_new_share) -> bool


def less_than_or_equal_to_final_share(preemptor_new: int, _old: int, target_new: int) -> bool:
    """Rule S2-a (reference strategy.go)."""
    return preemptor_new <= target_new


def less_than_initial_share(preemptor_new: int, target_old: int, _new: int) -> bool:
    """Rule S2-b (reference strategy.go)."""
    return preemptor_new < target_old


DEFAULT_STRATEGIES = (less_than_or_equal_to_final_share, less_than_initial_share)


def parse_strategies(names: list[str] | None):
    """reference preemption.go:353 parseStrategies."""
    if not names:
        return list(DEFAULT_STRATEGIES)
    mapping = {
        "LessThanOrEqualToFinalShare": less_than_or_equal_to_final_share,
        "LessThanInitialShare": less_than_initial_share,
    }
    return [mapping[n] for n in names]


def _drs(node) -> int:
    return dominant_resource_share(node)[0]


class TargetClusterQueue:
    """reference fairsharing/target.go."""

    def __init__(self, ordering: "TargetClusterQueueOrdering", cq: CQState):
        self.ordering = ordering
        self.target_cq = cq

    def in_cluster_queue_preemption(self) -> bool:
        return self.target_cq is self.ordering.preemptor_cq

    def has_workload(self) -> bool:
        return bool(self.ordering.cq_to_targets.get(self.target_cq.name))

    def pop_workload(self) -> Info:
        lst = self.ordering.cq_to_targets[self.target_cq.name]
        head = lst.pop(0)
        return head

    # -- almost-LCA shares (reference least_common_ancestor.go) --

    def _lca(self) -> Optional[CohortState]:
        cohort = self.target_cq.parent
        while cohort is not None:
            if cohort in self.ordering.preemptor_ancestors:
                return cohort
            cohort = cohort.parent
        return None

    @staticmethod
    def _almost_lca(cq: CQState, lca: CohortState):
        if cq.parent is lca:
            return cq
        cohort = cq.parent
        while cohort is not None and cohort.parent is not lca:
            cohort = cohort.parent
        return cohort

    def compute_shares(self) -> tuple[int, int]:
        """(preemptor almost-LCA DRS, target almost-LCA DRS)."""
        lca = self._lca()
        pre = self._almost_lca(self.ordering.preemptor_cq, lca)
        tgt = self._almost_lca(self.target_cq, lca)
        return _drs(pre), _drs(tgt)

    def compute_target_share_after_removal(self, wl: Info) -> int:
        lca = self._lca()
        tgt = self._almost_lca(self.target_cq, lca)
        revert = self.target_cq.simulate_usage_removal(wl.usage())
        drs = _drs(tgt)
        revert()
        return drs


class TargetClusterQueueOrdering:
    """reference fairsharing/ordering.go:43."""

    def __init__(self, preemptor_cq: CQState, candidates: list[Info],
                 snapshot_cqs: dict[str, CQState]):
        self.preemptor_cq = preemptor_cq
        self.snapshot_cqs = snapshot_cqs
        self.preemptor_ancestors: set = set()
        cohort = preemptor_cq.parent
        while cohort is not None:
            self.preemptor_ancestors.add(cohort)
            cohort = cohort.parent
        self.cq_to_targets: dict[str, list[Info]] = {}
        for cand in candidates:
            self.cq_to_targets.setdefault(cand.cluster_queue, []).append(cand)
        self.pruned_cqs: set[int] = set()
        self.pruned_cohorts: set[int] = set()

    def drop_queue(self, tcq: TargetClusterQueue) -> None:
        self.pruned_cqs.add(id(tcq.target_cq))

    def _has_workload(self, cq: CQState) -> bool:
        return bool(self.cq_to_targets.get(cq.name))

    def iterate(self) -> Iterator[TargetClusterQueue]:
        if self.preemptor_cq.parent is None:
            tcq = TargetClusterQueue(self, self.preemptor_cq)
            while tcq.has_workload():
                yield tcq
            return
        root = self.preemptor_cq.parent.root()
        while id(root) not in self.pruned_cohorts:
            tcq = self._next_target(root)
            if tcq is None:
                continue
            yield tcq

    def _next_target(self, cohort: CohortState) -> Optional[TargetClusterQueue]:
        highest_cq, highest_cq_drs = None, -1
        for cq in cohort.child_cqs:
            if id(cq) in self.pruned_cqs:
                continue
            drs = _drs(cq)
            if (drs == 0 and cq is not self.preemptor_cq) or not self._has_workload(cq):
                self.pruned_cqs.add(id(cq))
            elif drs >= highest_cq_drs:
                highest_cq_drs = drs
                highest_cq = cq
        highest_cohort, highest_cohort_drs = None, -1
        for child in cohort.child_cohorts:
            if id(child) in self.pruned_cohorts:
                continue
            drs = _drs(child)
            if drs == 0 and child not in self.preemptor_ancestors:
                self.pruned_cohorts.add(id(child))
            elif drs >= highest_cohort_drs:
                highest_cohort_drs = drs
                highest_cohort = child
        if highest_cohort is None and highest_cq is None:
            self.pruned_cohorts.add(id(cohort))
            return None
        if highest_cohort is not None and highest_cohort_drs >= highest_cq_drs:
            return self._next_target(highest_cohort)
        return TargetClusterQueue(self, highest_cq)
