"""Metrics-doc registry: every emitted ``kueue_*`` series is documented.

The single source of truth is ``metrics._SERIES_DEFS`` (name, type,
labels, help) — the table ``Registry.render()`` uses for ``# HELP`` /
``# TYPE`` exposition.  This pass proves, statically, that the table
and reality cannot drift (mirroring the env-flags check):

- ``unregistered-series``   a full ``kueue_*`` string literal in
                            ``metrics.py`` or ``kueue_tpu/obs/`` that
                            names no registered series (a series can be
                            emitted only through a literal name, so an
                            undeclared emission is always visible here)
- ``dynamic-series-name``   a ``"kueue_" + ...`` concatenation or
                            f-string in ``metrics.py`` — dynamic names
                            would blind this pass, so they are banned
                            outright (build a literal dict instead)
- ``readme-missing-series`` registered series absent from the README
                            "## Metrics" table
- ``readme-unknown-series`` README row naming an unregistered series
- ``readme-missing-table``  no "## Metrics" section at all
- ``registry-unparseable``  ``_SERIES_DEFS`` missing or not a literal
                            list of tuples
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, ParsedFile

RULE = "metrics-doc"

_SERIES_RE = re.compile(r"^kueue_[a-z0-9_]+$")
_README_ROW_RE = re.compile(r"^\|\s*`(kueue_[a-z0-9_]+)`", re.MULTILINE)
_REGISTRY_FILE = "kueue_tpu/metrics.py"
#: Files whose kueue_* literals must name registered series: the
#: registry implementation itself plus the obs plane (the only other
#: module that emits into the registry with literal series names).
_SCAN_PREFIXES = ("kueue_tpu/metrics.py", "kueue_tpu/obs/")


def _registry_names(pf: ParsedFile) -> tuple[set, Finding | None]:
    """Series names from the ``_SERIES_DEFS`` literal, or a finding."""
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "_SERIES_DEFS" not in targets:
            continue
        if not isinstance(node.value, (ast.List, ast.Tuple)):
            break
        names = set()
        for el in node.value.elts:
            if (isinstance(el, ast.Tuple) and el.elts
                    and isinstance(el.elts[0], ast.Constant)
                    and isinstance(el.elts[0].value, str)):
                names.add(el.elts[0].value)
            else:
                return set(), Finding(
                    RULE, "registry-unparseable", pf.path, el.lineno, "",
                    "_SERIES_DEFS entry is not a literal tuple with a "
                    "string name first")
        return names, None
    return set(), Finding(
        RULE, "registry-unparseable", pf.path, 1, "",
        "metrics.py has no literal _SERIES_DEFS list")


def _dynamic_name(node: ast.AST):
    """lineno when this expression builds a kueue_* name dynamically."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = node.left
        if (isinstance(left, ast.Constant) and isinstance(left.value, str)
                and left.value.startswith("kueue_")):
            return node.lineno
    if isinstance(node, ast.JoinedStr):
        for part in node.values:
            if (isinstance(part, ast.Constant)
                    and isinstance(part.value, str)
                    and part.value.startswith("kueue_")):
                return node.lineno
    return None


def run(files: list[ParsedFile], ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    registry_pf = next(
        (pf for pf in files if pf.path.endswith(_REGISTRY_FILE)), None)
    if registry_pf is None:
        src = ctx.text(_REGISTRY_FILE)
        if src is not None:
            registry_pf = ParsedFile.from_source(_REGISTRY_FILE, src)
    if registry_pf is None:
        return out  # nothing to check against (fixture run)
    registry, problem = _registry_names(registry_pf)
    if problem is not None:
        return [problem]

    for pf in files:
        if not pf.path.startswith(_SCAN_PREFIXES):
            continue
        for node in ast.walk(pf.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _SERIES_RE.match(node.value)
                    and node.value not in registry):
                out.append(Finding(
                    RULE, "unregistered-series", pf.path, node.lineno,
                    node.value,
                    f"`{node.value}` is not declared in "
                    "metrics._SERIES_DEFS"))
            if pf.path.endswith(_REGISTRY_FILE):
                dyn = _dynamic_name(node)
                if dyn is not None:
                    out.append(Finding(
                        RULE, "dynamic-series-name", pf.path, dyn, "",
                        "series name built dynamically — use a literal "
                        "name (or a literal dict) so this pass can see "
                        "every emitted series"))

    readme = ctx.text("README.md")
    if readme is None:
        return out
    if "## Metrics" not in readme:
        out.append(Finding(RULE, "readme-missing-table", "README.md", 1,
                           "", "README has no \"## Metrics\" section"))
        return out
    documented = set(_README_ROW_RE.findall(readme))
    for name in sorted(registry - documented):
        out.append(Finding(RULE, "readme-missing-series", "README.md", 1,
                           name,
                           f"registered series `{name}` is missing from "
                           "the README metrics table"))
    for name in sorted(documented - registry):
        out.append(Finding(RULE, "readme-unknown-series", "README.md", 1,
                           name,
                           f"README documents `{name}` but it is not in "
                           "metrics._SERIES_DEFS"))
    return out
