"""Env-flag registry: every ``KUEUE_TPU_*`` read is declared.

The single source of truth is ``features.ENV_FLAGS`` (name, default,
type, doc).  Reads go through ``features.env_value``/``env_int``; the
README "Environment flags" table is generated from the same registry
and checked here, so docs cannot drift from code.

- ``ad-hoc-env-read``     ``os.environ.get/[...]``/``os.getenv`` of a
                          ``KUEUE_TPU_*`` name outside features.py
                          (writes — ``environ[...] = ``, ``setdefault``,
                          ``pop`` — are fine: harnesses configure
                          children through the environment)
- ``unregistered-flag``   a ``KUEUE_TPU_*`` string literal that names
                          no registered flag (typo or undeclared knob)
- ``readme-missing-flag`` registered flag absent from the README table
- ``readme-unknown-flag`` README row naming an unregistered flag
- ``readme-missing-table``no "## Environment flags" section at all
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, ParsedFile, dotted

RULE = "env-flags"

_PREFIX = "KUEUE_TPU_"
_FLAG_RE = re.compile(r"^KUEUE_TPU_[A-Z0-9_]+$")
_README_ROW_RE = re.compile(r"^\|\s*`(KUEUE_TPU_[A-Z0-9_]+)`", re.MULTILINE)
_REGISTRY_FILE = "kueue_tpu/features.py"


def _registry(ctx: Context) -> set[str]:
    if ctx.env_flags is not None:
        return set(ctx.env_flags)
    from ..features import ENV_FLAGS
    return set(ENV_FLAGS)


def _os_aliases(tree: ast.Module) -> set[str]:
    """Names the ``os`` module is bound to in this file (``os``,
    ``import os as _os``, ...)."""
    out = {"os"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "os":
                    out.add(a.asname or "os")
    return out


def _env_read(node: ast.AST, os_names: set[str]):
    """lineno if this node reads the environment; the flag literal (or
    None for dynamic names) is returned alongside."""
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        parts = d.split(".") if d else []
        is_get = (
            (len(parts) == 3 and parts[0] in os_names
             and parts[1:] == ["environ", "get"])
            or parts == ["environ", "get"]
            or (len(parts) == 2 and parts[0] in os_names
                and parts[1] == "getenv")
            or parts == ["getenv"])
        if is_get and node.args:
            a = node.args[0]
            lit = a.value if isinstance(a, ast.Constant) and \
                isinstance(a.value, str) else None
            return node.lineno, lit
    elif isinstance(node, ast.Subscript) and \
            isinstance(node.ctx, ast.Load):
        d = dotted(node.value)
        parts = d.split(".") if d else []
        if parts == ["environ"] or (len(parts) == 2
                                    and parts[0] in os_names
                                    and parts[1] == "environ"):
            s = node.slice
            lit = s.value if isinstance(s, ast.Constant) and \
                isinstance(s.value, str) else None
            return node.lineno, lit
    return None


def run(files: list[ParsedFile], ctx: Context) -> list[Finding]:
    registry = _registry(ctx)
    out: list[Finding] = []

    for pf in files:
        is_registry_impl = pf.path.endswith(_REGISTRY_FILE)
        os_names = _os_aliases(pf.tree)
        for node in ast.walk(pf.tree):
            read = _env_read(node, os_names)
            if read is not None and not is_registry_impl:
                line, lit = read
                if lit is not None and lit.startswith(_PREFIX):
                    out.append(Finding(
                        RULE, "ad-hoc-env-read", pf.path, line, lit,
                        f"direct environment read of `{lit}` — go "
                        "through features.env_value/env_int"))
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _FLAG_RE.match(node.value) \
                    and node.value not in registry:
                out.append(Finding(
                    RULE, "unregistered-flag", pf.path, node.lineno,
                    node.value,
                    f"`{node.value}` is not declared in "
                    "features.ENV_FLAGS"))

    readme = ctx.text("README.md")
    if readme is None:
        return out
    if "## Environment flags" not in readme:
        out.append(Finding(RULE, "readme-missing-table", "README.md", 1,
                           "", "README has no \"## Environment flags\" "
                           "section"))
        return out
    documented = set(_README_ROW_RE.findall(readme))
    for name in sorted(registry - documented):
        out.append(Finding(RULE, "readme-missing-flag", "README.md", 1,
                           name,
                           f"registered flag `{name}` is missing from "
                           "the README flag table"))
    for name in sorted(documented - registry):
        out.append(Finding(RULE, "readme-unknown-flag", "README.md", 1,
                           name,
                           f"README documents `{name}` but it is not in "
                           "features.ENV_FLAGS"))
    return out
