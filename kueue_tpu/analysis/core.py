"""Findings, file collection, pass protocol, baseline and runner.

Design notes:

- A :class:`Finding`'s baseline key deliberately excludes the line
  number — grandfathered entries survive unrelated edits to the same
  file and go stale only when the underlying violation moves or dies.
- A baseline entry suppresses *every* finding with its key (the key
  includes rule, code, file and enclosing symbol, so collisions mean
  "the same kind of violation in the same function" — close enough to
  one decision).  Stale entries (no matching finding) are themselves
  an error: the baseline may only shrink.
- Nothing in this package imports jax or numpy.  Passes that reason
  about dtypes do it over strings; the whole suite must stay cheap
  enough to run as a tier-1 test.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

#: Directory basenames never worth parsing.
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".pytest_cache"}


@dataclass(frozen=True)
class Finding:
    rule: str       # pass name, e.g. "wal-order"
    code: str       # check within the pass, e.g. "mutation-before-append"
    path: str       # repo-relative posix path
    line: int
    symbol: str     # enclosing function qualname ("" = module level)
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.code}:{self.path}:{self.symbol}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}/{self.code}{sym}: {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "code": self.code, "path": self.path,
                "line": self.line, "symbol": self.symbol,
                "message": self.message, "key": self.key}


@dataclass
class ParsedFile:
    path: str                    # repo-relative posix path
    source: str
    tree: ast.Module

    @classmethod
    def from_source(cls, path: str, source: str) -> "ParsedFile":
        return cls(path=path, source=source,
                   tree=ast.parse(source, filename=path))


class Context:
    """What a pass may see beyond the scanned file list.

    ``extra_sources`` (path -> source text) shadows the filesystem so
    fixture tests can feed synthetic READMEs / test files; ``env_flags``
    likewise overrides the live registry."""

    def __init__(self, root: str, extra_sources: Optional[dict] = None,
                 env_flags: Optional[dict] = None):
        self.root = root
        self.extra_sources = dict(extra_sources or {})
        self.env_flags = env_flags

    def text(self, relpath: str) -> Optional[str]:
        if relpath in self.extra_sources:
            return self.extra_sources[relpath]
        full = os.path.join(self.root, relpath)
        if not os.path.isfile(full):
            return None
        with open(full, encoding="utf-8") as fh:
            return fh.read()

    def parse_dir(self, reldir: str) -> list[ParsedFile]:
        """Parse ``reldir/*.py`` (non-recursive), extras included."""
        out = []
        seen = set()
        prefix = reldir.rstrip("/") + "/"
        for path, src in self.extra_sources.items():
            if path.startswith(prefix) and path.endswith(".py"):
                out.append(ParsedFile.from_source(path, src))
                seen.add(path)
        full = os.path.join(self.root, reldir)
        if os.path.isdir(full):
            for name in sorted(os.listdir(full)):
                rel = prefix + name
                if name.endswith(".py") and rel not in seen:
                    text = self.text(rel)
                    if text is not None:
                        out.append(ParsedFile.from_source(rel, text))
        return out


@dataclass
class Pass:
    name: str
    doc: str
    run: Callable[[list[ParsedFile], Context], list[Finding]]


def collect_files(root: str, paths: Iterable[str]) -> list[ParsedFile]:
    """Parse every ``*.py`` under ``paths`` (files or directories,
    repo-relative to ``root``), sorted for determinism."""
    found: list[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full):
            found.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, name), root)
                    found.append(rel.replace(os.sep, "/"))
    out = []
    for rel in sorted(set(found)):
        with open(os.path.join(root, rel), encoding="utf-8") as fh:
            out.append(ParsedFile.from_source(rel, fh.read()))
    return out


def all_passes() -> list[Pass]:
    from . import (chaos_sites, dtypes, env_flags, metrics_doc, purity,
                   wal_order)
    return [
        Pass("purity", "no host effects reachable from jit/shard_map",
             purity.run),
        Pass("dtype", "plane creations match the declared schema",
             dtypes.run),
        Pass("wal-order", "journal append dominates the store mutation",
             wal_order.run),
        Pass("chaos-sites", "doc / code / scenario site sets agree",
             chaos_sites.run),
        Pass("env-flags", "KUEUE_TPU_* reads go through the registry",
             env_flags.run),
        Pass("metrics-doc", "every emitted kueue_* series is documented",
             metrics_doc.run),
    ]


def run_all(root: str, paths: Optional[Iterable[str]] = None,
            passes: Optional[list[Pass]] = None,
            ctx: Optional[Context] = None) -> list[Finding]:
    if paths is None:
        paths = ("kueue_tpu", "scripts", "bench.py")
    files = collect_files(root, paths)
    ctx = ctx or Context(root)
    findings: list[Finding] = []
    for p in (passes if passes is not None else all_passes()):
        findings.extend(p.run(files, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.code))
    return findings


# --------------------------------------------------------------------------
# Baseline
# --------------------------------------------------------------------------

def load_baseline(path: str = BASELINE_PATH) -> dict:
    if not os.path.isfile(path):
        return {"first_full_run_findings": 0, "entries": []}
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def apply_baseline(findings: list[Finding], baseline: dict):
    """Split findings into (unsuppressed, suppressed) and report stale
    baseline entries (entries matching nothing — they must be deleted,
    which is how "the baseline only shrinks" is enforced)."""
    keys = {e["key"] if isinstance(e, dict) else e
            for e in baseline.get("entries", [])}
    unsuppressed = [f for f in findings if f.key not in keys]
    suppressed = [f for f in findings if f.key in keys]
    live = {f.key for f in suppressed}
    stale = sorted(keys - live)
    return unsuppressed, suppressed, stale


# --------------------------------------------------------------------------
# Small AST helpers shared by the passes
# --------------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass
class FuncInfo:
    qualname: str
    node: ast.AST            # FunctionDef | AsyncFunctionDef | Lambda
    parent: Optional[str] = None


def index_functions(tree: ast.Module) -> dict[str, FuncInfo]:
    """qualname -> FuncInfo for every (nested) def in the module."""
    out: dict[str, FuncInfo] = {}

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}" if prefix else child.name
                out[qn] = FuncInfo(qn, child, prefix.rstrip(".") or None)
                walk(child, qn + ".")
            elif isinstance(child, ast.ClassDef):
                walk(child, (prefix + child.name + ".") if prefix
                     else child.name + ".")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out
