"""AST-level invariant lint for the kueue-tpu stack.

The solver stack rests on a handful of invariants that, until this
package, were only enforced dynamically (a 2-hour soak failure instead
of a review-time error):

- **kernel purity** — code traced by ``jax.jit``/``shard_map`` must be
  a pure function of its tensors (``analysis.purity``);
- **dtype discipline** — packed planes carry declared dtypes; an
  accidental int64/float64 default on the transfer boundary defeats
  the tightening contract (``analysis.dtypes``);
- **WAL ordering** — every store mutation in the driver is journaled
  first, and every ``wal.*`` chaos point sits between append and
  mutation (``analysis.wal_order``);
- **chaos-site registry** — documented, threaded and scenario-covered
  injection sites agree exactly (``analysis.chaos_sites``);
- **env-flag registry** — every ``KUEUE_TPU_*`` read goes through the
  ``features.ENV_FLAGS`` table and appears in the README flag table
  (``analysis.env_flags``);
- **metrics-doc registry** — every ``kueue_*`` series emitted into the
  metrics registry is declared in ``metrics._SERIES_DEFS`` and
  documented in the README metrics table, both directions
  (``analysis.metrics_doc``).

``scripts/lint_invariants.py`` is the CLI; ``run_all`` is the API.
Grandfathered findings live in ``baseline.json`` next to this file —
the baseline may only shrink (tests/test_static_analysis.py enforces
both the zero-unsuppressed-findings and the shrink-only invariant).
Everything here is stdlib-``ast`` only: no jax, no numpy, so the lint
stays fast enough for tier-1.
"""

from .core import (  # noqa: F401
    BASELINE_PATH,
    Context,
    Finding,
    ParsedFile,
    all_passes,
    apply_baseline,
    load_baseline,
    run_all,
)
