"""Chaos-site registry: doc, code and scenarios must agree exactly.

Three sets of injection-site names are compared:

- **documented** — the reST site table in ``chaos/injector.py``'s
  module docstring (rows opening with ````site```` markers);
- **threaded**   — literal site strings passed to ``crashpoint(...)``
  / ``hit(...)`` anywhere in the scanned code (the points that
  actually consult the injector);
- **armed**      — literal site strings passed to ``arm(...)`` in
  ``tests/*.py`` and ``scripts/*.py`` (the scenarios that exercise
  them).

Findings:

- ``undocumented-site``  threaded but missing from the docstring table
- ``unthreaded-site``    documented but no code point consults it
- ``untested-site``      documented/threaded but no scenario arms it
- ``unknown-armed-site`` a scenario arms a name no site answers to
  (a typo'd arm silently tests nothing)
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, ParsedFile

RULE = "chaos-sites"

_INJECTOR_SUFFIX = "chaos/injector.py"
_DOC_SITE_RE = re.compile(r"^``([a-z_][a-z0-9_.]*)``", re.MULTILINE)


def _literal_site_args(tree: ast.Module, attrs: tuple[str, ...]):
    """(site, lineno) for every ``*.<attr>("literal", ...)`` call."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in attrs
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            yield node.args[0].value, node.lineno


def run(files: list[ParsedFile], ctx: Context) -> list[Finding]:
    injector = next((f for f in files
                     if f.path.endswith(_INJECTOR_SUFFIX)), None)
    if injector is None:
        return []

    documented: set[str] = set()
    doc = ast.get_docstring(injector.tree) or ""
    documented.update(_DOC_SITE_RE.findall(doc))

    threaded: dict[str, tuple[str, int]] = {}
    for pf in files:
        if pf.path.endswith(_INJECTOR_SUFFIX):
            continue
        for site, line in _literal_site_args(pf.tree,
                                             ("crashpoint", "hit")):
            threaded.setdefault(site, (pf.path, line))

    armed: dict[str, tuple[str, int]] = {}
    arm_files = [pf for pf in files if pf.path.startswith("scripts/")]
    arm_files += ctx.parse_dir("tests")
    for pf in arm_files:
        for site, line in _literal_site_args(pf.tree, ("arm",)):
            armed.setdefault(site, (pf.path, line))

    out: list[Finding] = []
    known = documented | set(threaded)

    for site in sorted(set(threaded) - documented):
        path, line = threaded[site]
        out.append(Finding(RULE, "undocumented-site", path, line, site,
                           f"site `{site}` is threaded through the code "
                           "but missing from the injector.py site table"))
    for site in sorted(documented - set(threaded)):
        out.append(Finding(RULE, "unthreaded-site", injector.path, 1,
                           site,
                           f"site `{site}` is documented but no "
                           "crashpoint()/hit() call consults it"))
    for site in sorted(known - set(armed)):
        path, line = threaded.get(site, (injector.path, 1))
        out.append(Finding(RULE, "untested-site", path, line, site,
                           f"site `{site}` is never armed by any test "
                           "or soak scenario"))
    for site in sorted(set(armed) - known):
        path, line = armed[site]
        out.append(Finding(RULE, "unknown-armed-site", path, line, site,
                           f"scenario arms `{site}` but no such site "
                           "exists — the fault can never fire"))
    return out
