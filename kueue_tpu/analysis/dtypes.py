"""Dtype discipline on the transfer boundary.

``PLANE_SCHEMA`` is the declared plane registry: every packed plane the
delta-pack / streaming-pack / arena layer materializes, with its
contract dtype (as a *string* — this package never imports numpy).
The pass checks, in ``ops/packing.py``, ``ops/stream_pack.py`` and
``cache/arena.py``:

- ``dtype-less``     np/jnp array creations with no explicit dtype
                     (the silent int64/float64 default defeats
                     tightening and doubles transfer bytes)
- ``platform-dtype`` explicit bare ``int``/``float`` dtypes (width
                     depends on the platform)
- ``schema-mismatch``a literal plane name created/ensured with a dtype
                     other than its registered one
- ``unknown-plane``  a literal plane name absent from the schema
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Context, Finding, ParsedFile, dotted, index_functions

RULE = "dtype"

_SCOPE_SUFFIXES = ("ops/packing.py", "ops/stream_pack.py", "cache/arena.py")

#: plane name -> contract dtype string.  Row planes (streamed grids),
#: the per-CQ usage plane, and the int32 structure planes that
#: ``TIGHTEN_PLANES`` is allowed to narrow.
PLANE_SCHEMA: dict[str, str] = {
    # streamed row planes (_ROW_PLANES)
    "wl_req": "int32", "wl_rank": "int32", "wl_cycle_rank": "int32",
    "wl_prio": "int32", "wl_uidrank": "int32",
    "vec_ok": "bool", "elig0": "bool", "parked0": "bool",
    "resume0": "int32", "adm0": "bool", "adm_seq0": "int32",
    "adm_usage0": "int32", "adm_uses0": "bool", "death0": "int32",
    # arena extras
    "u_cq0": "int32", "keys_grid": "object",
    # cohort-forest aggregate planes (ops/aggregate.py)
    "agg_heads": "int32", "agg_rows": "int32", "agg_comp": "int32",
    "agg_comp_ts": "float64", "agg_best_prio": "int32",
    "agg_best_ts": "float64",
    # tightenable structure planes
    "parent": "int32", "node_level": "int32", "nominal_cq": "int32",
    "slot_fr": "int32", "forest_of_cq": "int32", "members": "int32",
    "cand_rows": "int32", "cand_lmem": "int32", "self_lmem": "int32",
}

#: planes tighten_arrays() may narrow — must be int32 in the schema
TIGHTENABLE = ("wl_req", "wl_cycle_rank", "wl_prio", "wl_uidrank",
               "parent", "node_level", "nominal_cq", "slot_fr",
               "forest_of_cq", "members", "cand_rows", "cand_lmem",
               "self_lmem")

_CREATORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
             "asarray": 1, "array": 1, "arange": None, "fromiter": 1,
             "frombuffer": None}


def _np_aliases(tree: ast.Module) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("numpy", "jax.numpy"):
                    out.add(a.asname or a.name.split(".")[0])
    return out


def _dtype_str(node: Optional[ast.AST]) -> Optional[str]:
    """Resolve a dtype expression to a string, or None if dynamic."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return node.id if node.id in ("bool", "object", "int",
                                      "float", "complex") else None
    d = dotted(node)
    if d and "." in d:
        tail = d.split(".")[-1]
        if tail.startswith(("int", "uint", "float", "bool", "complex")) \
                or tail in ("object_",):
            return tail.rstrip("_")
    return None


def _creation_dtype(call: ast.Call, pos: Optional[int]):
    """(dtype node or None, explicitly-given?) for a creation call."""
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value, True
    if pos is not None and len(call.args) > pos:
        return call.args[pos], True
    return None, False


def _enclosing(funcs, lineno: int) -> str:
    best = ""
    for info in funcs.values():
        n = info.node
        if n.lineno <= lineno and (getattr(n, "end_lineno", n.lineno)
                                   >= lineno):
            if len(info.qualname) > len(best):
                best = info.qualname
    return best


def run(files: list[ParsedFile], ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for pf in files:
        if not pf.path.endswith(_SCOPE_SUFFIXES):
            continue
        np_names = _np_aliases(pf.tree)
        funcs = index_functions(pf.tree)

        def emit(code, node, msg):
            out.append(Finding(RULE, code, pf.path, node.lineno,
                               _enclosing(funcs, node.lineno), msg))

        for node in ast.walk(pf.tree):
            # --- creation calls -------------------------------------
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if (d and d.split(".")[0] in np_names
                        and d.split(".")[-1] in _CREATORS
                        and len(d.split(".")) == 2):
                    fn = d.split(".")[-1]
                    dt_node, given = _creation_dtype(node, _CREATORS[fn])
                    if not given:
                        emit("dtype-less", node,
                             f"`{d}()` without an explicit dtype: the "
                             "int64/float64 default defeats tightening")
                    elif _dtype_str(dt_node) in ("int", "float"):
                        emit("platform-dtype", node,
                             f"`{d}(dtype={_dtype_str(dt_node)})`: bare "
                             "`int`/`float` width is platform-dependent")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "astype" and node.args):
                    if _dtype_str(node.args[0]) in ("int", "float"):
                        emit("platform-dtype", node,
                             "`.astype(int/float)`: width is "
                             "platform-dependent")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "ensure"):
                    # arena.ensure(name, shape, dtype, fill, ...)
                    name = None
                    if node.args:
                        c = node.args[0]
                        if isinstance(c, ast.Constant) and \
                                isinstance(c.value, str):
                            name = c.value
                    if name is not None:
                        want = PLANE_SCHEMA.get(name)
                        if want is None:
                            emit("unknown-plane", node,
                                 f"arena.ensure of undeclared plane "
                                 f"`{name}` (add it to PLANE_SCHEMA)")
                        else:
                            got = _dtype_str(node.args[2]) \
                                if len(node.args) > 2 else None
                            if got is not None and got != want:
                                emit("schema-mismatch", node,
                                     f"plane `{name}` ensured as {got}, "
                                     f"schema says {want}")
            # --- the _ROW_PLANES declaration itself -----------------
            elif (isinstance(node, ast.Assign)
                  and len(node.targets) == 1
                  and isinstance(node.targets[0], ast.Name)
                  and node.targets[0].id == "_ROW_PLANES"
                  and isinstance(node.value, ast.Dict)):
                for k, v in zip(node.value.keys, node.value.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(v, (ast.Tuple, ast.List))
                            and len(v.elts) >= 2):
                        continue
                    name = k.value
                    want = PLANE_SCHEMA.get(name)
                    got = _dtype_str(v.elts[1])
                    if want is None:
                        emit("unknown-plane", k,
                             f"row plane `{name}` not in PLANE_SCHEMA")
                    elif got is not None and got != want:
                        emit("schema-mismatch", k,
                             f"row plane `{name}` declared {got}, "
                             f"schema says {want}")
            # --- TIGHTEN_PLANES names must be tightenable int32 -----
            elif (isinstance(node, ast.Assign)
                  and len(node.targets) == 1
                  and isinstance(node.targets[0], ast.Name)
                  and node.targets[0].id == "TIGHTEN_PLANES"
                  and isinstance(node.value, (ast.Tuple, ast.List))):
                for elt in node.value.elts:
                    if not (isinstance(elt, ast.Constant)
                            and isinstance(elt.value, str)):
                        continue
                    want = PLANE_SCHEMA.get(elt.value)
                    if want is None:
                        emit("unknown-plane", elt,
                             f"TIGHTEN_PLANES entry `{elt.value}` not in "
                             "PLANE_SCHEMA")
                    elif want != "int32":
                        emit("schema-mismatch", elt,
                             f"TIGHTEN_PLANES entry `{elt.value}` is "
                             f"{want} in the schema; only int32 planes "
                             "tighten")
    return out
