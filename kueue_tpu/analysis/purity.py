"""Kernel purity: no host effects reachable from jit/shard_map entry.

Scope is deliberately *not* "all of ops/": those modules mix jitted
kernels with host-side orchestration that legitimately reads clocks and
env vars.  The pass finds jit/shard_map entry points, walks the
intra-module call graph from them, and only code reachable from a
traced entry is held to purity:

- ``wall-clock``   calls through ``time``/``datetime``
- ``stdlib-random``calls through ``random`` (or names imported from it)
- ``np-random``    ``np.random.*`` (the unseeded global generator)
- ``traced-coercion`` ``.item()`` / ``float(x)`` / ``bool(x)`` on
  non-constant arguments (host round-trip of a traced value)
- ``host-io``      ``open``/``print``/``input``, ``os.*`` calls
- ``global-mutation`` ``global`` statements, or stores through a
  module-level name (mutating trace-time state)
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Context, Finding, FuncInfo, ParsedFile, dotted, \
    index_functions

RULE = "purity"

_SCOPE_PREFIXES = ("kueue_tpu/ops/", "kueue_tpu/parallel/")


def _in_scope(path: str) -> bool:
    return path.startswith(_SCOPE_PREFIXES)


def _module_imports(tree: ast.Module):
    """(module alias -> module name, from-imported name -> module)."""
    mod_alias: dict[str, str] = {}
    from_name: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod_alias[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                from_name[a.asname or a.name] = node.module
    return mod_alias, from_name


def _is_jit_expr(node: ast.AST, from_name: dict[str, str]) -> bool:
    """True for ``jax.jit`` / bare ``jit`` imported from jax."""
    d = dotted(node)
    if d in ("jax.jit", "jax.pjit", "pjit.pjit"):
        return True
    return d in ("jit", "pjit") and from_name.get(d, "").startswith("jax")


def _is_shard_map(node: ast.AST) -> bool:
    d = dotted(node)
    return d is not None and d.split(".")[-1] == "shard_map"


def _callee_roots(node: ast.AST) -> list[str]:
    """Names a traced callable expression resolves to: a Name is itself;
    a Lambda contributes every simple name it calls."""
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Lambda):
        return [c.func.id for c in ast.walk(node.body)
                if isinstance(c, ast.Call) and isinstance(c.func, ast.Name)]
    return []


def _entry_names(tree: ast.Module, from_name: dict[str, str]) -> set[str]:
    """Simple names of functions that enter tracing: decorated defs and
    ``jit(f)`` / ``partial(jit, ...)(f)`` / ``shard_map(f, ...)`` calls."""
    roots: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _is_jit_expr(target, from_name) or _is_shard_map(target):
                    roots.add(node.name)
                elif (isinstance(dec, ast.Call)
                      and dotted(dec.func) in ("partial", "functools.partial")
                      and dec.args
                      and (_is_jit_expr(dec.args[0], from_name)
                           or _is_shard_map(dec.args[0]))):
                    roots.add(node.name)
        elif isinstance(node, ast.Call):
            fn = node.func
            if (_is_jit_expr(fn, from_name) or _is_shard_map(fn)) and node.args:
                roots.update(_callee_roots(node.args[0]))
            # partial(jax.jit, ...)(f)
            elif (isinstance(fn, ast.Call)
                  and dotted(fn.func) in ("partial", "functools.partial")
                  and fn.args
                  and (_is_jit_expr(fn.args[0], from_name)
                       or _is_shard_map(fn.args[0]))
                  and node.args):
                roots.update(_callee_roots(node.args[0]))
    return roots


def _reachable(tree: ast.Module, roots: set[str]) -> dict[str, FuncInfo]:
    """Kernel scope: defs reachable from the entry names via simple-name
    calls within this module."""
    funcs = index_functions(tree)
    by_simple: dict[str, list[FuncInfo]] = {}
    for info in funcs.values():
        by_simple.setdefault(info.qualname.split(".")[-1], []).append(info)

    seen: dict[str, FuncInfo] = {}
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        for info in by_simple.get(name, []):
            if info.qualname in seen:
                continue
            seen[info.qualname] = info
            for call in ast.walk(info.node):
                if isinstance(call, ast.Call) and isinstance(call.func,
                                                             ast.Name):
                    if call.func.id not in seen:
                        frontier.append(call.func.id)
    return seen


def _module_level_names(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _check_kernel(pf: ParsedFile, info: FuncInfo, mod_alias: dict[str, str],
                  from_name: dict[str, str], module_names: set[str],
                  out: list[Finding]):
    clock_mods = {a for a, m in mod_alias.items()
                  if m in ("time", "datetime")}
    rand_mods = {a for a, m in mod_alias.items() if m == "random"}
    rand_names = {n for n, m in from_name.items() if m == "random"}
    clock_names = {n for n, m in from_name.items()
                   if m in ("time", "datetime")}
    os_mods = {a for a, m in mod_alias.items() if m == "os"}
    np_mods = {a for a, m in mod_alias.items() if m == "numpy"}

    def emit(code: str, node: ast.AST, msg: str):
        out.append(Finding(RULE, code, pf.path, node.lineno,
                           info.qualname, msg))

    # locals of this def shadow module globals for the mutation check
    local_names = {a.arg for a in ast.walk(info.node)
                   if isinstance(a, ast.arg)}
    for n in ast.walk(info.node):
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            ts = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in ts:
                if isinstance(t, ast.Name):
                    local_names.add(t.id)

    for node in ast.walk(info.node):
        if isinstance(node, ast.Global):
            emit("global-mutation", node,
                 f"`global {', '.join(node.names)}` inside a traced "
                 "function mutates module state at trace time")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if (isinstance(base, ast.Name) and base.id in module_names
                        and base.id not in local_names):
                    emit("global-mutation", node,
                         f"store through module-level `{base.id}` from "
                         "traced code")
        elif isinstance(node, ast.Call):
            d = dotted(node.func)
            root = d.split(".")[0] if d else None
            if root in clock_mods or d in clock_names:
                emit("wall-clock", node,
                     f"wall-clock call `{d}()` in traced code")
            elif (root in rand_mods or d in rand_names):
                emit("stdlib-random", node,
                     f"stdlib random call `{d}()` in traced code")
            elif (d and root in np_mods
                  and d.split(".")[1:2] == ["random"]):
                emit("np-random", node,
                     f"unseeded `{d}()` in traced code")
            elif root in os_mods:
                emit("host-io", node,
                     f"host call `{d}()` in traced code")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                emit("traced-coercion", node,
                     "`.item()` forces a device->host round-trip of a "
                     "traced value")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("float", "bool")
                  and node.args
                  and not isinstance(node.args[0], ast.Constant)):
                emit("traced-coercion", node,
                     f"`{node.func.id}()` coercion of a (potentially "
                     "traced) value in traced code")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("open", "print", "input")):
                emit("host-io", node,
                     f"`{node.func.id}()` in traced code")


def run(files: list[ParsedFile], ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for pf in files:
        if not _in_scope(pf.path):
            continue
        mod_alias, from_name = _module_imports(pf.tree)
        roots = _entry_names(pf.tree, from_name)
        if not roots:
            continue
        module_names = _module_level_names(pf.tree)
        for info in _reachable(pf.tree, roots).values():
            _check_kernel(pf, info, mod_alias, from_name, module_names, out)
    # a nested def reachable both via its parent's subtree and by name
    # would double-report: keep the first finding per site
    seen: set[tuple] = set()
    deduped = []
    for f in out:
        site = (f.code, f.path, f.line)
        if site not in seen:
            seen.add(site)
            deduped.append(f)
    return deduped
