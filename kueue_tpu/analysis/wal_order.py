"""WAL ordering: journal append dominates the store mutation.

The crash-consistency contract (PR 4) is *journal first, mutate
second*: recovery replays the WAL tail against the durable store, so a
mutation that precedes its append is lost on a crash between the two.
Chaos sites named ``wal.<op>`` exist precisely to crash in that window,
so they must sit *between* the append and the mutation.

The pass scans ``controller/driver.py``.  A function is WAL-scoped if
it journal-appends or hits a ``wal.*`` crashpoint; inside those:

- ``mutation-before-append``  a recognized store mutation precedes its
                              matching ``_journal.<op>_op`` append
- ``unjournaled-mutation``    a recognized mutation with no matching
                              append anywhere in the function
- ``chaos-outside-window``    a ``wal.<op>`` crashpoint not strictly
                              between the append and the mutation

Module-wide, every op kind that mutates somewhere must append
somewhere (``missing-journal-kind``) — this is what still fires when a
regression deletes both the append *and* the chaos point.

Functions like ``create_workload``/``restore_workload`` also write
``self.workloads`` but are repopulated from the durable store on
recovery, not from the WAL; they are out of scope by construction
(no append, no wal.* site).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Context, Finding, ParsedFile, dotted, index_functions

RULE = "wal-order"

_SCOPE_SUFFIX = "controller/driver.py"

#: op kind -> (journal encoder name, chaos site, mutation recognizer)
_KINDS = ("admit", "evict", "requeue", "finish", "deactivate")


def _mutation_kind(node: ast.AST):
    """(kind, lineno) if this statement is a recognized store mutation."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                d = dotted(t.value)
                if d and d.split(".")[-1] == "workloads":
                    return "admit", node.lineno
            if isinstance(t, ast.Attribute) and t.attr == "active":
                return "deactivate", node.lineno
    elif isinstance(node, ast.Call):
        d = dotted(node.func)
        tail = d.split(".")[-1] if d else None
        if tail == "set_evicted_condition":
            return "evict", node.lineno
        if tail == "set_finished_condition":
            return "finish", node.lineno
        if tail == "update_requeue_state":
            return "requeue", node.lineno
    return None


def _append_kind(node: ast.AST):
    """(kind, lineno) if this is ``*.log(_journal.<kind>_op(...))``."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "log"):
        return None
    for arg in ast.walk(node):
        if isinstance(arg, ast.Call):
            d = dotted(arg.func)
            tail = d.split(".")[-1] if d else ""
            if tail.endswith("_op") and tail[:-3] in _KINDS:
                return tail[:-3], node.lineno
    return None


def _chaos_site(node: ast.AST):
    """(site, lineno) for ``*.crashpoint("wal.<op>")`` / ``.hit(...)``."""
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("crashpoint", "hit")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("wal.")):
        return node.args[0].value, node.lineno
    return None


@dataclass
class _FuncEvents:
    appends: dict = field(default_factory=dict)    # kind -> [lineno]
    chaos: dict = field(default_factory=dict)      # kind -> [lineno]
    mutations: dict = field(default_factory=dict)  # kind -> [lineno]


def _collect(node: ast.AST) -> _FuncEvents:
    ev = _FuncEvents()
    for n in ast.walk(node):
        m = _mutation_kind(n)
        if m:
            ev.mutations.setdefault(m[0], []).append(m[1])
        a = _append_kind(n)
        if a:
            ev.appends.setdefault(a[0], []).append(a[1])
        c = _chaos_site(n)
        if c:
            kind = c[0].split(".", 1)[1]
            ev.chaos.setdefault(kind, []).append(c[1])
    return ev


def run(files: list[ParsedFile], ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for pf in files:
        if not pf.path.endswith(_SCOPE_SUFFIX):
            continue
        funcs = index_functions(pf.tree)
        file_appends: set[str] = set()
        file_mutations: set[str] = set()

        for info in funcs.values():
            ev = _collect(info.node)
            file_appends.update(ev.appends)
            file_mutations.update(ev.mutations)
            if not ev.appends and not ev.chaos:
                continue  # not WAL-scoped (store repopulation paths)

            def emit(code, line, msg):
                out.append(Finding(RULE, code, pf.path, line,
                                   info.qualname, msg))

            for kind, mlines in ev.mutations.items():
                alines = ev.appends.get(kind)
                if not alines:
                    emit("unjournaled-mutation", min(mlines),
                         f"`{kind}` mutation with no "
                         f"`_journal.{kind}_op` append in this function")
                    continue
                first_append = min(alines)
                for ml in mlines:
                    if ml < first_append:
                        emit("mutation-before-append", ml,
                             f"`{kind}` mutation at line {ml} precedes "
                             f"its journal append at line {first_append}"
                             "; a crash between them loses the op")
            for kind, clines in ev.chaos.items():
                alines = ev.appends.get(kind, [])
                mlines = ev.mutations.get(kind, [])
                for cl in clines:
                    before = [a for a in alines if a < cl]
                    after = [m for m in mlines if m > cl]
                    if not before:
                        emit("chaos-outside-window", cl,
                             f"`wal.{kind}` chaos point fires before the "
                             f"`{kind}` append — it would test nothing")
                    elif mlines and not after:
                        emit("chaos-outside-window", cl,
                             f"`wal.{kind}` chaos point fires after the "
                             f"`{kind}` mutation — the crash window it "
                             "models is append-done/mutation-pending")

        for kind in sorted(file_mutations - file_appends):
            out.append(Finding(
                RULE, "missing-journal-kind", pf.path, 1, "",
                f"`{kind}` mutations exist but no function appends "
                f"`_journal.{kind}_op` — the op kind is unjournaled"))
    return out
