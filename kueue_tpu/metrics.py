"""Metrics registry: the Prometheus-series equivalent.

Capability parity with reference pkg/metrics/metrics.go:62-386 (namespace
``kueue_``): admission attempts/durations, pending/reserving/admitted
counts, quota-reserved and admission wait times, evictions/preemptions with
reason labels, per-CQ resource usage, weighted shares.  Values are plain
Python numbers; ``render()`` emits Prometheus text exposition format so the
series names stay wire-compatible.
"""

from __future__ import annotations

import math
from collections import defaultdict
from dataclasses import dataclass, field


def exponential_buckets(start: float, factor: float, count: int) -> list[float]:
    """reference metrics.go:387 generateExponentialBuckets."""
    return [start * factor**i for i in range(count)]


ATTEMPT_BUCKETS = exponential_buckets(0.001, 2, 16)  # seconds
WAIT_BUCKETS = exponential_buckets(1, 2, 14)
# open-loop admission latency (submit→admit, virtual seconds) and
# requeue-storm sizes (workloads unparked per cohort wakeup)
LATENCY_BUCKETS = exponential_buckets(0.25, 2, 18)
STORM_BUCKETS = exponential_buckets(1, 2, 16)


@dataclass
class Histogram:
    buckets: list[float]
    counts: list[int] = field(default_factory=list)
    total: float = 0.0
    n: int = 0

    def __post_init__(self):
        if not self.counts:
            self.counts = [0] * (len(self.buckets) + 1)

    def observe(self, value: float) -> None:
        self.n += 1
        self.total += value
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return 0.0
        target = math.ceil(q * self.n)
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")


class Registry:
    def __init__(self):
        self.counters: dict[tuple, float] = defaultdict(float)
        self.gauges: dict[tuple, float] = defaultdict(float)
        self.histograms: dict[tuple, Histogram] = {}

    # -- generic --

    def inc(self, name: str, labels: tuple = (), value: float = 1.0) -> None:
        self.counters[(name, *labels)] += value

    def set_gauge(self, name: str, labels: tuple, value: float) -> None:
        self.gauges[(name, *labels)] = value

    def add_gauge(self, name: str, labels: tuple, delta: float) -> None:
        self.gauges[(name, *labels)] += delta

    def observe(self, name: str, labels: tuple, value: float,
                buckets: list[float] = ATTEMPT_BUCKETS) -> None:
        key = (name, *labels)
        if key not in self.histograms:
            self.histograms[key] = Histogram(buckets=buckets)
        self.histograms[key].observe(value)

    # -- kueue series (reference metrics.go) --

    def cycle_preemption_skip(self) -> None:
        """reference admission_cycle_preemption_skips (metrics.go)."""
        self.inc("kueue_admission_cycle_preemption_skips", ())

    def admission_checks_wait(self, cq: str, wait_s: float) -> None:
        """Time from quota reservation to all checks ready
        (reference admission_checks_wait_time_seconds)."""
        self.observe("kueue_admission_checks_wait_time_seconds", (cq,),
                     wait_s, WAIT_BUCKETS)

    def admission_attempt(self, success: bool, duration_s: float) -> None:
        result = "success" if success else "inadmissible"
        self.inc("kueue_admission_attempts_total", (result,))
        self.observe("kueue_admission_attempt_duration_seconds", (result,), duration_s)

    def pending_inc(self, wl) -> None:
        pass  # pending gauges are sampled from the queues (see sample_pending)

    def sample_pending(self, queues) -> None:
        for name in queues.cluster_queue_names():
            q = queues.queue_for(name)
            self.set_gauge("kueue_pending_workloads", (name, "active"),
                           q.pending_active())
            self.set_gauge("kueue_pending_workloads", (name, "inadmissible"),
                           q.pending_inadmissible())

    def quota_reserved(self, cq: str, wait_s: float) -> None:
        self.inc("kueue_quota_reserved_workloads_total", (cq,))
        self.observe("kueue_quota_reserved_wait_time_seconds", (cq,), wait_s,
                     WAIT_BUCKETS)
        self.add_gauge("kueue_reserving_active_workloads", (cq,), 1)

    def admitted_workload(self, cq: str, wait_s: float) -> None:
        self.inc("kueue_admitted_workloads_total", (cq,))
        self.observe("kueue_admission_wait_time_seconds", (cq,), wait_s,
                     WAIT_BUCKETS)
        self.add_gauge("kueue_admitted_active_workloads", (cq,), 1)

    def release_reservation(self, cq: str) -> None:
        self.add_gauge("kueue_reserving_active_workloads", (cq,), -1)

    def release_admitted(self, cq: str) -> None:
        self.add_gauge("kueue_admitted_active_workloads", (cq,), -1)

    def evicted(self, cq: str, reason: str) -> None:
        self.inc("kueue_evicted_workloads_total", (cq, reason))

    def preempted(self, preempting_cq: str, reason: str) -> None:
        self.inc("kueue_preempted_workloads_total", (preempting_cq, reason))

    def cluster_queue_status(self, cq: str, active: bool) -> None:
        """Exactly one status series is 1 (reference ReportClusterQueueStatus)."""
        current = "active" if active else "pending"
        for status in ("pending", "active", "terminating"):
            self.set_gauge("kueue_cluster_queue_status", (cq, status),
                           1.0 if status == current else 0.0)

    def report_resource_usage(self, cq: str, flavor: str, resource: str,
                              usage: float, nominal: float,
                              reservation: float | None = None,
                              borrowing_limit: float | None = None,
                              lending_limit: float | None = None) -> None:
        self.set_gauge("kueue_cluster_queue_resource_usage",
                       (cq, flavor, resource), usage)
        self.set_gauge("kueue_cluster_queue_resource_nominal_quota",
                       (cq, flavor, resource), nominal)
        if reservation is not None:
            self.set_gauge("kueue_cluster_queue_resource_reservation",
                           (cq, flavor, resource), reservation)
        if borrowing_limit is not None:
            self.set_gauge("kueue_cluster_queue_resource_borrowing_limit",
                           (cq, flavor, resource), borrowing_limit)
        if lending_limit is not None:
            self.set_gauge("kueue_cluster_queue_resource_lending_limit",
                           (cq, flavor, resource), lending_limit)

    def local_queue_counts(self, namespace: str, lq: str, pending: int,
                           reserving: int, admitted: int) -> None:
        """local_queue_* mirrors (LocalQueueMetrics feature gate)."""
        self.set_gauge("kueue_local_queue_pending_workloads",
                       (namespace, lq), pending)
        self.set_gauge("kueue_local_queue_reserving_active_workloads",
                       (namespace, lq), reserving)
        self.set_gauge("kueue_local_queue_admitted_active_workloads",
                       (namespace, lq), admitted)

    # -- open-loop traffic series (traffic/runner.py; also read back by
    #    Driver.stats so the soak harness and the chaos report share one
    #    source) --

    def open_loop_sample(self, depth_active: int, depth_parked: int,
                         age_p50_s: float, age_p99_s: float,
                         admissions_per_s: float) -> None:
        """Per-sample open-loop gauges: queue depth by status, pending
        age quantiles, and the achieved admissions/s rate."""
        self.set_gauge("kueue_open_loop_queue_depth", ("active",),
                       depth_active)
        self.set_gauge("kueue_open_loop_queue_depth", ("inadmissible",),
                       depth_parked)
        self.set_gauge("kueue_open_loop_pending_age_seconds", ("p50",),
                       age_p50_s)
        self.set_gauge("kueue_open_loop_pending_age_seconds", ("p99",),
                       age_p99_s)
        self.set_gauge("kueue_open_loop_admissions_per_second", (),
                       admissions_per_s)

    def open_loop_latency(self, latency_s: float) -> None:
        self.observe("kueue_open_loop_admission_latency_seconds", (),
                     latency_s, LATENCY_BUCKETS)

    def open_loop_requeue_storm(self, size: int) -> None:
        self.observe("kueue_open_loop_requeue_storm_size", (), size,
                     STORM_BUCKETS)
        cur = self.gauges.get(("kueue_open_loop_requeue_storm_peak",), 0.0)
        self.set_gauge("kueue_open_loop_requeue_storm_peak", (),
                       max(cur, size))

    # -- heterogeneous fast-path series (ops/solver.py + ops/burst.py
    #    classify routing and host-fallback visibility; sampled by
    #    Driver.stats so the perf harness and /metrics agree) --

    def burst_solver_sample(self, burst_stats=None, walk_stats=None) -> None:
        """Publish the burst solver's dirty/fallback counters and the
        cycle solver's flavor-walk telemetry as ``kueue_burst_*`` gauges."""
        if burst_stats:
            for k in ("burst_dispatches", "burst_cycles_decided",
                      "burst_suppressed_cycles", "burst_dirty_cycles",
                      "burst_dirty_preempt", "burst_dirty_scalar",
                      "burst_dirty_resume"):
                self.set_gauge("kueue_" + k, (), float(burst_stats.get(k, 0)))
        if walk_stats:
            for k in ("host_cycles", "scalar_heads", "resume_heads",
                      "walk_stop_heads", "native_ff_fallbacks"):
                self.set_gauge(f"kueue_burst_{k}", (),
                               float(walk_stats.get(k, 0)))
            for reason, n in walk_stats.get("scalar_reasons", {}).items():
                self.set_gauge("kueue_burst_scalar_heads_by_reason",
                               (reason,), float(n))

    # -- streaming-pack + WAL series (ops/stream_pack.py arena patching,
    #    packing.py dtype tightening, utils/journal.py group commit;
    #    sampled by Driver.stats so the scale harness and /metrics
    #    agree) --

    def pack_sample(self, pack_stats=None, wal_stats=None) -> None:
        """Publish the streaming pack's host-cost and arena telemetry as
        ``kueue_pack_*`` gauges and the WAL's group-commit counters as
        ``kueue_wal_*`` gauges."""
        gauge_of = {
            "stream_packs": "kueue_pack_stream_packs",
            "stream_full_packs": "kueue_pack_full_packs",
            "stream_pack_bails": "kueue_pack_stream_bails",
            "stream_pack_s": "kueue_pack_host_seconds",
            "pack_last_ms": "kueue_pack_last_ms",
            "pack_row_patches": "kueue_pack_row_patches",
            "pack_rows_verified": "kueue_pack_rows_verified",
            "pack_rank_patches": "kueue_pack_rank_patches",
            "pack_arena_growth_events": "kueue_pack_arena_growth_events",
            "pack_arena_planes": "kueue_pack_arena_planes",
            "pack_arena_bytes": "kueue_pack_arena_bytes",
            "pack_arena_used_bytes": "kueue_pack_arena_used_bytes",
            "pack_tighten_bytes_saved": "kueue_pack_tighten_bytes_saved",
            "pack_tighten_widened": "kueue_pack_tighten_widened",
            "burst_launch_bytes_h2d": "kueue_pack_bytes_to_device",
        }
        if pack_stats:
            for k, gauge in gauge_of.items():
                if k in pack_stats:
                    self.set_gauge(gauge, (), float(pack_stats[k]))
        if wal_stats:
            for k in ("wal_appends", "wal_commits", "wal_flushes",
                      "wal_fsyncs", "wal_compactions"):
                if k in wal_stats:
                    self.set_gauge("kueue_" + k, (), float(wal_stats[k]))

    def report_weighted_share(self, cq: str, share: float) -> None:
        self.set_gauge("kueue_cluster_queue_weighted_share", (cq,), share)

    def report_cohort_weighted_share(self, cohort: str, share: float) -> None:
        self.set_gauge("kueue_cohort_weighted_share", (cohort,), share)

    # -- exposition --

    def render(self) -> str:
        lines = []
        for key, val in sorted(self.counters.items()):
            name, *labels = key
            lines.append(f"{name}{_fmt_labels(name, labels)} {val}")
        for key, val in sorted(self.gauges.items()):
            name, *labels = key
            lines.append(f"{name}{_fmt_labels(name, labels)} {val}")
        for key, h in sorted(self.histograms.items()):
            name, *labels = key
            lines.append(f"{name}_count{_fmt_labels(name, labels)} {h.n}")
            lines.append(f"{name}_sum{_fmt_labels(name, labels)} {h.total}")
        return "\n".join(lines) + "\n"


# Label-name tables per series (reference metrics.go label definitions)
LABEL_NAMES = {
    "kueue_admission_attempts_total": ("result",),
    "kueue_admission_attempt_duration_seconds": ("result",),
    "kueue_pending_workloads": ("cluster_queue", "status"),
    "kueue_quota_reserved_workloads_total": ("cluster_queue",),
    "kueue_quota_reserved_wait_time_seconds": ("cluster_queue",),
    "kueue_reserving_active_workloads": ("cluster_queue",),
    "kueue_admitted_workloads_total": ("cluster_queue",),
    "kueue_admission_wait_time_seconds": ("cluster_queue",),
    "kueue_admission_checks_wait_time_seconds": ("cluster_queue",),
    "kueue_admitted_active_workloads": ("cluster_queue",),
    "kueue_evicted_workloads_total": ("cluster_queue", "reason"),
    "kueue_preempted_workloads_total": ("preempting_cluster_queue", "reason"),
    "kueue_cluster_queue_status": ("cluster_queue", "status"),
    "kueue_cluster_queue_resource_usage":
        ("cluster_queue", "flavor", "resource"),
    "kueue_cluster_queue_resource_reservation":
        ("cluster_queue", "flavor", "resource"),
    "kueue_cluster_queue_resource_nominal_quota":
        ("cluster_queue", "flavor", "resource"),
    "kueue_cluster_queue_resource_borrowing_limit":
        ("cluster_queue", "flavor", "resource"),
    "kueue_cluster_queue_resource_lending_limit":
        ("cluster_queue", "flavor", "resource"),
    "kueue_cluster_queue_weighted_share": ("cluster_queue",),
    "kueue_cohort_weighted_share": ("cohort",),
    "kueue_local_queue_pending_workloads": ("namespace", "local_queue"),
    "kueue_local_queue_reserving_active_workloads":
        ("namespace", "local_queue"),
    "kueue_local_queue_admitted_active_workloads":
        ("namespace", "local_queue"),
    "kueue_burst_scalar_heads_by_reason": ("reason",),
    "kueue_open_loop_queue_depth": ("status",),
    "kueue_open_loop_pending_age_seconds": ("quantile",),
    "kueue_open_loop_admissions_per_second": (),
    "kueue_open_loop_admission_latency_seconds": (),
    "kueue_open_loop_requeue_storm_size": (),
    "kueue_open_loop_requeue_storm_peak": (),
}


def _fmt_labels(name: str, labels: list) -> str:
    if not labels:
        return ""
    names = LABEL_NAMES.get(name)
    parts = ",".join(
        f'{names[i] if names and i < len(names) else f"l{i}"}="{v}"'
        for i, v in enumerate(labels))
    return "{" + parts + "}"
