"""Metrics registry: the Prometheus-series equivalent.

Capability parity with reference pkg/metrics/metrics.go:62-386 (namespace
``kueue_``): admission attempts/durations, pending/reserving/admitted
counts, quota-reserved and admission wait times, evictions/preemptions with
reason labels, per-CQ resource usage, weighted shares.  Values are plain
Python numbers; ``render()`` emits Prometheus text exposition format so the
series names stay wire-compatible.
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict
from dataclasses import dataclass, field


def exponential_buckets(start: float, factor: float, count: int) -> list[float]:
    """reference metrics.go:387 generateExponentialBuckets."""
    return [start * factor**i for i in range(count)]


ATTEMPT_BUCKETS = exponential_buckets(0.001, 2, 16)  # seconds
WAIT_BUCKETS = exponential_buckets(1, 2, 14)
# open-loop admission latency (submit→admit, virtual seconds) and
# requeue-storm sizes (workloads unparked per cohort wakeup)
LATENCY_BUCKETS = exponential_buckets(0.25, 2, 18)
STORM_BUCKETS = exponential_buckets(1, 2, 16)
# serving admission latency is wall-clock (accept→admit): 1ms .. ~9min
SVC_LATENCY_BUCKETS = exponential_buckets(0.001, 2, 20)


@dataclass
class Histogram:
    buckets: list[float]
    counts: list[int] = field(default_factory=list)
    total: float = 0.0
    n: int = 0

    def __post_init__(self):
        if not self.counts:
            self.counts = [0] * (len(self.buckets) + 1)

    def observe(self, value: float) -> None:
        self.n += 1
        self.total += value
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return 0.0
        target = math.ceil(q * self.n)
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")


class Registry:
    """Thread-safe: the serving path (serving/service.py) updates
    counters and gauges from submitter threads while the HTTP
    ``/metrics`` handler renders from another, so every mutation and
    the full render hold ``_lock``.  An RLock, and uncontended in the
    single-threaded batch harnesses (a few ns per op); the tracer's
    direct histogram inserts (obs/trace.py) take the same lock only on
    the first observation of a phase."""

    def __init__(self):
        self.counters: dict[tuple, float] = defaultdict(float)
        self.gauges: dict[tuple, float] = defaultdict(float)
        self.histograms: dict[tuple, Histogram] = {}
        self._lock = threading.RLock()

    # -- generic --

    def inc(self, name: str, labels: tuple = (), value: float = 1.0) -> None:
        with self._lock:
            self.counters[(name, *labels)] += value

    def set_gauge(self, name: str, labels: tuple, value: float) -> None:
        with self._lock:
            self.gauges[(name, *labels)] = value

    def add_gauge(self, name: str, labels: tuple, delta: float) -> None:
        with self._lock:
            self.gauges[(name, *labels)] += delta

    def observe(self, name: str, labels: tuple, value: float,
                buckets: list[float] = ATTEMPT_BUCKETS) -> None:
        key = (name, *labels)
        with self._lock:
            if key not in self.histograms:
                self.histograms[key] = Histogram(buckets=buckets)
            self.histograms[key].observe(value)

    # -- kueue series (reference metrics.go) --

    def cycle_preemption_skip(self) -> None:
        """reference admission_cycle_preemption_skips (metrics.go)."""
        self.inc("kueue_admission_cycle_preemption_skips", ())

    def admission_checks_wait(self, cq: str, wait_s: float) -> None:
        """Time from quota reservation to all checks ready
        (reference admission_checks_wait_time_seconds)."""
        self.observe("kueue_admission_checks_wait_time_seconds", (cq,),
                     wait_s, WAIT_BUCKETS)

    def admission_attempt(self, success: bool, duration_s: float) -> None:
        result = "success" if success else "inadmissible"
        self.inc("kueue_admission_attempts_total", (result,))
        self.observe("kueue_admission_attempt_duration_seconds", (result,), duration_s)

    def pending_inc(self, wl) -> None:
        pass  # pending gauges are sampled from the queues (see sample_pending)

    def sample_pending(self, queues) -> None:
        for name in queues.cluster_queue_names():
            q = queues.queue_for(name)
            self.set_gauge("kueue_pending_workloads", (name, "active"),
                           q.pending_active())
            self.set_gauge("kueue_pending_workloads", (name, "inadmissible"),
                           q.pending_inadmissible())

    def quota_reserved(self, cq: str, wait_s: float) -> None:
        self.inc("kueue_quota_reserved_workloads_total", (cq,))
        self.observe("kueue_quota_reserved_wait_time_seconds", (cq,), wait_s,
                     WAIT_BUCKETS)
        self.add_gauge("kueue_reserving_active_workloads", (cq,), 1)

    def admitted_workload(self, cq: str, wait_s: float) -> None:
        self.inc("kueue_admitted_workloads_total", (cq,))
        self.observe("kueue_admission_wait_time_seconds", (cq,), wait_s,
                     WAIT_BUCKETS)
        self.add_gauge("kueue_admitted_active_workloads", (cq,), 1)

    def release_reservation(self, cq: str) -> None:
        self.add_gauge("kueue_reserving_active_workloads", (cq,), -1)

    def release_admitted(self, cq: str) -> None:
        self.add_gauge("kueue_admitted_active_workloads", (cq,), -1)

    def evicted(self, cq: str, reason: str) -> None:
        self.inc("kueue_evicted_workloads_total", (cq, reason))

    def preempted(self, preempting_cq: str, reason: str) -> None:
        self.inc("kueue_preempted_workloads_total", (preempting_cq, reason))

    def cluster_queue_status(self, cq: str, active: bool) -> None:
        """Exactly one status series is 1 (reference ReportClusterQueueStatus)."""
        current = "active" if active else "pending"
        for status in ("pending", "active", "terminating"):
            self.set_gauge("kueue_cluster_queue_status", (cq, status),
                           1.0 if status == current else 0.0)

    def report_resource_usage(self, cq: str, flavor: str, resource: str,
                              usage: float, nominal: float,
                              reservation: float | None = None,
                              borrowing_limit: float | None = None,
                              lending_limit: float | None = None) -> None:
        self.set_gauge("kueue_cluster_queue_resource_usage",
                       (cq, flavor, resource), usage)
        self.set_gauge("kueue_cluster_queue_resource_nominal_quota",
                       (cq, flavor, resource), nominal)
        if reservation is not None:
            self.set_gauge("kueue_cluster_queue_resource_reservation",
                           (cq, flavor, resource), reservation)
        if borrowing_limit is not None:
            self.set_gauge("kueue_cluster_queue_resource_borrowing_limit",
                           (cq, flavor, resource), borrowing_limit)
        if lending_limit is not None:
            self.set_gauge("kueue_cluster_queue_resource_lending_limit",
                           (cq, flavor, resource), lending_limit)

    def local_queue_counts(self, namespace: str, lq: str, pending: int,
                           reserving: int, admitted: int) -> None:
        """local_queue_* mirrors (LocalQueueMetrics feature gate)."""
        self.set_gauge("kueue_local_queue_pending_workloads",
                       (namespace, lq), pending)
        self.set_gauge("kueue_local_queue_reserving_active_workloads",
                       (namespace, lq), reserving)
        self.set_gauge("kueue_local_queue_admitted_active_workloads",
                       (namespace, lq), admitted)

    # -- open-loop traffic series (traffic/runner.py; also read back by
    #    Driver.stats so the soak harness and the chaos report share one
    #    source) --

    def open_loop_sample(self, depth_active: int, depth_parked: int,
                         age_p50_s: float, age_p99_s: float,
                         admissions_per_s: float) -> None:
        """Per-sample open-loop gauges: queue depth by status, pending
        age quantiles, and the achieved admissions/s rate."""
        self.set_gauge("kueue_open_loop_queue_depth", ("active",),
                       depth_active)
        self.set_gauge("kueue_open_loop_queue_depth", ("inadmissible",),
                       depth_parked)
        self.set_gauge("kueue_open_loop_pending_age_seconds", ("p50",),
                       age_p50_s)
        self.set_gauge("kueue_open_loop_pending_age_seconds", ("p99",),
                       age_p99_s)
        self.set_gauge("kueue_open_loop_admissions_per_second", (),
                       admissions_per_s)

    def open_loop_latency(self, latency_s: float) -> None:
        self.observe("kueue_open_loop_admission_latency_seconds", (),
                     latency_s, LATENCY_BUCKETS)

    def open_loop_requeue_storm(self, size: int) -> None:
        self.observe("kueue_open_loop_requeue_storm_size", (), size,
                     STORM_BUCKETS)
        cur = self.gauges.get(("kueue_open_loop_requeue_storm_peak",), 0.0)
        self.set_gauge("kueue_open_loop_requeue_storm_peak", (),
                       max(cur, size))

    # -- heterogeneous fast-path series (ops/solver.py + ops/burst.py
    #    classify routing and host-fallback visibility; sampled by
    #    Driver.stats so the perf harness and /metrics agree) --

    def burst_solver_sample(self, burst_stats=None, walk_stats=None) -> None:
        """Publish the burst solver's dirty/fallback counters and the
        cycle solver's flavor-walk telemetry as ``kueue_burst_*`` gauges.

        Gauge names are spelled out literally (no ``"kueue_" + k``
        construction) so the metrics-doc lint can statically prove every
        emitted series is documented."""
        burst_gauge_of = {
            "burst_dispatches": "kueue_burst_dispatches",
            "burst_cycles_decided": "kueue_burst_cycles_decided",
            "burst_suppressed_cycles": "kueue_burst_suppressed_cycles",
            "burst_dirty_cycles": "kueue_burst_dirty_cycles",
            "burst_dirty_preempt": "kueue_burst_dirty_preempt",
            "burst_dirty_scalar": "kueue_burst_dirty_scalar",
            "burst_dirty_resume": "kueue_burst_dirty_resume",
        }
        walk_gauge_of = {
            "host_cycles": "kueue_burst_host_cycles",
            "scalar_heads": "kueue_burst_scalar_heads",
            "resume_heads": "kueue_burst_resume_heads",
            "walk_stop_heads": "kueue_burst_walk_stop_heads",
            "native_ff_fallbacks": "kueue_burst_native_ff_fallbacks",
        }
        if burst_stats:
            for k, gauge in burst_gauge_of.items():
                self.set_gauge(gauge, (), float(burst_stats.get(k, 0)))
        if walk_stats:
            for k, gauge in walk_gauge_of.items():
                self.set_gauge(gauge, (), float(walk_stats.get(k, 0)))
            for reason, n in walk_stats.get("scalar_reasons", {}).items():
                self.set_gauge("kueue_burst_scalar_heads_by_reason",
                               (reason,), float(n))

    # -- streaming-pack + WAL series (ops/stream_pack.py arena patching,
    #    packing.py dtype tightening, utils/journal.py group commit;
    #    sampled by Driver.stats so the scale harness and /metrics
    #    agree) --

    def pack_sample(self, pack_stats=None, wal_stats=None) -> None:
        """Publish the streaming pack's host-cost and arena telemetry as
        ``kueue_pack_*`` gauges and the WAL's group-commit counters as
        ``kueue_wal_*`` gauges."""
        gauge_of = {
            "stream_packs": "kueue_pack_stream_packs",
            "stream_full_packs": "kueue_pack_full_packs",
            "stream_pack_bails": "kueue_pack_stream_bails",
            "stream_pack_s": "kueue_pack_host_seconds",
            "pack_last_ms": "kueue_pack_last_ms",
            "pack_row_patches": "kueue_pack_row_patches",
            "pack_rows_verified": "kueue_pack_rows_verified",
            "pack_rank_patches": "kueue_pack_rank_patches",
            "pack_arena_growth_events": "kueue_pack_arena_growth_events",
            "pack_arena_planes": "kueue_pack_arena_planes",
            "pack_arena_bytes": "kueue_pack_arena_bytes",
            "pack_arena_used_bytes": "kueue_pack_arena_used_bytes",
            "pack_tighten_bytes_saved": "kueue_pack_tighten_bytes_saved",
            "pack_tighten_widened": "kueue_pack_tighten_widened",
            "burst_launch_bytes_h2d": "kueue_pack_bytes_to_device",
        }
        if pack_stats:
            for k, gauge in gauge_of.items():
                if k in pack_stats:
                    self.set_gauge(gauge, (), float(pack_stats[k]))
        wal_gauge_of = {
            "wal_appends": "kueue_wal_appends",
            "wal_commits": "kueue_wal_commits",
            "wal_flushes": "kueue_wal_flushes",
            "wal_fsyncs": "kueue_wal_fsyncs",
            "wal_compactions": "kueue_wal_compactions",
        }
        if wal_stats:
            for k, gauge in wal_gauge_of.items():
                if k in wal_stats:
                    self.set_gauge(gauge, (), float(wal_stats[k]))

    def scale_opt_sample(self, agg_stats=None, heap_stats=None,
                         wal_shard_stats=None, head_pack_stats=None,
                         host_pool_stats=None) -> None:
        """Publish the 1M-CQ scale-path telemetry: cohort-forest
        aggregate compression (``kueue_agg_*``, ops/aggregate.py), lazy
        heap repair (``kueue_heap_repair_*``, utils/heap.py), sharded
        WAL striping (``kueue_wal_shard_*``, utils/journal.py),
        head-only packing (``kueue_head_pack_*``, ops/burst.py budget
        scoping), and the parallel host plane (``kueue_host_pool_*``,
        utils/parallel_host.py).  Sampled by ``Driver.stats`` like the
        pack/WAL series."""
        agg_gauge_of = {
            "agg_rows_compressed": "kueue_agg_rows_compressed",
            "agg_rows_packed": "kueue_agg_rows_packed",
            "agg_heads": "kueue_agg_heads",
            "agg_cqs_compressible": "kueue_agg_cqs_compressible",
        }
        heap_gauge_of = {
            "heap_repair_settles": "kueue_heap_repair_settles",
            "heap_repair_deferred": "kueue_heap_repair_deferred",
            "heap_repair_settled_items": "kueue_heap_repair_settled_items",
            "heap_repair_bulk": "kueue_heap_repair_bulk",
        }
        shard_gauge_of = {
            "wal_shards": "kueue_wal_shards",
            "wal_shard_skew": "kueue_wal_shard_skew",
        }
        head_pack_gauge_of = {
            "head_pack_budget_rows": "kueue_head_pack_budget_rows",
            "head_pack_exempt_rows": "kueue_head_pack_exempt_rows",
        }
        pool_gauge_of = {
            "host_pool_workers": "kueue_host_pool_workers",
            "host_pool_tasks": "kueue_host_pool_tasks",
            "host_pool_batches": "kueue_host_pool_batches",
            "host_pool_partitions": "kueue_host_pool_partitions",
        }
        if agg_stats:
            for k, gauge in agg_gauge_of.items():
                if k in agg_stats:
                    self.set_gauge(gauge, (), float(agg_stats[k]))
        if heap_stats:
            for k, gauge in heap_gauge_of.items():
                if k in heap_stats:
                    self.set_gauge(gauge, (), float(heap_stats[k]))
        if wal_shard_stats:
            for k, gauge in shard_gauge_of.items():
                if k in wal_shard_stats:
                    self.set_gauge(gauge, (), float(wal_shard_stats[k]))
        if head_pack_stats:
            for k, gauge in head_pack_gauge_of.items():
                if k in head_pack_stats:
                    self.set_gauge(gauge, (), float(head_pack_stats[k]))
        if host_pool_stats:
            for k, gauge in pool_gauge_of.items():
                if k in host_pool_stats:
                    self.set_gauge(gauge, (), float(host_pool_stats[k]))

    def report_weighted_share(self, cq: str, share: float) -> None:
        self.set_gauge("kueue_cluster_queue_weighted_share", (cq,), share)

    def report_cohort_weighted_share(self, cohort: str, share: float) -> None:
        self.set_gauge("kueue_cohort_weighted_share", (cohort,), share)

    # -- observability-plane series (obs/: event stream + flight
    #    recorder; sampled by Driver.refresh_resource_metrics so
    #    /metrics always carries the current counts) --

    def obs_sample(self, events_report=None, flight_recorded: int = 0) -> None:
        """Publish the event stream's per-kind totals and the flight
        recorder's cycle count as ``kueue_obs_*`` / ``kueue_flight_*``."""
        if events_report:
            for kind, n in events_report.get("counts", {}).items():
                self.set_gauge("kueue_obs_events_total", (kind,), float(n))
            self.set_gauge("kueue_obs_events_dropped_total", (),
                           float(events_report.get("dropped", 0)))
        self.set_gauge("kueue_flight_cycles_recorded", (),
                       float(flight_recorded))

    # -- serving series (serving/service.py: thread-safe ingest +
    #    adaptive burst window; the only series written from submitter
    #    threads, which is why the registry carries a lock) --

    def svc_submission(self, result: str) -> None:
        """One submission outcome: accepted / rejected / duplicate /
        shed / draining."""
        self.inc("kueue_svc_submissions_total", (result,))

    def svc_admission_latency(self, seconds: float) -> None:
        """Wall-clock accept→admit latency of one served workload."""
        self.observe("kueue_svc_admission_latency_seconds", (), seconds,
                     SVC_LATENCY_BUCKETS)

    def svc_sample(self, depth: int, high_water: int, burst_k: int,
                   ewma_rate: float, retry_after_s: float) -> None:
        """Per-step serving telemetry: ingest depth vs the backpressure
        high-water mark, the online-chosen burst window, the arrival
        EWMA, and the current retry-after estimate."""
        self.set_gauge("kueue_svc_ingest_depth", (), float(depth))
        self.set_gauge("kueue_svc_ingest_high_water", (), float(high_water))
        self.set_gauge("kueue_svc_burst_window", (), float(burst_k))
        self.set_gauge("kueue_svc_arrival_rate_ewma", (), float(ewma_rate))
        self.set_gauge("kueue_svc_retry_after_seconds", (),
                       float(retry_after_s))

    def dist_sample(self, by_role: dict, proxy_stats=None,
                    shard_depths=None) -> None:
        """Distributed-run telemetry: supervisor per-role lifecycle
        counts, socket-fault proxy totals, per-shard ingest depths."""
        for role, counts in by_role.items():
            self.set_gauge("kueue_dist_process_spawns_total", (role,),
                           float(counts.get("spawns", 0)))
            self.set_gauge("kueue_dist_process_kills_total", (role,),
                           float(counts.get("kills", 0)))
            self.set_gauge("kueue_dist_process_restarts_total", (role,),
                           float(counts.get("restarts", 0)))
        if proxy_stats:
            self.set_gauge("kueue_dist_proxy_connections_total", (),
                           float(proxy_stats.get("connections", 0)))
            for kind, stat in (("reset", "resets"),
                               ("latency", "latencies"),
                               ("truncate", "truncations"),
                               ("blackhole", "blackholes")):
                self.set_gauge("kueue_dist_proxy_faults_total", (kind,),
                               float(proxy_stats.get(stat, 0)))
        for shard, depth in (shard_depths or {}).items():
            self.set_gauge("kueue_dist_shard_ingest_depth",
                           (str(shard),), float(depth))

    def rpc_sample(self, stats: dict) -> None:
        """HTTP worker-client accounting (one client's ``.stats`` or a
        summed aggregate): requests, retries by transport cause,
        exhausted deadlines, noticed watch-epoch changes."""
        self.set_gauge("kueue_rpc_requests_total", (),
                       float(stats.get("requests", 0)))
        refused = stats.get("refused_retries", 0)
        midbody = stats.get("midbody_retries", 0)
        other = max(0, stats.get("retries", 0) - refused - midbody)
        self.set_gauge("kueue_rpc_retries_total", ("refused",),
                       float(refused))
        self.set_gauge("kueue_rpc_retries_total", ("mid_body",),
                       float(midbody))
        self.set_gauge("kueue_rpc_retries_total", ("other",),
                       float(other))
        self.set_gauge("kueue_rpc_deadline_exhausted_total", (),
                       float(stats.get("deadline_exhausted", 0)))
        self.set_gauge("kueue_rpc_epoch_resyncs_total", (),
                       float(stats.get("epoch_resyncs", 0)))

    # -- exposition --

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4: per-family ``# HELP``
        / ``# TYPE`` headers, cumulative ``_bucket{le=...}`` series ending
        in ``+Inf`` plus ``_sum``/``_count`` for histograms, and escaped
        label values.  Round-trip checked against a strict parser in
        tests/test_obs.py."""
        with self._lock:
            families: dict[str, list] = defaultdict(list)
            for key, val in self.counters.items():
                families[key[0]].append((key[1:], val))
            for key, val in self.gauges.items():
                families[key[0]].append((key[1:], val))
            for key, h in self.histograms.items():
                families[key[0]].append((key[1:], h))
            lines: list[str] = []
            for name in sorted(families):
                spec = SERIES.get(name)
                kind = spec.kind if spec else (
                    "histogram"
                    if isinstance(families[name][0][1], Histogram)
                    else "untyped")
                help_text = spec.help if spec else name
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
                lines.append(f"# TYPE {name} {kind}")
                for labels, val in sorted(families[name],
                                          key=lambda kv: kv[0]):
                    if isinstance(val, Histogram):
                        lines.extend(_render_histogram(name, labels, val))
                    else:
                        lines.append(
                            f"{name}{_fmt_labels(name, labels)}"
                            f" {_fmt_value(val)}")
            return "\n".join(lines) + "\n"


@dataclass(frozen=True)
class Series:
    """One documented metric family: exposition type, label names in
    emission order, and the HELP string."""
    name: str
    kind: str            # "counter" | "gauge" | "histogram"
    labels: tuple
    help: str


# Every series this registry emits, in one place.  The metrics-doc lint
# (analysis/metrics_doc.py) proves two invariants statically: every
# ``kueue_*`` string literal in this module names a row here, and this
# table matches the README "## Metrics" table in both directions.
_SERIES_DEFS = [
    # reference pkg/metrics parity
    ("kueue_admission_attempts_total", "counter", ("result",),
     "Admission attempts by result (success / inadmissible)."),
    ("kueue_admission_attempt_duration_seconds", "histogram", ("result",),
     "Latency of one admission attempt, by result."),
    ("kueue_admission_cycle_preemption_skips", "counter", (),
     "Workloads skipped in a cycle because preemption was still pending."),
    ("kueue_pending_workloads", "gauge", ("cluster_queue", "status"),
     "Pending workloads per cluster queue, by active/inadmissible status."),
    ("kueue_quota_reserved_workloads_total", "counter", ("cluster_queue",),
     "Workloads that reserved quota, cumulative per cluster queue."),
    ("kueue_quota_reserved_wait_time_seconds", "histogram",
     ("cluster_queue",),
     "Wait from creation to quota reservation."),
    ("kueue_reserving_active_workloads", "gauge", ("cluster_queue",),
     "Workloads currently holding a quota reservation."),
    ("kueue_admitted_workloads_total", "counter", ("cluster_queue",),
     "Admitted workloads, cumulative per cluster queue."),
    ("kueue_admission_wait_time_seconds", "histogram", ("cluster_queue",),
     "Wait from creation to admission."),
    ("kueue_admission_checks_wait_time_seconds", "histogram",
     ("cluster_queue",),
     "Wait from quota reservation to all admission checks ready."),
    ("kueue_admitted_active_workloads", "gauge", ("cluster_queue",),
     "Workloads currently admitted."),
    ("kueue_evicted_workloads_total", "counter", ("cluster_queue", "reason"),
     "Evictions by cluster queue and reason."),
    ("kueue_preempted_workloads_total", "counter",
     ("preempting_cluster_queue", "reason"),
     "Preemptions by preempting cluster queue and reason."),
    ("kueue_cluster_queue_status", "gauge", ("cluster_queue", "status"),
     "Cluster queue status one-hot (pending / active / terminating)."),
    ("kueue_cluster_queue_resource_usage", "gauge",
     ("cluster_queue", "flavor", "resource"),
     "Admitted resource usage per cluster queue, flavor, and resource."),
    ("kueue_cluster_queue_resource_reservation", "gauge",
     ("cluster_queue", "flavor", "resource"),
     "Reserved (incl. non-admitted) quota per cluster queue and flavor."),
    ("kueue_cluster_queue_resource_nominal_quota", "gauge",
     ("cluster_queue", "flavor", "resource"),
     "Configured nominal quota per cluster queue and flavor."),
    ("kueue_cluster_queue_resource_borrowing_limit", "gauge",
     ("cluster_queue", "flavor", "resource"),
     "Configured borrowing limit, when set."),
    ("kueue_cluster_queue_resource_lending_limit", "gauge",
     ("cluster_queue", "flavor", "resource"),
     "Configured lending limit, when set."),
    ("kueue_cluster_queue_weighted_share", "gauge", ("cluster_queue",),
     "Fair-sharing weighted share per cluster queue."),
    ("kueue_cohort_weighted_share", "gauge", ("cohort",),
     "Fair-sharing weighted share per cohort."),
    ("kueue_local_queue_pending_workloads", "gauge",
     ("namespace", "local_queue"),
     "Pending workloads per local queue (LocalQueueMetrics gate)."),
    ("kueue_local_queue_reserving_active_workloads", "gauge",
     ("namespace", "local_queue"),
     "Reserving workloads per local queue (LocalQueueMetrics gate)."),
    ("kueue_local_queue_admitted_active_workloads", "gauge",
     ("namespace", "local_queue"),
     "Admitted workloads per local queue (LocalQueueMetrics gate)."),
    # open-loop traffic soak
    ("kueue_open_loop_queue_depth", "gauge", ("status",),
     "Open-loop soak queue depth by active/inadmissible status."),
    ("kueue_open_loop_pending_age_seconds", "gauge", ("quantile",),
     "Open-loop pending-age quantiles (p50/p99), virtual seconds."),
    ("kueue_open_loop_admissions_per_second", "gauge", (),
     "Achieved open-loop admission rate."),
    ("kueue_open_loop_admission_latency_seconds", "histogram", (),
     "Submit-to-admit latency in the open-loop soak, virtual seconds."),
    ("kueue_open_loop_requeue_storm_size", "histogram", (),
     "Workloads unparked per cohort wakeup."),
    ("kueue_open_loop_requeue_storm_peak", "gauge", (),
     "Largest requeue storm observed."),
    # burst solver + flavor walk
    ("kueue_burst_dispatches", "gauge", (),
     "Fused burst-kernel dispatches."),
    ("kueue_burst_cycles_decided", "gauge", (),
     "Cycles decided on-device by the burst solver."),
    ("kueue_burst_suppressed_cycles", "gauge", (),
     "Burst cycles suppressed by the dirty-set check."),
    ("kueue_burst_dirty_cycles", "gauge", (),
     "Burst cycles invalidated and replayed on host."),
    ("kueue_burst_dirty_preempt", "gauge", (),
     "Burst invalidations caused by preemption."),
    ("kueue_burst_dirty_scalar", "gauge", (),
     "Burst invalidations caused by scalar-path heads."),
    ("kueue_burst_dirty_resume", "gauge", (),
     "Burst invalidations caused by resume heads."),
    ("kueue_burst_host_cycles", "gauge", (),
     "Cycles that fell back to the host solver."),
    ("kueue_burst_scalar_heads", "gauge", (),
     "Heads routed to the scalar path."),
    ("kueue_burst_resume_heads", "gauge", (),
     "Heads resumed mid-walk after a preempting flavor."),
    ("kueue_burst_walk_stop_heads", "gauge", (),
     "Heads whose flavor walk stopped early."),
    ("kueue_burst_native_ff_fallbacks", "gauge", (),
     "Flavor-fungibility configs the native kernel could not encode."),
    ("kueue_burst_scalar_heads_by_reason", "gauge", ("reason",),
     "Scalar-path heads broken down by routing reason."),
    # streaming pack + arena + WAL
    ("kueue_pack_stream_packs", "gauge", (),
     "Streaming (delta) pack invocations."),
    ("kueue_pack_full_packs", "gauge", (),
     "Full repacks (stream path unavailable or bailed)."),
    ("kueue_pack_stream_bails", "gauge", (),
     "Streaming packs that bailed to a full repack."),
    ("kueue_pack_host_seconds", "gauge", (),
     "Cumulative host seconds spent packing."),
    ("kueue_pack_last_ms", "gauge", (),
     "Duration of the most recent pack, milliseconds."),
    ("kueue_pack_row_patches", "gauge", (),
     "Arena row patches applied by streaming packs."),
    ("kueue_pack_rows_verified", "gauge", (),
     "Arena rows verified against a full repack."),
    ("kueue_pack_rank_patches", "gauge", (),
     "Rank-plane patches applied by streaming packs."),
    ("kueue_pack_arena_growth_events", "gauge", (),
     "Times the pinned arena had to grow."),
    ("kueue_pack_arena_planes", "gauge", (),
     "Planes resident in the pinned arena."),
    ("kueue_pack_arena_bytes", "gauge", (),
     "Pinned arena capacity, bytes."),
    ("kueue_pack_arena_used_bytes", "gauge", (),
     "Pinned arena bytes in use."),
    ("kueue_pack_tighten_bytes_saved", "gauge", (),
     "Bytes saved by dtype tightening."),
    ("kueue_pack_tighten_widened", "gauge", (),
     "Planes widened back after a tightening overflow."),
    ("kueue_pack_bytes_to_device", "gauge", (),
     "Host-to-device bytes shipped per burst launch."),
    ("kueue_wal_appends", "gauge", (),
     "WAL operation records appended."),
    ("kueue_wal_commits", "gauge", (),
     "WAL cycle commits."),
    ("kueue_wal_flushes", "gauge", (),
     "WAL buffered-write flushes."),
    ("kueue_wal_fsyncs", "gauge", (),
     "WAL fsync calls."),
    ("kueue_wal_compactions", "gauge", (),
     "WAL checkpoint compactions."),
    # 1M-CQ scale path: aggregate compression, lazy heap, WAL shards
    ("kueue_agg_rows_compressed", "gauge", (),
     "Admitted rows held as per-CQ aggregates instead of packed rows."),
    ("kueue_agg_rows_packed", "gauge", (),
     "Admitted rows materialized as packed kernel rows."),
    ("kueue_agg_heads", "gauge", (),
     "Pending heads tracked by the aggregate planes."),
    ("kueue_agg_cqs_compressible", "gauge", (),
     "CQs in non-preempting forests eligible for row compression."),
    ("kueue_heap_repair_settles", "gauge", (),
     "Lazy-heap settle passes (one per ordered read after mutations)."),
    ("kueue_heap_repair_deferred", "gauge", (),
     "Heap pushes/updates buffered by lazy repair."),
    ("kueue_heap_repair_settled_items", "gauge", (),
     "Buffered heap items applied during settle passes."),
    ("kueue_heap_repair_bulk", "gauge", (),
     "Settle passes that used the O(n) bulk heapify."),
    ("kueue_wal_shards", "gauge", (),
     "Configured CycleWAL segment count (1 = unsharded)."),
    ("kueue_wal_shard_skew", "gauge", (),
     "Max-minus-min appended ops across WAL segments."),
    # r19 scale path: head-only packing + parallel host plane
    ("kueue_head_pack_budget_rows", "gauge", (),
     "Packed rows charged against the kernel's 2^19 composite-key "
     "budget (rows of preempting forests)."),
    ("kueue_head_pack_exempt_rows", "gauge", (),
     "Packed rows exempt from the composite-key budget (rank context "
     "of never-preempting forests)."),
    ("kueue_host_pool_workers", "gauge", (),
     "Configured host-plane worker threads (0/1 = serial)."),
    ("kueue_host_pool_tasks", "gauge", (),
     "Tasks executed on host-pool worker threads."),
    ("kueue_host_pool_batches", "gauge", (),
     "Fork-join rounds the host pool fanned out."),
    ("kueue_host_pool_partitions", "gauge", (),
     "Cohort-forest partitions dispatched by the host pool."),
    # observability plane (obs/)
    ("kueue_span_duration_seconds", "histogram", ("phase",),
     "Traced hot-path phase durations (obs tracer), wall seconds."),
    ("kueue_obs_events_total", "gauge", ("kind",),
     "Events emitted, by kind (admit/evict/preempt/requeue/eject)."),
    ("kueue_obs_events_dropped_total", "gauge", (),
     "Events dropped from the bounded stream after overflow."),
    ("kueue_flight_cycles_recorded", "gauge", (),
     "Cycles recorded by the flight recorder, cumulative."),
    # serving plane (serving/)
    ("kueue_svc_submissions_total", "counter", ("result",),
     "Service submissions by outcome "
     "(accepted/rejected/duplicate/shed/draining)."),
    ("kueue_svc_admission_latency_seconds", "histogram", (),
     "Wall-clock accept-to-admit latency through the service."),
    ("kueue_svc_ingest_depth", "gauge", (),
     "Pending submissions in the service ingest queue."),
    ("kueue_svc_ingest_high_water", "gauge", (),
     "Configured ingest backpressure high-water mark."),
    ("kueue_svc_burst_window", "gauge", (),
     "Burst-window K chosen online for the current service step."),
    ("kueue_svc_arrival_rate_ewma", "gauge", (),
     "EWMA of the submission arrival rate, events/s."),
    ("kueue_svc_retry_after_seconds", "gauge", (),
     "Current retry-after hint handed to rejected submitters."),
    # distributed control plane (dist/)
    ("kueue_dist_process_spawns_total", "gauge", ("role",),
     "Child processes spawned by the supervisor, by role."),
    ("kueue_dist_process_kills_total", "gauge", ("role",),
     "Child processes SIGKILLed by the supervisor, by role."),
    ("kueue_dist_process_restarts_total", "gauge", ("role",),
     "Killed child processes respawned by the supervisor, by role."),
    ("kueue_dist_proxy_connections_total", "gauge", (),
     "Connections accepted by the socket-fault proxy."),
    ("kueue_dist_proxy_faults_total", "gauge", ("kind",),
     "Wire faults injected by the socket-fault proxy "
     "(reset/latency/truncate/blackhole)."),
    ("kueue_dist_shard_ingest_depth", "gauge", ("shard",),
     "Pending submissions per front-end shard process."),
    # remote-transport client accounting (remote.py HttpWorkerClient)
    ("kueue_rpc_requests_total", "gauge", (),
     "HTTP worker-client requests issued, attempts included."),
    ("kueue_rpc_retries_total", "gauge", ("cause",),
     "HTTP worker-client in-place retries by transport cause "
     "(refused/mid_body/other)."),
    ("kueue_rpc_deadline_exhausted_total", "gauge", (),
     "Requests whose retry budget ran out (surfaced ConnectionLost)."),
    ("kueue_rpc_epoch_resyncs_total", "gauge", (),
     "Watch-epoch changes noticed by clients (worker restarts)."),
]

SERIES: dict[str, Series] = {
    name: Series(name, kind, labels, help)
    for name, kind, labels, help in _SERIES_DEFS
}

# Label-name tables per series, derived from SERIES (reference
# metrics.go label definitions).
LABEL_NAMES = {s.name: s.labels for s in SERIES.values() if s.labels}


def _escape_label(value) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(val: float) -> str:
    f = float(val)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(name: str, labels, extra: str = "") -> str:
    if not labels and not extra:
        return ""
    names = LABEL_NAMES.get(name)
    parts = [
        f'{names[i] if names and i < len(names) else f"l{i}"}'
        f'="{_escape_label(v)}"'
        for i, v in enumerate(labels)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}"


def _render_histogram(name: str, labels, h: Histogram) -> list[str]:
    lines = []
    cum = 0
    for i, b in enumerate(h.buckets):
        cum += h.counts[i]
        le = _fmt_value(b) if float(b) == int(b) else repr(float(b))
        extra = 'le="' + le + '"'
        lines.append(f"{name}_bucket"
                     f"{_fmt_labels(name, labels, extra)} {cum}")
    cum += h.counts[-1]
    inf_extra = 'le="+Inf"'
    lines.append(f"{name}_bucket"
                 f"{_fmt_labels(name, labels, inf_extra)} {cum}")
    lines.append(f"{name}_sum{_fmt_labels(name, labels)}"
                 f" {_fmt_value(h.total)}")
    lines.append(f"{name}_count{_fmt_labels(name, labels)} {h.n}")
    return lines
