"""RayJob / RayCluster integrations (reference pkg/controller/jobs/rayjob
623 LoC, raycluster 531 LoC).

A Ray cluster contributes one PodSet for the head plus one per worker
group; a RayJob wraps a cluster spec and finishes with the job's
terminal status, while a RayCluster is a long-running service that only
finishes on deletion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..jobframework.interface import IntegrationCallbacks, register_integration
from .base import PodTemplate, TemplateJob


@dataclass
class WorkerGroupSpec:
    name: str
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)


def _cluster_templates(head_requests: dict[str, int],
                       worker_groups: list[WorkerGroupSpec]) -> list[PodTemplate]:
    templates = [PodTemplate(name="head", count=1,
                             requests=dict(head_requests))]
    templates += [PodTemplate(name=wg.name, count=wg.replicas,
                              requests=dict(wg.requests))
                  for wg in worker_groups]
    return templates


class RayJob(TemplateJob):
    kind = "RayJob"
    STATUS_FIELDS = ("job_status",)

    def __init__(self, name: str, head_requests: dict[str, int],
                 worker_groups: list[WorkerGroupSpec], **kw):
        super().__init__(name, templates=_cluster_templates(
            head_requests, worker_groups), **kw)
        self.job_status: Optional[str] = None   # SUCCEEDED | FAILED

    def mark_status(self, status: str) -> None:
        self.job_status = status

    def finished(self) -> tuple[str, bool, bool]:
        if self.job_status == "SUCCEEDED":
            return "RayJob succeeded", True, True
        if self.job_status == "FAILED":
            return "RayJob failed", False, True
        return "", False, False


class RayCluster(TemplateJob):
    """A serving-style cluster: admitted while it exists."""

    kind = "RayCluster"
    STATUS_FIELDS = ("deleted",)

    def __init__(self, name: str, head_requests: dict[str, int],
                 worker_groups: list[WorkerGroupSpec], **kw):
        super().__init__(name, templates=_cluster_templates(
            head_requests, worker_groups), **kw)
        self.deleted = False

    def finished(self) -> tuple[str, bool, bool]:
        if self.deleted:
            return "RayCluster deleted", True, True
        return "", False, False


register_integration(IntegrationCallbacks(
    name="ray.io/rayjob", gvk=RayJob.kind, new_job=RayJob))
register_integration(IntegrationCallbacks(
    name="ray.io/raycluster", gvk=RayCluster.kind, new_job=RayCluster))
