"""RayJob / RayCluster integrations (reference pkg/controller/jobs/rayjob
623 LoC, raycluster 531 LoC).

A Ray cluster contributes one PodSet for the head plus one per worker
group (count = replicas × numOfHosts — multi-host TPU worker groups,
rayjob_controller.go:135-153); a RayJob in K8sJobMode adds a submitter
pod set (:155-168).  A RayJob finishes with the job's terminal status; a
RayCluster is a long-running service that only finishes on deletion.
Webhook rules follow rayjob_webhook.go:100-143: shutdownAfterJobFinishes
must be set, no pre-existing cluster, no in-tree autoscaling, at most 7
worker groups (8 pod sets with the head), and "head" is a reserved
group name.  One deliberate tightening: in K8sJobMode the submitter pod
set also consumes a slot, so the cap drops to 6 — the reference webhook
allows 7 there (rayjob_webhook.go:123 ignores submission mode) and then
rejects the 9-pod-set Workload at the workload webhook instead; we fail
at job admission where the user can see it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..jobframework.interface import IntegrationCallbacks, register_integration
from .base import PodTemplate, TemplateJob

HEAD_GROUP = "head"
SUBMITTER = "submitter"
MAX_WORKER_GROUPS = 7          # 8 pod sets minus the head
SUBMISSION_MODES = ("K8sJobMode", "HTTPMode", "InteractiveMode")


@dataclass
class WorkerGroupSpec:
    name: str
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    num_of_hosts: int = 1
    topology_request: object = None


def _cluster_templates(head_requests: dict[str, int],
                       worker_groups: list[WorkerGroupSpec],
                       head_topology=None) -> list[PodTemplate]:
    templates = [PodTemplate(name=HEAD_GROUP, count=1,
                             requests=dict(head_requests),
                             topology_request=head_topology)]
    templates += [
        PodTemplate(name=wg.name,
                    count=wg.replicas * max(1, wg.num_of_hosts),
                    requests=dict(wg.requests),
                    topology_request=wg.topology_request)
        for wg in worker_groups]
    return templates


def _validate_cluster(worker_groups, autoscaling, path,
                      reserved=(HEAD_GROUP,),
                      max_groups=MAX_WORKER_GROUPS) -> list[str]:
    errors = []
    if autoscaling:
        errors.append(
            f"{path}.enableInTreeAutoscaling: a kueue managed job "
            "should not use autoscaling")
    if len(worker_groups) > max_groups:
        errors.append(
            f"{path}.workerGroupSpecs: too many worker groups "
            f"({len(worker_groups)} > {max_groups})")
    seen: set[str] = set()
    for i, wg in enumerate(worker_groups):
        if wg.name in reserved:
            errors.append(
                f"{path}.workerGroupSpecs[{i}].groupName: "
                f"{wg.name!r} is reserved for the "
                f"{'head group' if wg.name == HEAD_GROUP else 'submitter pod'}")
        if wg.name in seen:
            errors.append(
                f"{path}.workerGroupSpecs[{i}].groupName: duplicate "
                f"group name {wg.name!r}")
        seen.add(wg.name)
    return errors


class RayJob(TemplateJob):
    kind = "RayJob"
    STATUS_FIELDS = ("job_status",)

    def __init__(self, name: str, head_requests: dict[str, int],
                 worker_groups: list[WorkerGroupSpec],
                 submission_mode: str = "K8sJobMode",
                 submitter_requests: Optional[dict[str, int]] = None,
                 shutdown_after_job_finishes: bool = True,
                 cluster_selector: Optional[dict[str, str]] = None,
                 enable_in_tree_autoscaling: bool = False,
                 head_topology=None, **kw):
        templates = _cluster_templates(head_requests, worker_groups,
                                       head_topology)
        if submission_mode == "K8sJobMode":
            # the job-submission pod competes for quota too
            # (rayjob_controller.go:155-168)
            # reference default submitter shape: 500m cpu + 200Mi memory
            # (rayjob_controller.go getSubmitterTemplate; memory is in
            # bytes in the canonical units, api/quantity.py)
            templates.append(PodTemplate(
                name=SUBMITTER, count=1,
                requests=dict(submitter_requests
                              or {"cpu": 500, "memory": 200 << 20})))
        super().__init__(name, templates=templates, **kw)
        self.worker_groups = list(worker_groups)
        self.submission_mode = submission_mode
        self.shutdown_after_job_finishes = shutdown_after_job_finishes
        self.cluster_selector = dict(cluster_selector or {})
        self.enable_in_tree_autoscaling = enable_in_tree_autoscaling
        self.job_status: Optional[str] = None   # SUCCEEDED | FAILED

    def mark_status(self, status: str) -> None:
        self.job_status = status

    def finished(self) -> tuple[str, bool, bool]:
        if self.job_status == "SUCCEEDED":
            return "RayJob succeeded", True, True
        if self.job_status == "FAILED":
            return "RayJob failed", False, True
        return "", False, False

    def validate_on_create(self) -> list[str]:
        errors = []
        if self.submission_mode not in SUBMISSION_MODES:
            errors.append(
                f"spec.submissionMode: {self.submission_mode!r} is not "
                f"one of {list(SUBMISSION_MODES)}")
        if not self.shutdown_after_job_finishes:
            errors.append(
                "spec.shutdownAfterJobFinishes: a kueue managed job "
                "should delete the cluster after finishing")
        if self.cluster_selector:
            errors.append(
                "spec.clusterSelector: a kueue managed job should not "
                "use an existing cluster")
        # the submitter pod set consumes one of the 8 pod-set slots and
        # reserves its name
        k8s_mode = self.submission_mode == "K8sJobMode"
        errors.extend(_validate_cluster(
            self.worker_groups, self.enable_in_tree_autoscaling,
            "spec.rayClusterSpec",
            reserved=(HEAD_GROUP, SUBMITTER) if k8s_mode else (HEAD_GROUP,),
            max_groups=MAX_WORKER_GROUPS - (1 if k8s_mode else 0)))
        return errors


class RayCluster(TemplateJob):
    """A serving-style cluster: admitted while it exists."""

    kind = "RayCluster"
    STATUS_FIELDS = ("deleted",)

    def __init__(self, name: str, head_requests: dict[str, int],
                 worker_groups: list[WorkerGroupSpec],
                 enable_in_tree_autoscaling: bool = False,
                 head_topology=None, **kw):
        super().__init__(name, templates=_cluster_templates(
            head_requests, worker_groups, head_topology), **kw)
        self.worker_groups = list(worker_groups)
        self.enable_in_tree_autoscaling = enable_in_tree_autoscaling
        self.deleted = False

    def finished(self) -> tuple[str, bool, bool]:
        if self.deleted:
            return "RayCluster deleted", True, True
        return "", False, False

    def validate_on_create(self) -> list[str]:
        return _validate_cluster(
            self.worker_groups, self.enable_in_tree_autoscaling, "spec")


register_integration(IntegrationCallbacks(
    name="ray.io/rayjob", gvk=RayJob.kind, new_job=RayJob))
register_integration(IntegrationCallbacks(
    name="ray.io/raycluster", gvk=RayCluster.kind, new_job=RayCluster))
