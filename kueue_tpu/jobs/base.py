"""Shared machinery for the concrete integrations.

``TemplateJob`` keeps a mutable pod-template overlay (node selectors,
tolerations, counts) that admission injects and suspension restores —
the equivalent of the reference integrations mutating the job's pod
template in RunWithPodSetsInfo / RestorePodSetsInfo.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..api.types import PodSet, Toleration
from ..jobframework.interface import GenericJob, JobWithManagedBy
from ..podset import PodSetInfo


@dataclass
class PodTemplate:
    """A pod template for one role of a job."""
    name: str = "main"
    count: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    topology_request: object = None

    def to_pod_set(self, count: Optional[int] = None) -> PodSet:
        return PodSet(
            name=self.name, count=count if count is not None else self.count,
            requests=dict(self.requests),
            node_selector=dict(self.node_selector),
            tolerations=list(self.tolerations),
            topology_request=self.topology_request)


class TemplateJob(GenericJob, JobWithManagedBy):
    """Base for template-driven integrations: suspend flag + overlay."""

    kind = "TemplateJob"
    # execution-status fields mirrored back from a remote copy
    # (MultiKueue adapter copy-back)
    STATUS_FIELDS: tuple[str, ...] = ()

    def __init__(self, name: str, namespace: str = "default",
                 queue: str = "", templates: Sequence[PodTemplate] = (),
                 priority_class: str = "", managed_by: Optional[str] = None):
        self._name = name
        self._namespace = namespace
        self.queue = queue
        self._priority_class = priority_class
        self.templates = list(templates)
        self.suspended = True
        self.started_infos: Optional[list[PodSetInfo]] = None
        self._managed_by = managed_by
        self._original: list[PodTemplate] = [
            dataclasses.replace(t,
                                requests=dict(t.requests),
                                node_selector=dict(t.node_selector),
                                tolerations=list(t.tolerations),
                                labels=dict(t.labels),
                                annotations=dict(t.annotations))
            for t in self.templates]

    # -- identity ------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def gvk(self) -> str:
        return self.kind

    @property
    def priority_class_name(self) -> str:
        return self._priority_class

    # -- managed-by (MultiKueue) ---------------------------------------

    def managed_by(self) -> Optional[str]:
        return self._managed_by

    def set_managed_by(self, manager: Optional[str]) -> None:
        self._managed_by = manager

    # -- gating --------------------------------------------------------

    def is_suspended(self) -> bool:
        return self.suspended

    def suspend(self) -> None:
        self.suspended = True
        self.started_infos = None

    def run_with_podsets_info(self, infos: Sequence[PodSetInfo]) -> None:
        by_name = {i.name: i for i in infos}
        for t in self.templates:
            info = by_name.get(t.name)
            if info is None:
                continue
            t.node_selector.update(info.node_selector)
            t.labels.update(info.labels)
            t.annotations.update(info.annotations)
            t.tolerations.extend(
                tol for tol in info.tolerations if tol not in t.tolerations)
            if info.count:
                t.count = info.count      # partial admission (KEP 420)
        self.suspended = False
        self.started_infos = list(infos)

    def restore_podsets_info(self, infos: Sequence[PodSetInfo]) -> bool:
        changed = False
        for t, orig in zip(self.templates, self._original):
            if (t.node_selector != orig.node_selector
                    or t.count != orig.count
                    or t.tolerations != orig.tolerations
                    or t.labels != orig.labels
                    or t.annotations != orig.annotations):
                t.node_selector = dict(orig.node_selector)
                t.tolerations = list(orig.tolerations)
                t.labels = dict(orig.labels)
                t.annotations = dict(orig.annotations)
                t.count = orig.count
                changed = True
        return changed

    # -- observation ---------------------------------------------------

    def pod_sets(self) -> list[PodSet]:
        return [t.to_pod_set() for t in self.templates]

    def finished(self) -> tuple[str, bool, bool]:
        return "", False, False

    def sync_status_from(self, other: "TemplateJob") -> None:
        for field_name in self.STATUS_FIELDS:
            setattr(self, field_name, getattr(other, field_name))
