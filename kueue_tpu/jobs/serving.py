"""Serving-workload integrations (reference pkg/controller/jobs/
{deployment 207, statefulset 463, leaderworkerset 654} LoC).

Serving workloads never "finish"; they hold quota while scaled up.  A
Deployment is admitted pod-by-pod (each replica is its own workload in
the reference — modeled here as a single resizable workload per scale);
a StatefulSet gangs its replicas; a LeaderWorkerSet admits per-group
(leader + workers).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..api.types import PodSet, Workload
from ..jobframework.interface import (
    ComposableJob,
    IntegrationCallbacks,
    register_integration,
    workload_name_for_job,
)
from .base import PodTemplate, TemplateJob


def _serving_queue_frozen(new, old) -> bool:
    """Shared serving-kind freeze rule ({statefulset,deployment}
    _webhook.go): the queue can move until pods are Ready; removing the
    label is always forbidden."""
    return old.ready_replicas > 0 or not new.queue_name


class StatefulSet(TemplateJob):
    kind = "StatefulSet"

    def __init__(self, name: str, replicas: int,
                 requests: dict[str, int], **kw):
        super().__init__(name, templates=[PodTemplate(
            name="main", count=replicas, requests=dict(requests))], **kw)
        # status mirrors: pods Ready / pods still existing (the webhook
        # consults both, statefulset_webhook.go:140,168)
        self.ready_replicas = 0
        self.status_replicas = 0
        self.deleted = False

    @property
    def replicas(self) -> int:
        """The spec replica count (the template count may be reduced by
        partial admission; _original holds the spec)."""
        return self._original[0].count

    def finished(self) -> tuple[str, bool, bool]:
        if self.deleted:
            return "StatefulSet deleted", True, True
        return "", False, False

    def queue_name_frozen(self, old: "StatefulSet") -> bool:
        return _serving_queue_frozen(self, old)   # statefulset_webhook.go:140

    def validate_on_update(self, old: "StatefulSet") -> list[str]:
        """statefulset_webhook.go:155-171: replicas only scale to/from
        zero (#3279), and not up from zero while the previous
        scale-down is still terminating."""
        errors = []
        if (self.replicas != 0 and old.replicas != 0
                and self.replicas != old.replicas):
            errors.append("spec.replicas: field is immutable "
                          "(only scaling to or from zero is supported)")
        if (old.replicas == 0 and self.replicas > 0
                and old.status_replicas > 0):
            errors.append(
                "spec.replicas: scaling down is still in progress")
        return errors


class Deployment(TemplateJob):
    """Admitted pod-by-pod in the reference (deployment integration);
    each replica is independently gated, so the pod set is resizable
    without re-admission of the whole workload."""

    kind = "Deployment"

    def __init__(self, name: str, replicas: int,
                 requests: dict[str, int], **kw):
        super().__init__(name, templates=[PodTemplate(
            name="main", count=replicas, requests=dict(requests))], **kw)
        self.ready_replicas = 0
        self.deleted = False

    def scale(self, replicas: int) -> None:
        self.templates[0].count = replicas
        self._original[0].count = replicas

    def finished(self) -> tuple[str, bool, bool]:
        if self.deleted:
            return "Deployment deleted", True, True
        return "", False, False

    def queue_name_frozen(self, old: "Deployment") -> bool:
        return _serving_queue_frozen(self, old)   # deployment_webhook.go:131


@dataclass
class LWSGroup:
    index: int
    workers: int
    leader_requests: dict[str, int] = field(default_factory=dict)
    worker_requests: dict[str, int] = field(default_factory=dict)


class LeaderWorkerSet(TemplateJob, ComposableJob):
    """Each group = 1 leader + N workers, gang-admitted per group
    (reference leaderworkerset integration)."""

    kind = "LeaderWorkerSet"

    def __init__(self, name: str, groups: list[LWSGroup], **kw):
        templates = []
        for g in groups:
            templates.append(PodTemplate(
                name=f"group-{g.index}-leader", count=1,
                requests=dict(g.leader_requests)))
            if g.workers:
                templates.append(PodTemplate(
                    name=f"group-{g.index}-workers", count=g.workers,
                    requests=dict(g.worker_requests)))
        super().__init__(name, templates=templates, **kw)
        self.groups = list(groups)
        self.deleted = False

    def construct_composable_workload(self) -> Workload:
        return Workload(
            name=workload_name_for_job(self.kind, self.name),
            namespace=self.namespace, queue_name=self.queue_name,
            pod_sets=[t.to_pod_set() for t in self.templates])

    def list_members(self) -> list:
        return list(self.groups)

    def finished(self) -> tuple[str, bool, bool]:
        if self.deleted:
            return "LeaderWorkerSet deleted", True, True
        return "", False, False


register_integration(IntegrationCallbacks(
    name="statefulset", gvk=StatefulSet.kind, new_job=StatefulSet,
    depends_on=("pod",)))
register_integration(IntegrationCallbacks(
    name="deployment", gvk=Deployment.kind, new_job=Deployment,
    depends_on=("pod",)))
register_integration(IntegrationCallbacks(
    name="leaderworkerset.x-k8s.io/leaderworkerset",
    gvk=LeaderWorkerSet.kind, new_job=LeaderWorkerSet,
    depends_on=("pod",)))
