"""AppWrapper integration (reference pkg/controller/jobs/appwrapper, 361
LoC): a wrapper bundling arbitrary component pod sets into one gang."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..jobframework.interface import IntegrationCallbacks, register_integration
from .base import PodTemplate, TemplateJob


@dataclass
class Component:
    name: str
    count: int = 1
    requests: dict[str, int] = field(default_factory=dict)


class AppWrapper(TemplateJob):
    kind = "AppWrapper"
    STATUS_FIELDS = ("phase",)

    def __init__(self, name: str, components: list[Component], **kw):
        templates = [PodTemplate(name=c.name, count=c.count,
                                 requests=dict(c.requests))
                     for c in components]
        super().__init__(name, templates=templates, **kw)
        self.phase: Optional[str] = None     # Succeeded | Failed

    def mark_phase(self, phase: str) -> None:
        self.phase = phase

    def finished(self) -> tuple[str, bool, bool]:
        if self.phase == "Succeeded":
            return "AppWrapper succeeded", True, True
        if self.phase == "Failed":
            return "AppWrapper failed", False, True
        return "", False, False


register_integration(IntegrationCallbacks(
    name="workload.codeflare.dev/appwrapper", gvk=AppWrapper.kind,
    new_job=AppWrapper))
