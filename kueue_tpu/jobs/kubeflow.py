"""Kubeflow training-operator family + MPIJob (reference
pkg/controller/jobs/kubeflow, 1,165 LoC + mpijob 515 LoC).

All kubeflow kinds share one adapter over replica specs (the reference's
kubeflowjob common adapter): each replica role (Master/Worker/PS/...)
becomes a PodSet.  The reference wires TFJob, PyTorchJob, XGBoostJob,
PaddleJob and JAXJob through this adapter; MPIJob has the same shape with
Launcher/Worker roles.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..jobframework.interface import IntegrationCallbacks, register_integration
from .base import PodTemplate, TemplateJob


@dataclass
class ReplicaSpec:
    role: str                 # e.g. "Master", "Worker", "PS", "Launcher"
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    topology_request: object = None
    # template.spec.priorityClassName (PriorityClass precedence rule,
    # kubeflowjob_controller.go:150-170)
    priority_class_name: str = ""
    # template.metadata annotations (TAS request validation,
    # mpijob_webhook.go:125 validateTopologyRequest)
    annotations: dict[str, str] = field(default_factory=dict)


@dataclass
class SchedulingPolicy:
    """runPolicy.schedulingPolicy (kubeflow common types)."""
    priority_class: str = ""


@dataclass
class RunPolicy:
    """spec.runPolicy — gang-suspension + scheduling policy."""
    suspend: bool = True
    scheduling_policy: Optional[SchedulingPolicy] = None


@dataclass
class ReplicaStatus:
    """status.replicaStatuses[role] (kubeflow common types)."""
    active: int = 0
    succeeded: int = 0
    failed: int = 0


class KubeflowJob(TemplateJob):
    """Common adapter (reference kubeflowjob.KubeflowJob)."""

    kind = "KubeflowJob"
    STATUS_FIELDS = ("condition", "replica_statuses", "job_running")
    # roles ordered first in the workload's pod sets (reference orders
    # Master before Worker for stable PodSet naming)
    role_order: tuple[str, ...] = ()
    # the kind's replica-specs field (reference ReplicaSpecsFieldName,
    # e.g. tfjob_controller.go:116 "tfReplicaSpecs")
    replica_specs_field: str = "replicaSpecs"

    def __init__(self, name: str, replicas: list[ReplicaSpec],
                 run_policy: Optional[RunPolicy] = None, **kw):
        order = {r: i for i, r in enumerate(self.role_order)}
        replicas = sorted(replicas,
                          key=lambda r: order.get(r.role, len(order)))
        templates = [PodTemplate(name=r.role.lower(), count=r.replicas,
                                 requests=dict(r.requests),
                                 annotations=dict(r.annotations),
                                 topology_request=r.topology_request)
                     for r in replicas]
        super().__init__(name, templates=templates, **kw)
        self.replicas = replicas
        self.run_policy = run_policy or RunPolicy()
        self.suspended = self.run_policy.suspend
        self.condition: Optional[tuple[str, bool]] = None  # (message, success)
        # status mirrors (kubeflow common JobStatus)
        self.replica_statuses: dict[str, ReplicaStatus] = {}
        self.job_running = False        # JobRunning condition

    # -- gang suspension rides runPolicy.suspend (controller.go:48-57) --

    def is_suspended(self) -> bool:
        return self.suspended

    def suspend(self) -> None:
        self.suspended = True
        self.run_policy.suspend = True
        self.started_infos = None

    def run_with_podsets_info(self, infos) -> None:
        super().run_with_podsets_info(infos)
        self.run_policy.suspend = False

    @property
    def priority_class_name(self) -> str:
        """PriorityClass precedence (kubeflowjob_controller.go:150-170,
        mirroring mpi-operator's podgroup rule):
        1. runPolicy.schedulingPolicy.priorityClass
        2. the first ordered replica's template priorityClassName
        3. the next replica's, and so on."""
        sp = self.run_policy.scheduling_policy
        if sp is not None and sp.priority_class:
            return sp.priority_class
        for r in self.replicas:        # already in role order
            if r.priority_class_name:
                return r.priority_class_name
        return self._priority_class

    def mark_succeeded(self, message: str = "") -> None:
        self.condition = (message or f"{self.kind} finished", True)
        self.job_running = False

    def mark_failed(self, message: str = "") -> None:
        self.condition = (message or f"{self.kind} failed", False)
        self.job_running = False

    def mark_running(self, per_role_active: Optional[dict] = None) -> None:
        """JobRunning condition + replicaStatuses (the operator's status
        sync; drives PodsReady and IsActive)."""
        self.job_running = True
        for r in self.replicas:
            active = (per_role_active or {}).get(r.role, r.replicas)
            self.replica_statuses[r.role] = ReplicaStatus(active=active)

    def finished(self) -> tuple[str, bool, bool]:
        if self.condition is None:
            return "", False, False
        message, success = self.condition
        return message, success, True

    def pods_ready(self) -> bool:
        """reference kubeflowjob_controller.go:131 PodsReady: the
        JobRunning condition is True."""
        return self.job_running

    def is_active(self) -> bool:
        """reference kubeflowjob_controller.go:123 IsActive: any replica
        status reports active pods."""
        return any(rs.active for rs in self.replica_statuses.values())

    def validate_on_create(self) -> list[str]:
        """Per-kind replica-spec validation (reference
        kubeflowjob_controller.go:182-196 plus the per-kind webhooks'
        replica-type allowlists): roles must be unique, known to the
        kind, and carry a positive replica count.  TAS annotations on
        each replica are checked by the generic job webhook."""
        errors: list[str] = []
        seen: set[str] = set()
        for r in self.replicas:
            path = f"spec.{self.replica_specs_field}[{r.role}]"
            if r.role in seen:
                errors.append(f"{path}: duplicate replica type")
            seen.add(r.role)
            if self.role_order and r.role not in self.role_order:
                errors.append(
                    f"{path}: unsupported replica type for {self.kind}; "
                    f"must be one of {list(self.role_order)}")
            if r.replicas < 1:
                errors.append(f"{path}.replicas: should be >= 1")
        errors.extend(self.validate_topology_request())
        return errors

    def validate_topology_request(self) -> list[str]:
        """TAS request validation per replica template, errors sorted by
        field path (mpijob_webhook.go:125-135 validateTopologyRequest
        over ValidateTASPodSetRequest)."""
        from ..jobframework.webhook import validate_tas_podset_request
        errors: list[str] = []
        for r in self.replicas:
            meta = (f"spec.{self.replica_specs_field}[{r.role}]"
                    f".template.metadata")
            errors.extend(validate_tas_podset_request(
                meta, r.topology_request))
        return sorted(errors)


class TFJob(KubeflowJob):
    kind = "TFJob"
    role_order = ("Master", "Chief", "PS", "Worker", "Evaluator")
    replica_specs_field = "tfReplicaSpecs"


class PyTorchJob(KubeflowJob):
    kind = "PyTorchJob"
    role_order = ("Master", "Worker")
    replica_specs_field = "pytorchReplicaSpecs"


class XGBoostJob(KubeflowJob):
    kind = "XGBoostJob"
    role_order = ("Master", "Worker")
    replica_specs_field = "xgbReplicaSpecs"


class PaddleJob(KubeflowJob):
    kind = "PaddleJob"
    role_order = ("Master", "Worker")
    replica_specs_field = "paddleReplicaSpecs"


class JAXJob(KubeflowJob):
    kind = "JAXJob"
    role_order = ("Worker",)
    replica_specs_field = "jaxReplicaSpecs"


class MPIJob(KubeflowJob):
    kind = "MPIJob"
    role_order = ("Launcher", "Worker")
    replica_specs_field = "mpiReplicaSpecs"


for _cls, _name in [(TFJob, "kubeflow.org/tfjob"),
                    (PyTorchJob, "kubeflow.org/pytorchjob"),
                    (XGBoostJob, "kubeflow.org/xgboostjob"),
                    (PaddleJob, "kubeflow.org/paddlejob"),
                    (JAXJob, "kubeflow.org/jaxjob"),
                    (MPIJob, "kubeflow.org/mpijob")]:
    register_integration(IntegrationCallbacks(
        name=_name, gvk=_cls.kind, new_job=_cls))
