"""Kubeflow training-operator family + MPIJob (reference
pkg/controller/jobs/kubeflow, 1,165 LoC + mpijob 515 LoC).

All kubeflow kinds share one adapter over replica specs (the reference's
kubeflowjob common adapter): each replica role (Master/Worker/PS/...)
becomes a PodSet.  The reference wires TFJob, PyTorchJob, XGBoostJob,
PaddleJob and JAXJob through this adapter; MPIJob has the same shape with
Launcher/Worker roles.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..jobframework.interface import IntegrationCallbacks, register_integration
from .base import PodTemplate, TemplateJob


@dataclass
class ReplicaSpec:
    role: str                 # e.g. "Master", "Worker", "PS", "Launcher"
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    topology_request: object = None


class KubeflowJob(TemplateJob):
    """Common adapter (reference kubeflowjob.KubeflowJob)."""

    kind = "KubeflowJob"
    STATUS_FIELDS = ("condition",)
    # roles ordered first in the workload's pod sets (reference orders
    # Master before Worker for stable PodSet naming)
    role_order: tuple[str, ...] = ()
    # the kind's replica-specs field (reference ReplicaSpecsFieldName,
    # e.g. tfjob_controller.go:116 "tfReplicaSpecs")
    replica_specs_field: str = "replicaSpecs"

    def __init__(self, name: str, replicas: list[ReplicaSpec], **kw):
        order = {r: i for i, r in enumerate(self.role_order)}
        replicas = sorted(replicas,
                          key=lambda r: order.get(r.role, len(order)))
        templates = [PodTemplate(name=r.role.lower(), count=r.replicas,
                                 requests=dict(r.requests),
                                 topology_request=r.topology_request)
                     for r in replicas]
        super().__init__(name, templates=templates, **kw)
        self.replicas = replicas
        self.condition: Optional[tuple[str, bool]] = None  # (message, success)

    def mark_succeeded(self, message: str = "") -> None:
        self.condition = (message or f"{self.kind} finished", True)

    def mark_failed(self, message: str = "") -> None:
        self.condition = (message or f"{self.kind} failed", False)

    def finished(self) -> tuple[str, bool, bool]:
        if self.condition is None:
            return "", False, False
        message, success = self.condition
        return message, success, True

    def validate_on_create(self) -> list[str]:
        """Per-kind replica-spec validation (reference
        kubeflowjob_controller.go:182-196 plus the per-kind webhooks'
        replica-type allowlists): roles must be unique, known to the
        kind, and carry a positive replica count.  TAS annotations on
        each replica are checked by the generic job webhook."""
        errors: list[str] = []
        seen: set[str] = set()
        for r in self.replicas:
            path = f"spec.{self.replica_specs_field}[{r.role}]"
            if r.role in seen:
                errors.append(f"{path}: duplicate replica type")
            seen.add(r.role)
            if self.role_order and r.role not in self.role_order:
                errors.append(
                    f"{path}: unsupported replica type for {self.kind}; "
                    f"must be one of {list(self.role_order)}")
            if r.replicas < 1:
                errors.append(f"{path}.replicas: should be >= 1")
        return errors


class TFJob(KubeflowJob):
    kind = "TFJob"
    role_order = ("Master", "Chief", "PS", "Worker", "Evaluator")
    replica_specs_field = "tfReplicaSpecs"


class PyTorchJob(KubeflowJob):
    kind = "PyTorchJob"
    role_order = ("Master", "Worker")
    replica_specs_field = "pytorchReplicaSpecs"


class XGBoostJob(KubeflowJob):
    kind = "XGBoostJob"
    role_order = ("Master", "Worker")
    replica_specs_field = "xgbReplicaSpecs"


class PaddleJob(KubeflowJob):
    kind = "PaddleJob"
    role_order = ("Master", "Worker")
    replica_specs_field = "paddleReplicaSpecs"


class JAXJob(KubeflowJob):
    kind = "JAXJob"
    role_order = ("Worker",)
    replica_specs_field = "jaxReplicaSpecs"


class MPIJob(KubeflowJob):
    kind = "MPIJob"
    role_order = ("Launcher", "Worker")
    replica_specs_field = "mpiReplicaSpecs"


for _cls, _name in [(TFJob, "kubeflow.org/tfjob"),
                    (PyTorchJob, "kubeflow.org/pytorchjob"),
                    (XGBoostJob, "kubeflow.org/xgboostjob"),
                    (PaddleJob, "kubeflow.org/paddlejob"),
                    (JAXJob, "kubeflow.org/jaxjob"),
                    (MPIJob, "kubeflow.org/mpijob")]:
    register_integration(IntegrationCallbacks(
        name=_name, gvk=_cls.kind, new_job=_cls))
