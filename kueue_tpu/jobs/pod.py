"""Pod and pod-group integration (reference pkg/controller/jobs/pod).

A plain Pod is gated with a scheduling gate instead of a suspend flag
(pods can't be suspended); a PodGroup is a ComposableJob building one
Workload from N pods that share the group name/total-count annotations
(reference pod/constants/constants.go:27-33), ungated together on
admission.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..api.types import PodSet, Workload
from ..webhooks.validation import valid_dns1123_label
from ..jobframework.interface import (
    ComposableJob,
    GenericJob,
    IntegrationCallbacks,
    register_integration,
    workload_name_for_job,
)
from ..podset import PodSetInfo

SCHEDULING_GATE = "kueue.x-k8s.io/admission"
GROUP_NAME_LABEL = "kueue.x-k8s.io/pod-group-name"
GROUP_TOTAL_COUNT_ANNOTATION = "kueue.x-k8s.io/pod-group-total-count"
ROLE_HASH_ANNOTATION = "kueue.x-k8s.io/role-hash"
MANAGED_LABEL = "kueue.x-k8s.io/managed"
RETRIABLE_IN_GROUP_ANNOTATION = "kueue.x-k8s.io/retriable-in-group"


@dataclass
class Pod:
    """A bare pod object."""
    name: str
    namespace: str = "default"
    requests: dict[str, int] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    scheduling_gates: list[str] = field(default_factory=lambda: [SCHEDULING_GATE])
    phase: str = "Pending"    # Pending | Running | Succeeded | Failed

    @property
    def gated(self) -> bool:
        return SCHEDULING_GATE in self.scheduling_gates

    def ungate(self) -> None:
        if SCHEDULING_GATE in self.scheduling_gates:
            self.scheduling_gates.remove(SCHEDULING_GATE)
            self.phase = "Running"

    def gate(self) -> None:
        if SCHEDULING_GATE not in self.scheduling_gates:
            self.scheduling_gates.append(SCHEDULING_GATE)
        self.phase = "Pending"

    @property
    def role_hash(self) -> str:
        import hashlib
        key = (tuple(sorted(self.requests.items())),
               tuple(sorted(self.node_selector.items())))
        return hashlib.sha256(repr(key).encode()).hexdigest()[:8]


def default_pod(pod: Pod, queue: str = "") -> None:
    """Pod webhook Default(): inject the scheduling gate, the managed
    label, and — for group members — the role-hash annotation
    (reference pod_webhook.go Default)."""
    if pod.phase == "Pending" and SCHEDULING_GATE not in pod.scheduling_gates:
        pod.scheduling_gates.append(SCHEDULING_GATE)
    pod.labels.setdefault(MANAGED_LABEL, "true")
    if queue:
        pod.labels.setdefault("kueue.x-k8s.io/queue-name", queue)
    if pod.labels.get(GROUP_NAME_LABEL):
        pod.annotations.setdefault(ROLE_HASH_ANNOTATION, pod.role_hash)


def validate_pod_create(pod: Pod) -> list[str]:
    """Pod webhook ValidateCreate (reference pod_webhook.go:274-339):
    managed-label value, group-metadata pairing, total-count syntax."""
    errors: list[str] = []
    managed = pod.labels.get(MANAGED_LABEL)
    if managed is not None and managed != "true":
        errors.append(
            f"metadata.labels[{MANAGED_LABEL}]: "
            "managed label value can only be 'true'")
    group = pod.labels.get(GROUP_NAME_LABEL, "")
    gtc = pod.annotations.get(GROUP_TOTAL_COUNT_ANNOTATION)
    if not group:
        if gtc is not None:
            errors.append(
                f"metadata.labels[{GROUP_NAME_LABEL}]: both the "
                f"'{GROUP_TOTAL_COUNT_ANNOTATION}' annotation and the "
                f"'{GROUP_NAME_LABEL}' label should be set")
    else:
        if not valid_dns1123_label(group):
            errors.append(
                f"metadata.labels[{GROUP_NAME_LABEL}]: {group!r} "
                "must be a DNS-1123 label")
        if gtc is None:
            errors.append(
                f"metadata.annotations[{GROUP_TOTAL_COUNT_ANNOTATION}]: "
                f"both the '{GROUP_TOTAL_COUNT_ANNOTATION}' annotation and "
                f"the '{GROUP_NAME_LABEL}' label should be set")
        else:
            try:
                if int(gtc) < 1:
                    errors.append(
                        f"metadata.annotations"
                        f"[{GROUP_TOTAL_COUNT_ANNOTATION}]: "
                        "should be greater than or equal to 1")
            except ValueError:
                errors.append(
                    f"metadata.annotations[{GROUP_TOTAL_COUNT_ANNOTATION}]: "
                    f"{gtc!r} is not a valid integer")
    retriable = pod.annotations.get(RETRIABLE_IN_GROUP_ANNOTATION)
    if retriable is not None and retriable not in ("true", "false"):
        errors.append(
            f"metadata.annotations[{RETRIABLE_IN_GROUP_ANNOTATION}]: "
            "value can only be 'true' or 'false'")
    return errors


def validate_pod_update(old: Pod, new: Pod) -> list[str]:
    """Pod webhook ValidateUpdate — only the update-specific rules: the
    one-way retriable-in-group transition (pod_webhook.go:341-348) and
    group-name immutability.  Create rules run separately (the generic
    job webhook re-applies them on every update)."""
    errors: list[str] = []
    if new.labels.get(GROUP_NAME_LABEL):
        old_unretriable = old.annotations.get(
            RETRIABLE_IN_GROUP_ANNOTATION) == "false"
        new_unretriable = new.annotations.get(
            RETRIABLE_IN_GROUP_ANNOTATION) == "false"
        if old_unretriable and not new_unretriable:
            errors.append(
                f"metadata.annotations[{RETRIABLE_IN_GROUP_ANNOTATION}]: "
                "unretriable pod group can't be converted to retriable")
    if old.labels.get(GROUP_NAME_LABEL) != new.labels.get(GROUP_NAME_LABEL):
        errors.append(
            f"metadata.labels[{GROUP_NAME_LABEL}]: field is immutable")
    return errors


class PlainPod(GenericJob):
    """A single gated pod (reference pod integration, non-group mode)."""

    kind = "Pod"

    def __init__(self, pod: Pod, queue: str = ""):
        self.pod = pod
        self.queue = queue
        default_pod(pod, queue)

    @property
    def name(self) -> str:
        return self.pod.name

    @property
    def namespace(self) -> str:
        return self.pod.namespace

    @property
    def gvk(self) -> str:
        return self.kind

    def is_suspended(self) -> bool:
        return self.pod.gated

    def suspend(self) -> None:
        # a running pod cannot be re-gated; stopping means deletion in the
        # reference (pod_controller.go Stop) — model as re-gate for replay
        self.pod.gate()

    def run_with_podsets_info(self, infos: Sequence[PodSetInfo]) -> None:
        if infos:
            self.pod.node_selector.update(infos[0].node_selector)
        self.pod.ungate()

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="main", count=1,
                       requests=dict(self.pod.requests),
                       node_selector=dict(self.pod.node_selector))]

    def finished(self) -> tuple[str, bool, bool]:
        if self.pod.phase == "Succeeded":
            return "Pod succeeded", True, True
        if self.pod.phase == "Failed":
            return "Pod failed", False, True
        return "", False, False

    def validate_on_create(self) -> list[str]:
        return validate_pod_create(self.pod)

    def validate_on_update(self, old: "PlainPod") -> list[str]:
        return validate_pod_update(old.pod, self.pod)

    def is_active(self) -> bool:
        return self.pod.phase == "Running"

    def pods_ready(self) -> bool:
        return self.pod.phase == "Running"


class PodGroup(GenericJob, ComposableJob):
    """N pods forming one gang-admitted workload (reference pod/pod_controller.go
    ComposableJob implementation, the largest integration at 2,107 LoC)."""

    kind = "PodGroup"

    def __init__(self, group_name: str, total_count: int,
                 namespace: str = "default", queue: str = ""):
        self.group_name = group_name
        self.total_count = total_count
        self._namespace = namespace
        self.queue = queue
        self.pods: list[Pod] = []

    # -- membership ----------------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        pod.labels[GROUP_NAME_LABEL] = self.group_name
        pod.annotations[GROUP_TOTAL_COUNT_ANNOTATION] = str(self.total_count)
        default_pod(pod, self.queue)
        self.pods.append(pod)

    def list_members(self) -> list:
        return list(self.pods)

    # -- identity ------------------------------------------------------

    @property
    def name(self) -> str:
        return self.group_name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def gvk(self) -> str:
        return self.kind

    # -- composable workload -------------------------------------------

    def _roles(self) -> list[tuple[str, list[Pod]]]:
        """Group pods by role hash; stable order by first occurrence."""
        roles: dict[str, list[Pod]] = {}
        for p in self.pods:
            roles.setdefault(p.role_hash, []).append(p)
        return list(roles.items())

    def construct_composable_workload(self) -> Workload:
        pod_sets = []
        seen = 0
        roles = self._roles()
        for i, (role, pods) in enumerate(roles):
            count = len(pods)
            if i == len(roles) - 1:
                # the final role absorbs not-yet-created pods so the gang
                # totals the declared group size (expectations pattern,
                # pkg/util/expectations)
                count += self.total_count - len(self.pods)
            seen += count
            pod_sets.append(PodSet(
                name=f"role-{role}", count=count,
                requests=dict(pods[0].requests),
                node_selector=dict(pods[0].node_selector)))
        return Workload(
            name=workload_name_for_job(self.kind, self.group_name),
            namespace=self._namespace, queue_name=self.queue,
            pod_sets=pod_sets)

    # -- gating --------------------------------------------------------

    def is_suspended(self) -> bool:
        return any(p.gated for p in self.pods)

    def suspend(self) -> None:
        for p in self.pods:
            if p.phase not in ("Succeeded", "Failed"):
                p.gate()

    def run_with_podsets_info(self, infos: Sequence[PodSetInfo]) -> None:
        by_name = {i.name: i for i in infos}
        for role, pods in self._roles():
            info = by_name.get(f"role-{role}")
            for p in pods:
                if info is not None:
                    p.node_selector.update(info.node_selector)
                p.ungate()

    # -- observation ---------------------------------------------------

    def pod_sets(self) -> list[PodSet]:
        return self.construct_composable_workload().pod_sets

    def finished(self) -> tuple[str, bool, bool]:
        if len(self.pods) < self.total_count:
            return "", False, False
        done = [p for p in self.pods if p.phase in ("Succeeded", "Failed")]
        if len(done) < self.total_count:
            return "", False, False
        success = all(p.phase == "Succeeded" for p in done)
        return ("Pods succeeded" if success else "Some pods failed",
                success, True)

    def is_active(self) -> bool:
        return any(p.phase == "Running" for p in self.pods)

    def pods_ready(self) -> bool:
        running = sum(1 for p in self.pods if p.phase == "Running")
        return running >= self.total_count

    def validate_on_create(self) -> list[str]:
        errors: list[str] = []
        if self.total_count < 1:
            errors.append("pod-group total count: should be >= 1")
        if not valid_dns1123_label(self.group_name):
            errors.append(
                f"pod-group name: {self.group_name!r} must be a "
                "DNS-1123 label")
        for p in self.pods:
            errors.extend(validate_pod_create(p))
            declared = p.annotations.get(GROUP_TOTAL_COUNT_ANNOTATION)
            if declared is not None and declared != str(self.total_count):
                errors.append(
                    f"pod {p.name}: group-total-count annotation "
                    f"{declared!r} disagrees with the group size "
                    f"{self.total_count}")
        if len(self.pods) > self.total_count:
            errors.append(
                f"pod-group {self.group_name}: {len(self.pods)} member "
                f"pods exceed the declared total count {self.total_count}")
        return errors


register_integration(IntegrationCallbacks(
    name="pod", gvk=PlainPod.kind, new_job=PlainPod))
register_integration(IntegrationCallbacks(
    name="pod-group", gvk=PodGroup.kind, new_job=PodGroup,
    depends_on=("pod",)))
