"""batch/v1 Job integration (reference pkg/controller/jobs/job).

Suspend-based gating, partial admission by scaling parallelism (the
reference syncs the original parallelism via an annotation,
job_controller.go), reclaimable pods from the succeeded count (KEP 78),
and a MultiKueue adapter surface via JobWithManagedBy.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..api.types import PodSet
from ..jobframework.interface import (
    IntegrationCallbacks,
    JobWithReclaimablePods,
    register_integration,
)
from ..podset import PodSetInfo
from .base import PodTemplate, TemplateJob


class BatchJob(TemplateJob, JobWithReclaimablePods):
    kind = "BatchJob"
    STATUS_FIELDS = ("succeeded", "failed_message", "parallelism")

    def __init__(self, name: str, parallelism: int = 1,
                 completions: Optional[int] = None,
                 min_parallelism: Optional[int] = None,
                 requests: Optional[dict[str, int]] = None, **kw):
        template = PodTemplate(name="main", count=parallelism,
                               requests=dict(requests or {}))
        super().__init__(name, templates=[template], **kw)
        self.parallelism = parallelism
        self.completions = completions if completions is not None else parallelism
        self.min_parallelism = min_parallelism  # partial admission floor
        self.succeeded = 0
        self.failed_message: Optional[str] = None

    # -- pod sets ------------------------------------------------------

    def pod_sets(self) -> list[PodSet]:
        ps = self.templates[0].to_pod_set()
        if self.min_parallelism is not None:
            ps.min_count = self.min_parallelism
        return [ps]

    def run_with_podsets_info(self, infos: Sequence[PodSetInfo]) -> None:
        super().run_with_podsets_info(infos)
        if infos and infos[0].count:
            # partial admission scales parallelism (reference job
            # integration syncs via annotation)
            self.parallelism = infos[0].count

    def restore_podsets_info(self, infos: Sequence[PodSetInfo]) -> bool:
        changed = super().restore_podsets_info(infos)
        if self.parallelism != self._original[0].count:
            self.parallelism = self._original[0].count
            changed = True
        return changed

    # -- execution-side events -----------------------------------------

    def complete_pods(self, n: int = 1) -> None:
        self.succeeded = min(self.completions, self.succeeded + n)

    def fail(self, message: str = "BackoffLimitExceeded") -> None:
        self.failed_message = message

    # -- observation ---------------------------------------------------

    def finished(self) -> tuple[str, bool, bool]:
        if self.failed_message is not None:
            return self.failed_message, False, True
        if self.succeeded >= self.completions:
            return "Job finished successfully", True, True
        return "", False, False

    def pods_ready(self) -> bool:
        return not self.suspended

    def reclaimable_pods(self) -> dict[str, int]:
        """Pods that succeeded no longer need quota (KEP 78)."""
        remaining = self.completions - self.succeeded
        if remaining >= self.parallelism:
            return {}
        return {"main": self.parallelism - remaining}


register_integration(IntegrationCallbacks(
    name="batch/job", gvk=BatchJob.kind, new_job=BatchJob))
