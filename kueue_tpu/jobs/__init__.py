"""Concrete job integrations (reference pkg/controller/jobs/*).

Importing this package registers every built-in integration with the
jobframework registry, mirroring the reference's blank-import pattern
(pkg/controller/jobs/jobs.go:12-23).  The set mirrors the reference's 11
frameworks: batch Job, Pod (+ pod groups), JobSet, the Kubeflow family
(TFJob/PyTorchJob/XGBoostJob/PaddleJob/JAXJob), MPIJob, RayJob,
RayCluster, AppWrapper, LeaderWorkerSet, StatefulSet, Deployment.
"""

from .batch_job import BatchJob
from .pod import PlainPod, PodGroup
from .jobset import JobSet, ReplicatedJobSpec
from .kubeflow import (
    JAXJob,
    MPIJob,
    PaddleJob,
    PyTorchJob,
    ReplicaSpec,
    TFJob,
    XGBoostJob,
)
from .ray import RayCluster, RayJob
from .appwrapper import AppWrapper
from .serving import Deployment, LeaderWorkerSet, StatefulSet

__all__ = [
    "AppWrapper", "BatchJob", "Deployment", "JAXJob", "JobSet",
    "LeaderWorkerSet", "MPIJob", "PaddleJob", "PlainPod", "PodGroup",
    "PyTorchJob", "RayCluster", "RayJob", "ReplicaSpec",
    "ReplicatedJobSpec", "StatefulSet", "TFJob", "XGBoostJob",
]
