"""JobSet integration (reference pkg/controller/jobs/jobset, 522 LoC).

A JobSet is a list of replicated jobs; each replicated job contributes
one PodSet with count = replicas × parallelism.  Suspend/resume toggles
the whole set; success requires every replicated job to succeed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..jobframework.interface import (
    IntegrationCallbacks,
    JobWithReclaimablePods,
    register_integration,
)
from .base import PodTemplate, TemplateJob


@dataclass
class ReplicatedJobSpec:
    name: str
    replicas: int = 1
    parallelism: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    topology_request: object = None


class JobSet(TemplateJob, JobWithReclaimablePods):
    kind = "JobSet"
    STATUS_FIELDS = ("succeeded", "failed_message")

    def __init__(self, name: str, replicated_jobs: list[ReplicatedJobSpec],
                 **kw):
        templates = [
            PodTemplate(name=rj.name, count=rj.replicas * rj.parallelism,
                        requests=dict(rj.requests),
                        topology_request=rj.topology_request)
            for rj in replicated_jobs]
        super().__init__(name, templates=templates, **kw)
        self.replicated_jobs = list(replicated_jobs)
        self.succeeded: dict[str, int] = {}   # replicated-job name → pods done
        self.failed_message: Optional[str] = None

    def complete_replicated_job(self, name: str) -> None:
        for rj in self.replicated_jobs:
            if rj.name == name:
                self.succeeded[name] = rj.replicas * rj.parallelism

    def fail(self, message: str = "JobSet failed") -> None:
        self.failed_message = message

    def finished(self) -> tuple[str, bool, bool]:
        if self.failed_message is not None:
            return self.failed_message, False, True
        total = {rj.name: rj.replicas * rj.parallelism
                 for rj in self.replicated_jobs}
        if all(self.succeeded.get(n, 0) >= c for n, c in total.items()):
            return "JobSet finished successfully", True, True
        return "", False, False

    def reclaimable_pods(self) -> dict[str, int]:
        return {n: c for n, c in self.succeeded.items() if c > 0}

    def validate_on_create(self) -> list[str]:
        """jobset_webhook.go rules: replicated-job names must be unique
        and each must request at least one pod."""
        errors = []
        seen: set[str] = set()
        for i, rj in enumerate(self.replicated_jobs):
            path = f"spec.replicatedJobs[{i}]"
            if rj.name in seen:
                errors.append(f"{path}.name: duplicate replicated job "
                              f"{rj.name!r}")
            seen.add(rj.name)
            if rj.replicas < 1:
                errors.append(f"{path}.replicas: should be >= 1")
            if rj.parallelism < 1:
                errors.append(
                    f"{path}.template.spec.parallelism: should be >= 1")
        return errors


register_integration(IntegrationCallbacks(
    name="jobset.x-k8s.io/jobset", gvk=JobSet.kind, new_job=JobSet))
