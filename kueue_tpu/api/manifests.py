"""Kueue-shaped YAML manifest codec.

Parses the reference CRD manifests (apiVersion kueue.x-k8s.io/v1beta1 /
v1alpha1) into our API dataclasses and back, so existing kueue YAML
(examples/admin/*.yaml, user job manifests) drives this framework
unchanged.  Shape parity with apis/kueue/v1beta1/*_types.go.

CPU-family quantities parse to milli-units ("9" → 9000, "500m" → 500);
everything else to absolute integers ("36Gi" → bytes).
"""

from __future__ import annotations

from typing import Any, Optional

from .quantity import format_milli, parse_quantity
from .types import (
    AdmissionCheck,
    BorrowWithinCohort,
    BorrowWithinCohortPolicy,
    ClusterQueue,
    Cohort,
    FairSharing,
    FlavorFungibility,
    FlavorFungibilityPolicy,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PodSetTopologyRequest,
    PreemptionPolicy,
    QueueingStrategy,
    ReclaimWithinCohort,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    StopPolicy,
    Toleration,
    Topology,
    WithinClusterQueue,
    Workload,
    WorkloadPriorityClass,
)

_MILLI_RESOURCES = {"cpu"}


def _parse_qty(resource: str, value: Any) -> int:
    return parse_quantity(value, milli=resource in _MILLI_RESOURCES)


def _format_qty(resource: str, value: int) -> str:
    if resource in _MILLI_RESOURCES:
        return format_milli(value)
    return str(value)


class ManifestError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def from_manifest(doc: dict):
    """One YAML document → API object (dispatch on kind)."""
    kind = doc.get("kind", "")
    decoder = _DECODERS.get(kind)
    if decoder is None:
        raise ManifestError(f"unsupported kind {kind!r}")
    return decoder(doc)


def load_manifests(text: str) -> list:
    """Parse a (multi-document) YAML string."""
    import yaml
    return [from_manifest(doc)
            for doc in yaml.safe_load_all(text) if doc]


def _meta(doc: dict) -> tuple[str, str]:
    meta = doc.get("metadata") or {}
    return meta.get("name", ""), meta.get("namespace", "default")


def _decode_cluster_queue(doc: dict) -> ClusterQueue:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    groups = []
    for rg in spec.get("resourceGroups", []):
        covered = list(rg.get("coveredResources", []))
        flavors = []
        for f in rg.get("flavors", []):
            resources = {}
            for r in f.get("resources", []):
                rname = r["name"]
                resources[rname] = ResourceQuota(
                    nominal=_parse_qty(rname, r.get("nominalQuota", 0)),
                    borrowing_limit=(
                        _parse_qty(rname, r["borrowingLimit"])
                        if "borrowingLimit" in r else None),
                    lending_limit=(
                        _parse_qty(rname, r["lendingLimit"])
                        if "lendingLimit" in r else None))
            flavors.append(FlavorQuotas(name=f["name"], resources=resources))
        groups.append(ResourceGroup(covered_resources=covered,
                                    flavors=flavors))
    pre = spec.get("preemption") or {}
    bwc = pre.get("borrowWithinCohort") or {}
    ff = spec.get("flavorFungibility") or {}
    fs = spec.get("fairSharing") or {}
    return ClusterQueue(
        name=name,
        cohort=spec.get("cohort") or None,
        queueing_strategy=QueueingStrategy(
            spec.get("queueingStrategy", "BestEffortFIFO")),
        # nil selector matches nothing; {} matches everything
        namespace_selector=(
            spec["namespaceSelector"].get("matchLabels", {})
            if spec.get("namespaceSelector") is not None else None),
        resource_groups=groups,
        preemption=PreemptionPolicy(
            reclaim_within_cohort=ReclaimWithinCohort(
                pre.get("reclaimWithinCohort", "Never")),
            within_cluster_queue=WithinClusterQueue(
                pre.get("withinClusterQueue", "Never")),
            borrow_within_cohort=BorrowWithinCohort(
                policy=BorrowWithinCohortPolicy(
                    bwc.get("policy", "Never")),
                max_priority_threshold=bwc.get("maxPriorityThreshold"))),
        flavor_fungibility=FlavorFungibility(
            when_can_borrow=FlavorFungibilityPolicy(
                ff.get("whenCanBorrow", "Borrow")),
            when_can_preempt=FlavorFungibilityPolicy(
                ff.get("whenCanPreempt", "TryNextFlavor"))),
        admission_checks=list(spec.get("admissionChecks", [])),
        fair_sharing=(FairSharing(weight=fs.get("weight"))
                      if fs else None),
        stop_policy=StopPolicy(spec.get("stopPolicy", "None")),
    )


def _decode_local_queue(doc: dict) -> LocalQueue:
    name, namespace = _meta(doc)
    spec = doc.get("spec") or {}
    return LocalQueue(name=name, namespace=namespace,
                      cluster_queue=spec.get("clusterQueue", ""),
                      stop_policy=StopPolicy(spec.get("stopPolicy", "None")))


def _decode_resource_flavor(doc: dict) -> ResourceFlavor:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    return ResourceFlavor(
        name=name,
        node_labels=dict(spec.get("nodeLabels", {})),
        node_taints=[Taint_from(t) for t in spec.get("nodeTaints", [])],
        tolerations=[_decode_toleration(t)
                     for t in spec.get("tolerations", [])],
        topology_name=spec.get("topologyName", ""))


def Taint_from(t: dict):
    from .types import Taint
    return Taint(key=t.get("key", ""), value=t.get("value", ""),
                 effect=t.get("effect", ""))


def _decode_toleration(t: dict) -> Toleration:
    return Toleration(key=t.get("key", ""),
                      operator=t.get("operator", "Equal"),
                      value=t.get("value", ""),
                      effect=t.get("effect", ""))


def _decode_workload(doc: dict) -> Workload:
    name, namespace = _meta(doc)
    spec = doc.get("spec") or {}
    pod_sets = []
    for ps in spec.get("podSets", []):
        template_spec = ((ps.get("template") or {}).get("spec") or {})
        containers = template_spec.get("containers", [])
        requests: dict[str, int] = {}
        limits: dict[str, int] = {}
        for c in containers:
            resources = c.get("resources") or {}
            c_req = {r: _parse_qty(r, v)
                     for r, v in (resources.get("requests") or {}).items()}
            c_lim = {r: _parse_qty(r, v)
                     for r, v in (resources.get("limits") or {}).items()}
            for rname, v in c_req.items():
                requests[rname] = requests.get(rname, 0) + v
            if len(containers) == 1:
                limits = c_lim
            else:
                # requests<=limits is PER CONTAINER (workload.go
                # RequestsMustNotExceedLimitMessage); the aggregate can't
                # express that, so record only a violating container's
                # limit — the aggregate request is then guaranteed to
                # exceed it and the scheduler rejects, while clean
                # multi-container pods carry no limit entry at all
                for rname, lim in c_lim.items():
                    if c_req.get(rname, 0) > lim:
                        limits[rname] = lim
        tr = ps.get("topologyRequest") or {}
        pod_sets.append(PodSet(
            name=ps.get("name", "main"),
            count=ps.get("count", 1),
            min_count=ps.get("minCount"),
            requests=requests,
            limits=limits,
            node_selector=dict(template_spec.get("nodeSelector", {})),
            tolerations=[_decode_toleration(t)
                         for t in template_spec.get("tolerations", [])],
            topology_request=(PodSetTopologyRequest(
                required=tr.get("required"),
                preferred=tr.get("preferred"),
                unconstrained=bool(tr.get("unconstrained", False)))
                if tr else None)))
    meta = doc.get("metadata") or {}
    wl = Workload(
        name=name, namespace=namespace,
        queue_name=spec.get("queueName", ""),
        priority=spec.get("priority", 0),
        priority_class_name=spec.get("priorityClassName", ""),
        active=spec.get("active", True),
        creation_time=float(meta.get("creationTimestamp") or 0.0),
        pod_sets=pod_sets,
        maximum_execution_time_seconds=spec.get(
            "maximumExecutionTimeSeconds"))
    status = doc.get("status") or {}
    adm = status.get("admission")
    if adm:
        from .types import Admission, PodSetAssignment
        wl.admission = Admission(
            cluster_queue=adm.get("clusterQueue", ""),
            pod_set_assignments=[
                PodSetAssignment(
                    name=a.get("name", ""),
                    count=a.get("count", 0),
                    flavors=dict(a.get("flavors", {})),
                    resource_usage={
                        r: _parse_qty(r, v)
                        for r, v in (a.get("resourceUsage") or {}).items()})
                for a in adm.get("podSetAssignments", [])])
    for c in status.get("conditions", []):
        from .types import Condition, ConditionStatus
        wl.conditions[c["type"]] = Condition(
            type=c["type"],
            status=ConditionStatus(c.get("status", "True")),
            reason=c.get("reason", ""), message=c.get("message", ""),
            last_transition_time=c.get("lastTransitionTime", 0.0))
    return wl


def _decode_cohort(doc: dict) -> Cohort:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    fs = spec.get("fairSharing") or {}
    return Cohort(name=name,
                  parent_name=spec.get("parentName") or spec.get("parent"),
                  fair_sharing=(FairSharing(weight=fs.get("weight"))
                                if fs else None))


def _decode_admission_check(doc: dict) -> AdmissionCheck:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    return AdmissionCheck(name=name,
                          controller_name=spec.get("controllerName", ""),
                          parameters=spec.get("parameters"))


def _decode_priority_class(doc: dict) -> WorkloadPriorityClass:
    name, _ = _meta(doc)
    return WorkloadPriorityClass(name=name, value=doc.get("value", 0),
                                 description=doc.get("description", ""))


def _decode_topology(doc: dict) -> Topology:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    return Topology(name=name,
                    levels=[lv.get("nodeLabel", "")
                            for lv in spec.get("levels", [])])


_DECODERS = {
    "ClusterQueue": _decode_cluster_queue,
    "LocalQueue": _decode_local_queue,
    "ResourceFlavor": _decode_resource_flavor,
    "Workload": _decode_workload,
    "Cohort": _decode_cohort,
    "AdmissionCheck": _decode_admission_check,
    "WorkloadPriorityClass": _decode_priority_class,
    "Topology": _decode_topology,
}


# ---------------------------------------------------------------------------
# Encode
# ---------------------------------------------------------------------------

def to_manifest(obj) -> dict:
    if isinstance(obj, ClusterQueue):
        return _encode_cluster_queue(obj)
    if isinstance(obj, LocalQueue):
        return {"apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "LocalQueue",
                "metadata": {"name": obj.name, "namespace": obj.namespace},
                "spec": {"clusterQueue": obj.cluster_queue}}
    if isinstance(obj, ResourceFlavor):
        return {"apiVersion": "kueue.x-k8s.io/v1beta1",
                "kind": "ResourceFlavor",
                "metadata": {"name": obj.name},
                "spec": {"nodeLabels": dict(obj.node_labels),
                         "topologyName": obj.topology_name or None}}
    if isinstance(obj, Workload):
        return _encode_workload(obj)
    raise ManifestError(f"unsupported object {type(obj).__name__}")


def _encode_cluster_queue(cq: ClusterQueue) -> dict:
    groups = []
    for rg in cq.resource_groups:
        flavors = []
        for f in rg.flavors:
            resources = []
            for rname, q in f.resources.items():
                r: dict[str, Any] = {"name": rname,
                                     "nominalQuota": _format_qty(rname,
                                                                 q.nominal)}
                if q.borrowing_limit is not None:
                    r["borrowingLimit"] = _format_qty(rname, q.borrowing_limit)
                if q.lending_limit is not None:
                    r["lendingLimit"] = _format_qty(rname, q.lending_limit)
                resources.append(r)
            flavors.append({"name": f.name, "resources": resources})
        groups.append({"coveredResources": list(rg.covered_resources),
                       "flavors": flavors})
    return {"apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "ClusterQueue",
            "metadata": {"name": cq.name},
            "spec": {"cohort": cq.cohort,
                     "queueingStrategy": str(cq.queueing_strategy.value),
                     "resourceGroups": groups}}


def _encode_workload(wl: Workload) -> dict:
    pod_sets = []
    for ps in wl.pod_sets:
        pod_sets.append({
            "name": ps.name, "count": ps.count,
            **({"minCount": ps.min_count} if ps.min_count else {}),
            "template": {"spec": {
                "containers": [{"name": "main", "resources": {
                    "requests": {
                        r: _format_qty(r, v) for r, v in ps.requests.items()
                        if r != "pods"},
                    **({"limits": {r: _format_qty(r, v)
                                   for r, v in ps.limits.items()}}
                       if ps.limits else {})}}],
                **({"nodeSelector": dict(ps.node_selector)}
                   if ps.node_selector else {}),
            }}})
    status: dict[str, Any] = {}
    if wl.admission is not None:
        status["admission"] = {
            "clusterQueue": wl.admission.cluster_queue,
            "podSetAssignments": [
                {"name": a.name, "count": a.count,
                 "flavors": dict(a.flavors),
                 "resourceUsage": {r: _format_qty(r, v)
                                   for r, v in a.resource_usage.items()}}
                for a in wl.admission.pod_set_assignments]}
    if wl.conditions:
        status["conditions"] = [
            {"type": c.type, "status": str(c.status.value),
             "reason": c.reason, "message": c.message,
             "lastTransitionTime": c.last_transition_time}
            for c in wl.conditions.values()]
    return {"apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
            "metadata": {"name": wl.name, "namespace": wl.namespace,
                         # creation order must survive transport: a
                         # worker rebuilt from journaled manifests has
                         # no other source for the FIFO key
                         **({"creationTimestamp": wl.creation_time}
                            if wl.creation_time else {})},
            "spec": {"queueName": wl.queue_name, "priority": wl.priority,
                     "active": wl.active, "podSets": pod_sets},
            **({"status": status} if status else {})}
