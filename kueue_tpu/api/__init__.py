from . import quantity, types  # noqa: F401
from .types import *  # noqa: F401,F403
from .quantity import parse_quantity, format_milli  # noqa: F401
