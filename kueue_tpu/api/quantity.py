"""Kubernetes-style resource quantity parsing.

Capability parity with k8s.io/apimachinery resource.Quantity as used by the
reference (Kueue stores quantities as int64 milli-units for cpu and plain
units for everything else; see reference pkg/resources/requests.go).

We normalise every quantity to an integer number of *milli-units* so that
"250m" cpu == 250 and "1" cpu == 1000.  For non-cpu resources Kueue uses
whole units (bytes for memory); we keep the same convention via
``parse_quantity(value, milli=False)``.
"""

from __future__ import annotations

import re
from fractions import Fraction

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:[eE](?P<exp>[+-]?\d+))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]?)$"
)


def parse_quantity(value: int | float | str, *, milli: bool = True) -> int:
    """Parse a k8s quantity into integer units.

    With ``milli=True`` (default) the result is in milli-units (cpu
    convention); with ``milli=False`` the result is in whole units rounded
    up (memory/pods convention, matching resource.Quantity.Value()).
    """
    if isinstance(value, bool):  # bool is an int subclass; reject it
        raise ValueError(f"invalid quantity: {value!r}")
    if isinstance(value, int):
        frac = Fraction(value)
    elif isinstance(value, float):
        frac = Fraction(value).limit_denominator(10**9)
    else:
        text = value.strip()
        m = _QUANTITY_RE.match(text)
        if not m:
            raise ValueError(f"invalid quantity: {value!r}")
        num = Fraction(m.group("num"))
        if m.group("exp"):
            exp = int(m.group("exp"))
            num *= Fraction(10) ** exp
        suffix = m.group("suffix")
        if suffix in _BINARY_SUFFIXES:
            num *= _BINARY_SUFFIXES[suffix]
        else:
            num *= _DECIMAL_SUFFIXES[suffix]
        if m.group("sign") == "-":
            num = -num
        frac = num
    if milli:
        frac *= 1000
    # k8s rounds up to the smallest representable unit (Quantity.Value()).
    num, den = frac.numerator, frac.denominator
    if den == 1:
        return num
    return -((-num) // den) if num >= 0 else num // den


def format_milli(milli_value: int) -> str:
    """Render a milli-unit quantity the way `kubectl` would (e.g. 1500 -> "1500m")."""
    if milli_value % 1000 == 0:
        return str(milli_value // 1000)
    return f"{milli_value}m"
