"""Domain model: the CRD-equivalent API types.

Capability parity with the reference's apis/kueue/v1beta1 (workload_types.go,
clusterqueue_types.go, localqueue_types.go, resourceflavor_types.go,
admissioncheck_types.go, workloadpriorityclass_types.go) and
apis/kueue/v1alpha1 (cohort_types.go, tas_types.go).  These are plain Python
dataclasses — the durable-state story is different from Kubernetes CRDs (see
kueue_tpu.controller.store), but field semantics are kept 1:1 so that the
reference's scenarios translate directly.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

from .quantity import parse_quantity

# ---------------------------------------------------------------------------
# Shared small types
# ---------------------------------------------------------------------------

ResourceName = str  # "cpu", "memory", "nvidia.com/gpu", "google.com/tpu", ...

#: Resources accounted in milli-units (reference: pkg/resources treats cpu
#: via MilliValue, everything else via Value).
MILLI_RESOURCES = frozenset({"cpu"})


def quantity_to_int(resource: ResourceName, value: int | float | str) -> int:
    return parse_quantity(value, milli=resource in MILLI_RESOURCES)


class ConditionStatus(str, enum.Enum):
    TRUE = "True"
    FALSE = "False"
    UNKNOWN = "Unknown"


@dataclass
class Condition:
    type: str
    status: ConditionStatus
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0
    observed_generation: int = 0


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" matches all effects
    toleration_seconds: Optional[int] = None


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


def toleration_tolerates(tol: Toleration, taint: Taint) -> bool:
    """Reference semantics: k8s.io/api core/v1 Toleration.ToleratesTaint."""
    if tol.effect and tol.effect != taint.effect:
        return False
    if tol.key and tol.key != taint.key:
        return False
    if tol.operator == "Exists":
        return True
    return tol.value == taint.value


def taints_tolerated(taints: list[Taint], tolerations: list[Toleration],
                     *, include_prefer: bool = False) -> bool:
    """True when every NoSchedule/NoExecute taint is tolerated.

    PreferNoSchedule taints never block admission (matching the scheduling
    corev1helpers.FindMatchingUntoleratedTaint filter used by the
    flavorassigner, reference pkg/scheduler/flavorassigner/flavorassigner.go:662).
    """
    for taint in taints:
        if taint.effect == "PreferNoSchedule" and not include_prefer:
            continue
        if not any(toleration_tolerates(t, taint) for t in tolerations):
            return False
    return True


# ---------------------------------------------------------------------------
# ResourceFlavor (reference: resourceflavor_types.go:31)
# ---------------------------------------------------------------------------

@dataclass
class ResourceFlavor:
    name: str
    node_labels: dict[str, str] = field(default_factory=dict)
    node_taints: list[Taint] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    topology_name: Optional[str] = None  # TAS binding


# ---------------------------------------------------------------------------
# Quota model (reference: clusterqueue_types.go:169-252)
# ---------------------------------------------------------------------------

@dataclass
class ResourceQuota:
    """Per (flavor, resource) quota. Values in canonical integer units."""
    nominal: int = 0
    borrowing_limit: Optional[int] = None  # None = unlimited borrowing
    lending_limit: Optional[int] = None    # None = lend everything

    @staticmethod
    def make(resource: ResourceName, nominal: int | float | str,
             borrowing_limit: int | float | str | None = None,
             lending_limit: int | float | str | None = None) -> "ResourceQuota":
        return ResourceQuota(
            nominal=quantity_to_int(resource, nominal),
            borrowing_limit=None if borrowing_limit is None
            else quantity_to_int(resource, borrowing_limit),
            lending_limit=None if lending_limit is None
            else quantity_to_int(resource, lending_limit),
        )


@dataclass
class FlavorQuotas:
    name: str  # flavor name
    resources: dict[ResourceName, ResourceQuota] = field(default_factory=dict)


@dataclass
class ResourceGroup:
    covered_resources: list[ResourceName]
    flavors: list[FlavorQuotas]


# ---------------------------------------------------------------------------
# Preemption / fungibility policies (reference: clusterqueue_types.go:336-511)
# ---------------------------------------------------------------------------

class QueueingStrategy(str, enum.Enum):
    STRICT_FIFO = "StrictFIFO"
    BEST_EFFORT_FIFO = "BestEffortFIFO"


class ReclaimWithinCohort(str, enum.Enum):
    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"
    ANY = "Any"


class WithinClusterQueue(str, enum.Enum):
    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"
    LOWER_OR_NEWER_EQUAL_PRIORITY = "LowerOrNewerEqualPriority"


class BorrowWithinCohortPolicy(str, enum.Enum):
    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"


@dataclass
class BorrowWithinCohort:
    policy: BorrowWithinCohortPolicy = BorrowWithinCohortPolicy.NEVER
    max_priority_threshold: Optional[int] = None


@dataclass
class PreemptionPolicy:
    reclaim_within_cohort: ReclaimWithinCohort = ReclaimWithinCohort.NEVER
    borrow_within_cohort: BorrowWithinCohort = field(default_factory=BorrowWithinCohort)
    within_cluster_queue: WithinClusterQueue = WithinClusterQueue.NEVER


class FlavorFungibilityPolicy(str, enum.Enum):
    BORROW = "Borrow"
    PREEMPT = "Preempt"
    TRY_NEXT_FLAVOR = "TryNextFlavor"


@dataclass
class FlavorFungibility:
    when_can_borrow: FlavorFungibilityPolicy = FlavorFungibilityPolicy.BORROW
    when_can_preempt: FlavorFungibilityPolicy = FlavorFungibilityPolicy.TRY_NEXT_FLAVOR


class StopPolicy(str, enum.Enum):
    NONE = "None"
    HOLD = "Hold"
    HOLD_AND_DRAIN = "HoldAndDrain"


@dataclass
class FairSharing:
    weight: float = 1.0  # FairSharing.weight, default 1 (fairsharing_types.go:27)


# ---------------------------------------------------------------------------
# AdmissionChecks (reference: admissioncheck_types.go, KEP 993)
# ---------------------------------------------------------------------------

class AdmissionCheckState(str, enum.Enum):
    PENDING = "Pending"
    READY = "Ready"
    RETRY = "Retry"
    REJECTED = "Rejected"


@dataclass
class AdmissionCheck:
    name: str
    controller_name: str = ""
    parameters: Optional[dict[str, Any]] = None
    active: bool = True


@dataclass
class AdmissionCheckStrategyRule:
    name: str
    on_flavors: list[str] = field(default_factory=list)  # empty = all flavors


# ---------------------------------------------------------------------------
# ClusterQueue (reference: clusterqueue_types.go:511)
# ---------------------------------------------------------------------------

@dataclass
class ClusterQueue:
    name: str
    resource_groups: list[ResourceGroup] = field(default_factory=list)
    cohort: Optional[str] = None
    queueing_strategy: QueueingStrategy = QueueingStrategy.BEST_EFFORT_FIFO
    preemption: PreemptionPolicy = field(default_factory=PreemptionPolicy)
    flavor_fungibility: FlavorFungibility = field(default_factory=FlavorFungibility)
    admission_checks: list[str] = field(default_factory=list)
    admission_checks_strategy: list[AdmissionCheckStrategyRule] = field(default_factory=list)
    fair_sharing: Optional[FairSharing] = None
    stop_policy: StopPolicy = StopPolicy.NONE
    namespace_selector: Optional[dict[str, str]] = None  # None = match nothing? (ref: nil matches nothing; {} matches all)

    def flavor_resources(self) -> list[tuple[str, ResourceName]]:
        out = []
        for rg in self.resource_groups:
            for fq in rg.flavors:
                for rname in fq.resources:
                    out.append((fq.name, rname))
        return out


# ---------------------------------------------------------------------------
# Cohort (reference: v1alpha1 cohort_types.go:85, KEP 79)
# ---------------------------------------------------------------------------

@dataclass
class Cohort:
    name: str
    parent_name: Optional[str] = None
    resource_groups: list[ResourceGroup] = field(default_factory=list)
    fair_sharing: Optional[FairSharing] = None


# ---------------------------------------------------------------------------
# LocalQueue (reference: localqueue_types.go:187)
# ---------------------------------------------------------------------------

@dataclass
class LocalQueue:
    name: str
    namespace: str = "default"
    cluster_queue: str = ""
    stop_policy: StopPolicy = StopPolicy.NONE

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# Topology (reference: v1alpha1 tas_types.go, KEP 2724)
# ---------------------------------------------------------------------------

@dataclass
class Topology:
    name: str
    levels: list[str] = field(default_factory=list)  # ordered node-label keys, top→bottom


@dataclass
class PodSetTopologyRequest:
    required: Optional[str] = None     # level label that must contain all pods
    preferred: Optional[str] = None    # level label to try first, fall back upward
    unconstrained: bool = False
    pod_index_label: Optional[str] = None
    slice_required_topology: Optional[str] = None
    slice_size: Optional[int] = None


@dataclass
class TopologyDomainAssignment:
    values: list[str]  # node-label values along topology levels
    count: int


@dataclass
class TopologyAssignment:
    levels: list[str]
    domains: list[TopologyDomainAssignment] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Workload (reference: workload_types.go:639)
# ---------------------------------------------------------------------------

DEFAULT_POD_SET_NAME = "main"


@dataclass
class PodSet:
    """One homogeneous group of pods (reference workload_types.go:262)."""
    name: str = DEFAULT_POD_SET_NAME
    count: int = 1
    min_count: Optional[int] = None  # partial admission (KEP 420)
    # per-pod resource requests in canonical integer units
    requests: dict[ResourceName, int] = field(default_factory=dict)
    # optional per-pod limits (requests must not exceed them —
    # workload.go RequestsMustNotExceedLimitMessage)
    limits: dict[ResourceName, int] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    scheduling_gates: list[str] = field(default_factory=list)
    required_node_affinity: dict[str, list[str]] = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None

    @staticmethod
    def make(name: str = DEFAULT_POD_SET_NAME, count: int = 1,
             requests: dict[ResourceName, int | float | str] | None = None,
             limits: dict[ResourceName, int | float | str] | None = None,
             **kw) -> "PodSet":
        reqs = {r: quantity_to_int(r, v) for r, v in (requests or {}).items()}
        lims = {r: quantity_to_int(r, v) for r, v in (limits or {}).items()}
        return PodSet(name=name, count=count, requests=reqs, limits=lims,
                      **kw)


@dataclass
class PodSetAssignment:
    """Admission decision for one PodSet (reference workload_types.go:151)."""
    name: str
    flavors: dict[ResourceName, str] = field(default_factory=dict)
    resource_usage: dict[ResourceName, int] = field(default_factory=dict)
    count: int = 0
    topology_assignment: Optional[TopologyAssignment] = None
    delayed_topology_request: Optional[str] = None


@dataclass
class Admission:
    cluster_queue: str
    pod_set_assignments: list[PodSetAssignment] = field(default_factory=list)


@dataclass
class AdmissionCheckStatus:
    name: str
    state: AdmissionCheckState = AdmissionCheckState.PENDING
    message: str = ""
    last_transition_time: float = 0.0
    pod_set_updates: list[dict[str, Any]] = field(default_factory=list)


@dataclass
class RequeueState:
    """Eviction-requeue backoff (reference workload_types.go:372)."""
    count: int = 0
    requeue_at: Optional[float] = None


@dataclass
class ReclaimablePod:
    name: str  # PodSet name
    count: int  # number of pods no longer needing resources


# Workload condition types (reference pkg/workload + workload_types.go)
WL_QUOTA_RESERVED = "QuotaReserved"
WL_ADMITTED = "Admitted"
WL_FINISHED = "Finished"
WL_EVICTED = "Evicted"
WL_PREEMPTED = "Preempted"
WL_REQUEUED = "Requeued"
WL_DEACTIVATION_TARGET = "DeactivationTarget"
WL_PODS_READY = "PodsReady"

# Eviction reasons
EVICTED_BY_PREEMPTION = "Preempted"
EVICTED_BY_PODS_READY_TIMEOUT = "PodsReadyTimeout"
EVICTED_BY_ADMISSION_CHECK = "AdmissionCheck"
EVICTED_BY_CQ_STOPPED = "ClusterQueueStopped"
EVICTED_BY_LQ_STOPPED = "LocalQueueStopped"
EVICTED_BY_DEACTIVATION = "InactiveWorkload"
EVICTED_BY_NODE_FAILURES = "NodeFailures"

# Preemption reasons (reference pkg/scheduler/preemption/preemption.go)
IN_CLUSTER_QUEUE_REASON = "InClusterQueue"
IN_COHORT_RECLAMATION_REASON = "InCohortReclamation"
IN_COHORT_FAIR_SHARING_REASON = "InCohortFairSharing"
IN_COHORT_RECLAIM_WHILE_BORROWING_REASON = "InCohortReclaimWhileBorrowing"


@dataclass
class Workload:
    name: str
    namespace: str = "default"
    queue_name: str = ""
    pod_sets: list[PodSet] = field(default_factory=list)
    priority: int = 0
    priority_class_name: str = ""
    priority_class_source: str = ""  # "kueue.x-k8s.io/workloadpriorityclass" or pod PC
    active: bool = True
    creation_time: float = 0.0
    maximum_execution_time_seconds: Optional[int] = None

    # --- status ---
    admission: Optional[Admission] = None
    conditions: dict[str, Condition] = field(default_factory=dict)
    admission_check_states: dict[str, AdmissionCheckStatus] = field(default_factory=dict)
    requeue_state: Optional[RequeueState] = None
    reclaimable_pods: list[ReclaimablePod] = field(default_factory=list)
    scheduling_stats_evictions: dict[str, int] = field(default_factory=dict)
    generation: int = 1
    uid: str = ""

    def __post_init__(self):
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"
        # eager: hot identity in cache/queue maps; computing it here (not
        # lazily) means a later name/namespace mutation can't silently
        # desync map identity — name immutability is enforced by the
        # workload webhook, and clone() carries the same identity
        self._key = f"{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return self._key

    # -- condition helpers (reference pkg/workload/workload.go:774-789) --
    def condition_true(self, cond_type: str) -> bool:
        c = self.conditions.get(cond_type)
        return c is not None and c.status == ConditionStatus.TRUE

    @property
    def has_quota_reservation(self) -> bool:
        return self.admission is not None and self.condition_true(WL_QUOTA_RESERVED)

    @property
    def is_admitted(self) -> bool:
        return self.condition_true(WL_ADMITTED)

    @property
    def is_finished(self) -> bool:
        return self.condition_true(WL_FINISHED)

    @property
    def is_evicted(self) -> bool:
        return self.condition_true(WL_EVICTED)

    @property
    def is_active(self) -> bool:
        return self.active

    def set_condition(self, cond_type: str, status: ConditionStatus,
                      reason: str = "", message: str = "", now: float = 0.0) -> None:
        prev = self.conditions.get(cond_type)
        if prev is not None and prev.status == status and prev.reason == reason:
            return
        self.conditions[cond_type] = Condition(
            type=cond_type, status=status, reason=reason, message=message,
            last_transition_time=now, observed_generation=self.generation)

    def clone(self) -> "Workload":
        """Structural copy without deepcopy (the admit path clones every
        workload once per admission — reference SSA builds a fresh apply
        configuration instead)."""
        import copy as _copy
        import dataclasses as _dc
        new = _copy.copy(self)
        new.pod_sets = [
            _dc.replace(ps,
                        requests=dict(ps.requests),
                        limits=dict(ps.limits),
                        node_selector=dict(ps.node_selector),
                        tolerations=list(ps.tolerations),
                        labels=dict(ps.labels),
                        annotations=dict(ps.annotations),
                        scheduling_gates=list(ps.scheduling_gates),
                        required_node_affinity={
                            k: list(v) for k, v
                            in ps.required_node_affinity.items()})
            for ps in self.pod_sets]
        if self.admission is not None:
            new.admission = Admission(
                cluster_queue=self.admission.cluster_queue,
                pod_set_assignments=[
                    _dc.replace(a, flavors=dict(a.flavors),
                                resource_usage=dict(a.resource_usage))
                    for a in self.admission.pod_set_assignments])
        new.conditions = dict(self.conditions)
        new.admission_check_states = {
            k: _dc.replace(v, pod_set_updates=list(v.pod_set_updates))
            for k, v in self.admission_check_states.items()}
        if self.requeue_state is not None:
            new.requeue_state = _dc.replace(self.requeue_state)
        new.reclaimable_pods = list(self.reclaimable_pods)
        new.scheduling_stats_evictions = dict(self.scheduling_stats_evictions)
        return new


@dataclass
class WorkloadPriorityClass:
    name: str
    value: int = 0
    description: str = ""


# ---------------------------------------------------------------------------
# MultiKueue (reference: multikueue_types.go)
# ---------------------------------------------------------------------------

@dataclass
class MultiKueueCluster:
    name: str
    kubeconfig_ref: str = ""  # opaque connection handle for the transport layer
    active: bool = True


@dataclass
class MultiKueueConfig:
    name: str
    clusters: list[str] = field(default_factory=list)


@dataclass
class ProvisioningRequestRetryStrategy:
    """reference provisioningrequestconfig_types.go retry strategy."""
    backoff_limit_count: int = 3
    backoff_base_seconds: int = 60
    backoff_max_seconds: int = 1800


@dataclass
class ProvisioningRequestConfig:
    """reference provisioningrequestconfig_types.go:119."""
    name: str
    provisioning_class_name: str = ""
    parameters: dict[str, str] = field(default_factory=dict)
    managed_resources: list[str] = field(default_factory=list)
    retry_strategy: ProvisioningRequestRetryStrategy = field(
        default_factory=ProvisioningRequestRetryStrategy)
    pod_set_merge_policy: str = ""


__all__ = [
    name for name, value in list(globals().items())
    if not name.startswith("_")
    and (getattr(value, "__module__", None) == __name__  # classes/functions here
         or isinstance(value, (str, frozenset)))          # constants
]
