"""Performance harness (reference test/performance/scheduler).

Generator-config-driven scenario replay with fake workload execution,
stat collection, and a rangespec checker.
"""

from .harness import (
    PerfStats,
    check_rangespec,
    load_generator_config,
    run_scenario,
)

__all__ = ["PerfStats", "check_rangespec", "load_generator_config",
           "run_scenario"]
