"""Generator-config replay + rangespec checker.

Reads the reference's generator-config YAML shape
(test/performance/scheduler/default_generator_config.yaml: cohort classes
→ queue sets → workload sets with creationIntervalMs/runtimeMs/priority/
request) and replays it against a Driver in an event-driven virtual
timeline: arrivals at their creation intervals, fake execution finishing
each admitted workload runtimeMs after admission (the reference runner
flips conditions the same way — runner/controller/controller.go:113).

Collected stats mirror the reference rangespec
(default_rangespec.yaml): wall time, process CPU (mCPU), max RSS,
per-workload-class average time to admission (virtual ms), and per-CQ
class minimum time-averaged usage.  ``check_rangespec`` asserts them.

Run: ``python -m kueue_tpu.perf.harness <generator.yaml> [rangespec.yaml]``
"""

from __future__ import annotations

import heapq
import sys
import time
from dataclasses import dataclass, field

from ..api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PreemptionPolicy,
    ReclaimWithinCohort,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    WithinClusterQueue,
    Workload,
)
from ..controller.driver import Driver

UNIT = 1000  # 1 generator "request" unit = 1 CPU


def load_generator_config(path: str) -> list[dict]:
    import yaml
    with open(path) as f:
        return yaml.safe_load(f)


@dataclass
class PerfStats:
    wall_ms: float = 0.0
    virtual_ms: float = 0.0
    cpu_mcpu: float = 0.0         # cpu_s per arrival-schedule second
    cpu_mcpu_replay: float = 0.0  # cpu_s per compressed replay second
    maxrss_kb: float = 0.0
    total_workloads: int = 0
    admitted: int = 0
    finished: int = 0
    # workload class → average time-to-admission (virtual ms)
    avg_time_to_admission_ms: dict[str, float] = field(default_factory=dict)
    # cq class → minimum (across CQs) time-averaged usage percent
    min_avg_usage_pct: dict[str, float] = field(default_factory=dict)


class _Clock:
    def __init__(self):
        self.t = 0.0  # seconds

    def __call__(self):
        return self.t


def run_scenario(config: list[dict], driver: Driver | None = None) -> PerfStats:
    import resource

    clock = _Clock()
    d = driver or Driver(clock=clock)
    d.apply_resource_flavor(ResourceFlavor(name="default"))

    # --- build cohorts/CQs and the arrival schedule -------------------
    arrivals: list[tuple[float, int, Workload, str]] = []  # (ms, seq, wl, class)
    cq_class_members: dict[str, list[tuple[str, int]]] = {}  # class → [(cq, nominal)]
    runtime_ms: dict[str, float] = {}
    wl_class: dict[str, str] = {}
    seq = 0
    for ci, cohort_cls in enumerate(config):
        for cn in range(cohort_cls.get("count", 1)):
            cohort = f"{cohort_cls.get('className', 'cohort')}-{ci}-{cn}"
            for qi, qs in enumerate(cohort_cls.get("queuesSets", [])):
                for qn in range(qs.get("count", 1)):
                    cq_name = f"{cohort}-{qs.get('className', 'cq')}-{qi}-{qn}"
                    nominal = qs.get("nominalQuota", 0) * UNIT
                    blimit = qs.get("borrowingLimit")
                    d.apply_cluster_queue(ClusterQueue(
                        name=cq_name, cohort=cohort,
                        preemption=PreemptionPolicy(
                            reclaim_within_cohort=ReclaimWithinCohort(
                                qs.get("reclaimWithinCohort", "Never")),
                            within_cluster_queue=WithinClusterQueue(
                                qs.get("withinClusterQueue", "Never"))),
                        resource_groups=[ResourceGroup(
                            covered_resources=["cpu"],
                            flavors=[FlavorQuotas(name="default", resources={
                                "cpu": ResourceQuota(
                                    nominal=nominal,
                                    borrowing_limit=(blimit * UNIT
                                                     if blimit else None))})])]))
                    lq_name = f"lq-{cq_name}"
                    d.apply_local_queue(LocalQueue(name=lq_name,
                                                   cluster_queue=cq_name))
                    cq_class_members.setdefault(
                        qs.get("className", "cq"), []).append(
                            (cq_name, nominal))
                    for wsi, ws in enumerate(qs.get("workloadsSets", [])):
                        interval = ws.get("creationIntervalMs", 100)
                        for k in range(ws.get("count", 0)):
                            t_ms = (k + 1) * interval
                            for wli, wcfg in enumerate(ws.get("workloads", [])):
                                cls = wcfg.get("className", f"class-{wli}")
                                name = (f"{cls}-{cq_name}-{wsi}-{k}")
                                wl = Workload(
                                    name=name, queue_name=lq_name,
                                    priority=wcfg.get("priority", 0),
                                    creation_time=t_ms / 1000.0,
                                    pod_sets=[PodSet(
                                        name="main", count=1,
                                        requests={"cpu": wcfg.get(
                                            "request", 1) * UNIT})])
                                runtime_ms[wl.key] = wcfg.get("runtimeMs", 0)
                                wl_class[wl.key] = cls
                                seq += 1
                                arrivals.append((t_ms, seq, wl, cls))
    heapq.heapify(arrivals)

    # --- event loop ---------------------------------------------------
    stats = PerfStats(total_workloads=len(arrivals))
    finishes: list[tuple[float, str]] = []   # (ms, key)
    admission_time: dict[str, float] = {}
    adm_sum: dict[str, float] = {}
    adm_count: dict[str, int] = {}
    usage_integral: dict[str, float] = {}    # cq → ∫ usage/nominal dt
    last_t = 0.0

    cpu0 = time.process_time()
    wall0 = time.perf_counter()

    def integrate_usage(now_ms: float) -> None:
        nonlocal last_t
        dt = now_ms - last_t
        if dt <= 0:
            return
        for members in cq_class_members.values():
            for cq_name, nominal in members:
                if nominal <= 0:
                    continue
                used = sum(v for fr, v in d.cache.usage(cq_name).items()
                           if fr.resource == "cpu")
                usage_integral[cq_name] = (
                    usage_integral.get(cq_name, 0.0)
                    + min(1.0, used / nominal) * dt)
        last_t = now_ms

    def pump(now_ms: float) -> None:
        clock.t = now_ms / 1000.0
        while True:
            cycle_stats = d.schedule_once()
            if not cycle_stats.admitted and not cycle_stats.preempted_targets:
                break
            for key in cycle_stats.admitted:
                if key not in admission_time:
                    admission_time[key] = now_ms
                    cls = wl_class[key]
                    created = d.workloads[key].creation_time * 1000.0
                    adm_sum[cls] = adm_sum.get(cls, 0.0) + now_ms - created
                    adm_count[cls] = adm_count.get(cls, 0) + 1
                    stats.admitted += 1
                heapq.heappush(finishes,
                               (now_ms + runtime_ms.get(key, 0), key))

    while arrivals or finishes:
        next_arr = arrivals[0][0] if arrivals else float("inf")
        next_fin = finishes[0][0] if finishes else float("inf")
        now_ms = min(next_arr, next_fin)
        integrate_usage(now_ms)
        while arrivals and arrivals[0][0] <= now_ms:
            _, _, wl, cls = heapq.heappop(arrivals)
            d.create_workload(wl)
        while finishes and finishes[0][0] <= now_ms:
            _, key = heapq.heappop(finishes)
            wl = d.workloads.get(key)
            if wl is None or not wl.has_quota_reservation:
                # evicted meanwhile; it will be re-admitted and re-queued
                admission_time.pop(key, None)
                continue
            d.finish_workload(key)
            stats.finished += 1
        pump(now_ms)

    stats.virtual_ms = last_t
    stats.wall_ms = (time.perf_counter() - wall0) * 1000.0
    cpu_s = time.process_time() - cpu0
    # Two CPU figures, because the reference's 396-535 mCPU is measured
    # over an ARRIVAL-PACED run (wall ~= the generator schedule, the
    # process mostly idle between events).  The comparable number for a
    # virtual-time replay is cpu seconds per SCHEDULE second — what the
    # process would consume if arrivals were paced in real time (the
    # work is identical; only the idle gaps are compressed).  The replay
    # figure divides by compressed wall time and is ~1000 mCPU for any
    # CPU-bound replay by construction.  Degenerate all-at-t0 schedules
    # (virtual_ms ~ 0) fall back to the wall denominator.
    denom_s = max(stats.virtual_ms, stats.wall_ms) / 1000.0
    stats.cpu_mcpu = cpu_s / max(denom_s, 1e-9) * 1000.0
    stats.cpu_mcpu_replay = (
        cpu_s / max(stats.wall_ms / 1000.0, 1e-9)) * 1000.0
    stats.maxrss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    for cls, total in adm_sum.items():
        stats.avg_time_to_admission_ms[cls] = total / adm_count[cls]
    for cls, members in cq_class_members.items():
        pcts = [100.0 * usage_integral.get(cq, 0.0) / max(last_t, 1e-9)
                for cq, _ in members]
        stats.min_avg_usage_pct[cls] = min(pcts) if pcts else 0.0
    return stats


def require_accel_or_die() -> None:
    """Required-mode chip check for the bench entrypoints: with
    ``--require-accel`` (or ``KUEUE_TPU_REQUIRE_ACCEL=1``) an
    unreachable accelerator aborts the run instead of silently
    producing CPU-only numbers.  Also exports the env var so
    subprocess-based checks (tests/test_accel_route.py) FAIL rather
    than skip for the rest of the run."""
    import os
    os.environ["KUEUE_TPU_REQUIRE_ACCEL"] = "1"
    import jax
    accel = [dev for dev in jax.devices() if dev.platform != "cpu"]
    if not accel:
        raise SystemExit(
            "--require-accel: no accelerator platform reachable "
            f"(devices: {[dev.platform for dev in jax.devices()]})")
    print(f"require-accel: {len(accel)} {accel[0].platform} device(s)",
          file=sys.stderr)


def burst_boundary_report(bstats: dict) -> dict:
    """Summarize the burst-boundary pipeline from BurstSolver.stats:
    how many window boundaries overlapped pack+dispatch with the
    previous apply (the cost the two-slot pipeline removes from the
    first cycle of each window), how many speculations were discarded,
    and how many windows fell back to the serial pack."""
    spec = bstats.get("burst_spec_dispatches", 0)
    overlapped = bstats.get("burst_overlapped_packs", 0)
    cancelled = bstats.get("burst_spec_cancelled", 0)
    serial = bstats.get("burst_serial_windows", 0)
    packs = bstats.get("burst_packs", 0)
    return {
        "overlapped_packs": overlapped,
        "spec_dispatches": spec,
        "spec_cancelled": cancelled,
        "serial_windows": serial,
        # pack cost paid serially (per serial window) vs absorbed into
        # the previous window's apply phase (per overlapped window)
        "serial_pack_s": round(bstats.get("burst_pack_s", 0.0), 4),
        "boundary_overlap_share": round(
            overlapped / max(1, overlapped + packs), 3),
        "spec_fetch_wait_s": round(
            bstats.get("burst_spec_fetch_wait_s", 0.0), 4),
        "target_divergences": bstats.get("burst_target_divergences", 0),
        # incremental delta-pack (ops/burst.pack_burst_cached): windows
        # whose boundary re-walked only journal-dirty CQs vs counted
        # full-repack fallbacks, and the row-level reuse they bought
        "delta_packs": bstats.get("burst_delta_packs", 0),
        "full_packs": bstats.get("burst_full_packs", 0),
        "rows_reused": bstats.get("rows_reused", 0),
        "rows_repacked": bstats.get("rows_repacked", 0),
        "delta_pack_s": round(bstats.get("delta_pack_s", 0.0), 4),
        # shard-resident boundary: fresh packs that reused the on-mesh
        # row planes (scattering only dirty rows, coalesced into
        # ranges) vs full re-uploads, and the host→device bytes the
        # residency actually paid vs the upload-everything equivalent
        "resident_hits": bstats.get("burst_resident_hits", 0),
        "resident_misses": bstats.get("burst_resident_misses", 0),
        "resident_scatter_rows": bstats.get(
            "burst_resident_scatter_rows", 0),
        "resident_scatter_ranges": bstats.get(
            "burst_resident_scatter_ranges", 0),
        "journal_dirty_ranges": bstats.get(
            "burst_journal_dirty_ranges", 0),
        "boundary_bytes_h2d": bstats.get("burst_boundary_bytes_h2d", 0),
        "boundary_bytes_equiv": bstats.get(
            "burst_boundary_bytes_equiv", 0),
    }


def shard_imbalance_report(bstats: dict) -> dict:
    """The artifact mesh block's shard-imbalance counters: how the
    cost-balanced forest partition spread measured cycle cost across
    shards (max/mean ratio; 1.0 = perfectly even), the per-shard fetch
    waits the boundary pays, and the shard-resident reuse counters."""
    cost = bstats.get("burst_shard_cost")
    return {
        "layout_rebuilds": bstats.get("burst_layout_rebuilds", 0),
        "layouts_cost_balanced": bstats.get(
            "burst_layout_cost_balanced", 0),
        "forest_cost_max_mean_ratio": bstats.get(
            "burst_shard_cost_ratio", 0.0),
        "shard_cost": list(cost) if cost else [],
        "shard_fetch_wait_s": [
            round(x, 4) for x in bstats.get("burst_shard_fetch_s", [])],
        "shard_pack_s": [
            round(x, 4) for x in bstats.get("burst_shard_pack_s", [])],
        "resident_hits": bstats.get("burst_resident_hits", 0),
        "resident_misses": bstats.get("burst_resident_misses", 0),
    }


def chaos_report(injector=None, bstats: dict | None = None,
                 wal=None) -> dict:
    """The ``chaos`` block stamped into artifacts: which faults were
    armed and fired (seed included, so the scenario replays), what the
    solver's degradation counters recorded, and how much of the WAL a
    recovery had to roll forward."""
    out: dict = {}
    if injector is not None:
        out.update(injector.report())
    if bstats is not None:
        out["degradations"] = {
            "shard_degradations": bstats.get("burst_shard_degradations", 0),
            "shard_serial_fallbacks": bstats.get(
                "burst_shard_serial_fallbacks", 0),
            "chaos_divergences": bstats.get("burst_chaos_divergences", 0),
            "spec_cancelled": bstats.get("burst_spec_cancelled", 0),
        }
    if wal is not None:
        out["wal"] = {"batches": len(wal.batches),
                      "tail_ops": len(wal.tail),
                      "path": wal.path}
    return out


class MissingControlArm(ValueError):
    """An A/B block was requested without an interleaved control arm."""


# Host-fallback visibility for published A/B arms: any of these present
# in an arm's solver/burst stat blocks is copied into the block's
# environment_drift record, so a "device wins" artifact also proves how
# much of the arm actually ran on the device.
_FALLBACK_KEYS = ("host_cycles", "scalar_heads", "resume_heads",
                  "walk_stop_heads", "native_ff_fallbacks",
                  "burst_dirty_cycles", "burst_dirty_preempt",
                  "burst_dirty_scalar", "burst_dirty_resume",
                  "burst_suppressed_cycles",
                  # streaming-pack visibility: an arm claiming
                  # O(arrivals + dirty) host cost must show how many
                  # windows actually streamed vs fell back to full walks
                  "stream_packs", "stream_full_packs",
                  "stream_pack_bails", "pack_row_patches",
                  "pack_rank_patches", "pack_tighten_bytes_saved")


def _fallback_counters(arm: dict) -> dict:
    out: dict = {}
    for src_key in ("solver_stats", "flavor_walk", "burst_stats", "pack"):
        src = arm.get(src_key)
        if isinstance(src, dict):
            for k in _FALLBACK_KEYS:
                if k in src:
                    out[k] = src[k]
    for k in _FALLBACK_KEYS:       # counters may also sit at top level
        if k in arm:
            out[k] = arm[k]
    return out


def ab_block(treatment: dict, control: dict | None, *,
             treatment_label: str = "treatment",
             control_label: str = "control") -> dict:
    """Environment-drift bookkeeping for published artifacts: every A/B
    comparison must carry its own same-box control, measured
    *interleaved* with the treatment (control, treatment, control, …)
    so thermal/noisy-neighbor drift shows up as control variance
    instead of silently biasing the delta.  Refuses to build the block
    otherwise — a treatment-only number is not publishable."""
    if not control:
        raise MissingControlArm(
            "refusing to emit an A/B block without a control arm — "
            "measure an interleaved same-box control alongside the "
            "treatment")
    if not control.get("interleaved"):
        raise MissingControlArm(
            "control arm is not marked interleaved=True — a control "
            "measured before/after the treatment (not interleaved with "
            "it) does not bound environment drift")
    return {treatment_label: dict(treatment),
            control_label: dict(control),
            "environment_drift": {
                "interleaved": True,
                "fallback_counters": {
                    treatment_label: _fallback_counters(treatment),
                    control_label: _fallback_counters(control)}}}


def check_rangespec(stats: PerfStats, rangespec: dict) -> list[str]:
    """reference test/performance/scheduler checker semantics."""
    failures = []
    cmd = rangespec.get("cmd", {})
    if "maxWallMs" in cmd and stats.wall_ms > cmd["maxWallMs"]:
        failures.append(f"wall {stats.wall_ms:.0f}ms > {cmd['maxWallMs']}ms")
    if "mCPU" in cmd and stats.cpu_mcpu > cmd["mCPU"]:
        # vs the arrival schedule (see run()): directly comparable to
        # the reference's paced-run measurement, no headroom needed
        failures.append(f"cpu {stats.cpu_mcpu:.0f}mCPU > {cmd['mCPU']}")
    if "maxrss" in cmd and stats.maxrss_kb > cmd["maxrss"]:
        failures.append(f"rss {stats.maxrss_kb:.0f}KB > {cmd['maxrss']}KB")
    for cls, floor in (rangespec.get("clusterQueueClassesMinUsage")
                       or {}).items():
        got = stats.min_avg_usage_pct.get(cls, 0.0)
        if got < floor:
            failures.append(f"usage[{cls}] {got:.1f}% < {floor}%")
    for cls, cap in (rangespec.get("wlClassesMaxAvgTimeToAdmissionMs")
                     or {}).items():
        got = stats.avg_time_to_admission_ms.get(cls)
        if got is None:
            failures.append(f"timeToAdmission[{cls}]: no admissions")
        elif got > cap:
            failures.append(f"timeToAdmission[{cls}] {got:.0f}ms > {cap}ms")
    return failures


def main(argv: list[str]) -> int:
    import json
    import yaml
    config = load_generator_config(argv[0])
    stats = run_scenario(config)
    print(json.dumps({
        "wall_ms": round(stats.wall_ms, 1),
        "virtual_ms": round(stats.virtual_ms, 1),
        "cpu_mcpu": round(stats.cpu_mcpu, 1),
        "cpu_mcpu_replay": round(stats.cpu_mcpu_replay, 1),
        "maxrss_kb": stats.maxrss_kb,
        "workloads": stats.total_workloads,
        "finished": stats.finished,
        "avg_time_to_admission_ms": {
            k: round(v, 1)
            for k, v in sorted(stats.avg_time_to_admission_ms.items())},
        "min_avg_usage_pct": {
            k: round(v, 1)
            for k, v in sorted(stats.min_avg_usage_pct.items())},
    }, indent=1))
    if len(argv) > 1:
        with open(argv[1]) as f:
            rangespec = yaml.safe_load(f)
        failures = check_rangespec(stats, rangespec)
        for f_ in failures:
            print(f"RANGESPEC FAIL: {f_}", file=sys.stderr)
        return 1 if failures else 0
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
