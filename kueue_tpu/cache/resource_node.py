"""Hierarchical quota accounting over the cohort forest.

Capability parity with reference pkg/cache/resource_node.go: every
ClusterQueue and Cohort owns a ResourceNode (quotas, subtree quota, usage);
``available`` walks to the root combining local headroom with parent
capacity under borrowing limits; usage bubbles up past guaranteed
(lending-limited) quota.  Values are canonical integers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol

from ..api.types import ResourceQuota
from ..resources import FlavorResource, FlavorResourceQuantities


@dataclass
class ResourceNode:
    """Quotas + usage for one CQ or Cohort (reference resource_node.go:28)."""
    quotas: dict[FlavorResource, ResourceQuota] = field(default_factory=dict)
    subtree_quota: FlavorResourceQuantities = field(default_factory=FlavorResourceQuantities)
    usage: FlavorResourceQuantities = field(default_factory=FlavorResourceQuantities)

    def clone(self) -> "ResourceNode":
        # quotas/subtree_quota are replaced wholesale on update → share;
        # usage mutates → copy (reference resource_node.go:53).
        return ResourceNode(quotas=self.quotas,
                            subtree_quota=self.subtree_quota,
                            usage=self.usage.clone())

    def guaranteed_quota(self, fr: FlavorResource) -> int:
        """Capacity never lent to the cohort (reference resource_node.go:63).

        When the LendingLimit gate is off the limit never reaches this
        map — build_quotas drops it at cache build."""
        q = self.quotas.get(fr)
        if q is not None and q.lending_limit is not None:
            return max(0, self.subtree_quota.get(fr, 0) - q.lending_limit)
        return 0


class HierarchicalNode(Protocol):
    """Navigation protocol over CQs and Cohorts (resource_node.go:73)."""
    resource_node: ResourceNode

    def parent_node(self) -> Optional["HierarchicalNode"]: ...


def available(node: HierarchicalNode, fr: FlavorResource) -> int:
    """Remaining capacity incl. borrowing (reference resource_node.go:89).

    May be negative on over-admission (quota shrank under usage).
    """
    r = node.resource_node
    parent = node.parent_node()
    if parent is None:
        return r.subtree_quota.get(fr, 0) - r.usage.get(fr, 0)
    guaranteed = r.guaranteed_quota(fr)
    local_available = max(0, guaranteed - r.usage.get(fr, 0))
    parent_available = available(parent, fr)
    q = r.quotas.get(fr)
    if q is not None and q.borrowing_limit is not None:
        stored_in_parent = r.subtree_quota.get(fr, 0) - guaranteed
        used_in_parent = max(0, r.usage.get(fr, 0) - guaranteed)
        with_max_from_parent = stored_in_parent - used_in_parent + q.borrowing_limit
        parent_available = min(with_max_from_parent, parent_available)
    return local_available + parent_available


def potential_available(node: HierarchicalNode, fr: FlavorResource) -> int:
    """Max capacity assuming zero usage (reference resource_node.go:108)."""
    r = node.resource_node
    parent = node.parent_node()
    if parent is None:
        return r.subtree_quota.get(fr, 0)
    avail = r.guaranteed_quota(fr) + potential_available(parent, fr)
    q = r.quotas.get(fr)
    if q is not None and q.borrowing_limit is not None:
        avail = min(r.subtree_quota.get(fr, 0) + q.borrowing_limit, avail)
    return avail


def add_usage(node: HierarchicalNode, fr: FlavorResource, val: int) -> None:
    """Add usage, bubbling the above-guaranteed part to the parent
    (reference resource_node.go:123)."""
    r = node.resource_node
    local_available = max(0, r.guaranteed_quota(fr) - r.usage.get(fr, 0))
    r.usage[fr] = r.usage.get(fr, 0) + val
    parent = node.parent_node()
    if parent is not None and val > local_available:
        add_usage(parent, fr, val - local_available)


def remove_usage(node: HierarchicalNode, fr: FlavorResource, val: int) -> None:
    """Remove usage, reclaiming what was stored in the parent
    (reference resource_node.go:135)."""
    r = node.resource_node
    stored_in_parent = r.usage.get(fr, 0) - r.guaranteed_quota(fr)
    r.usage[fr] = r.usage.get(fr, 0) - val
    parent = node.parent_node()
    if stored_in_parent <= 0 or parent is None:
        return
    remove_usage(parent, fr, min(val, stored_in_parent))


def apply_usage(node: HierarchicalNode, usage: FlavorResourceQuantities,
                sign: int) -> None:
    for fr, qty in usage.items():
        if sign > 0:
            add_usage(node, fr, qty)
        else:
            remove_usage(node, fr, qty)
