"""Persistent packed-plane arena with slab-doubling growth.

The streaming burst pack (ops/stream_pack.py) patches a persistent
copy of the dense ``[C, M]`` packed universe in place instead of
rebuilding it every window.  The arena owns the backing slabs: each
named plane lives in a buffer whose leading (row-ish) dimensions are
rounded up to powers of two, so C and M can grow across structure
generations without reallocating — and, downstream, without changing
the plan shapes the XLA kernel was compiled for more often than the
sticky-``M`` bucketing already does.

Growth policy: when ``ensure`` asks for a shape that exceeds a slab's
capacity along any axis, the slab is reallocated at the next power of
two per overflowing axis (doubling amortizes to O(1) per row ever
stored), the live region is copied over and the new territory is
filled with the plane's pad value.  Shrink never happens — a smaller
request just views a prefix of the slab, so transient peaks don't
cause realloc churn.

The arena also keeps the occupancy/growth counters surfaced as
``kueue_pack_arena_*`` gauges.
"""

from __future__ import annotations

import numpy as np


def _cap(n: int) -> int:
    """Slab capacity for a requested extent: next power of two ≥ n
    (min 4, so early growth doesn't realloc every other row)."""
    c = 4
    while c < n:
        c <<= 1
    return c


class PlaneArena:
    """Named persistent plane slabs; see module docstring."""

    def __init__(self):
        self._slabs: dict[str, np.ndarray] = {}
        self._fills: dict[str, object] = {}
        self.stats = {"arena_growth_events": 0, "arena_planes": 0,
                      "arena_bytes": 0, "arena_used_bytes": 0}

    def drop(self) -> None:
        """Forget every slab (structure change with new trailing axes)."""
        self._slabs.clear()
        self._fills.clear()

    def ensure(self, name: str, shape: tuple, dtype, fill,
               grow_axes: int = 2) -> np.ndarray:
        """Return a ``shape``-sized view of the named slab, growing (or
        creating) the slab as needed.  The first ``grow_axes`` axes get
        power-of-two capacity; trailing axes are exact — a trailing-axis
        or dtype mismatch (new structure with different R/F) drops and
        reallocates the slab.  New territory is filled with ``fill``."""
        shape = tuple(int(s) for s in shape)
        grow_axes = min(grow_axes, len(shape))
        slab = self._slabs.get(name)
        want = tuple(_cap(s) for s in shape[:grow_axes]) + shape[grow_axes:]
        if (slab is None or slab.dtype != np.dtype(dtype)
                or slab.ndim != len(shape)
                or slab.shape[grow_axes:] != shape[grow_axes:]):
            slab = np.full(want, fill, dtype=dtype)
            if name in self._slabs:
                self.stats["arena_growth_events"] += 1
            self._slabs[name] = slab
            self._fills[name] = fill
        elif any(slab.shape[i] < shape[i] for i in range(grow_axes)):
            cap = tuple(max(slab.shape[i], want[i])
                        for i in range(grow_axes)) + shape[grow_axes:]
            grown = np.full(cap, fill, dtype=dtype)
            grown[tuple(slice(0, s) for s in slab.shape)] = slab
            self._slabs[name] = slab = grown
            self.stats["arena_growth_events"] += 1
        return slab[tuple(slice(0, s) for s in shape)]

    def view(self, name: str, shape: tuple) -> np.ndarray:
        return self._slabs[name][tuple(slice(0, int(s)) for s in shape)]

    def refresh_stats(self, used_shapes: dict | None = None) -> dict:
        """Recompute the byte counters; ``used_shapes`` maps plane name
        → live view shape for the occupancy ratio."""
        total = sum(s.nbytes for s in self._slabs.values())
        used = 0
        if used_shapes:
            for name, shp in used_shapes.items():
                slab = self._slabs.get(name)
                if slab is None:
                    continue
                n = slab.dtype.itemsize
                for s in shp:
                    n *= int(s)
                used += n
        self.stats["arena_planes"] = len(self._slabs)
        self.stats["arena_bytes"] = int(total)
        self.stats["arena_used_bytes"] = int(used)
        return self.stats
