"""Topology flavor snapshot: the domain tree + assignment algorithm.

Capability parity with reference pkg/cache/tas_flavor_snapshot.go:91: a tree
of topology domains (e.g. block → rack → hostname) built from node labels,
with per-leaf free capacity.  ``find_topology_assignment`` mirrors the
two-phase algorithm (tas_flavor_snapshot.go:406-613): phase 1 fills pod
counts bottom-up; phase 2 picks the lowest level whose best domain fits all
pods (falling back upward for `preferred`), then walks down level by level
minimizing the number of domains (BestFit).

The batched/TPU formulation of the same algorithm lives in
kueue_tpu.ops.tas_kernel (segment reductions over a level-indexed CSR tree).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.types import (
    PodSetTopologyRequest,
    TopologyAssignment,
    TopologyDomainAssignment,
)
from .tas_cache import NodeInfo


@dataclass(eq=False)  # identity hash: domains are keyed in chosen-maps
class Domain:
    """One topology domain (reference tas_flavor_snapshot.go `domain`)."""
    id: tuple                      # label values from root level to this level
    level: int
    parent: Optional["Domain"] = None
    children: list["Domain"] = field(default_factory=list)
    # leaf-only: free capacity (canonical ints)
    free: dict[str, int] = field(default_factory=dict)
    # per-query state
    state: int = 0                 # how many pods fit in this subtree


class TASFlavorSnapshot:
    def __init__(self, flavor: str, levels: list[str]):
        self.flavor = flavor
        self.levels = levels
        self.leaves: dict[tuple, Domain] = {}
        self.roots: list[Domain] = []
        self.domains_per_level: list[list[Domain]] = [[] for _ in levels]

    @staticmethod
    def build(flavor: str, levels: list[str], nodes: list[NodeInfo],
              usage: dict[tuple, dict[str, int]]) -> "TASFlavorSnapshot":
        snap = TASFlavorSnapshot(flavor, levels)
        by_id: dict[tuple, Domain] = {}
        for node in nodes:
            values = tuple(node.labels.get(lvl, "") for lvl in levels)
            if any(v == "" for v in values):
                continue  # node not fully labelled for this topology
            leaf = by_id.get(values)
            if leaf is None:
                leaf = Domain(id=values, level=len(levels) - 1)
                by_id[values] = leaf
                snap.leaves[values] = leaf
            for rname, cap in node.capacity.items():
                leaf.free[rname] = leaf.free.get(rname, 0) + cap
        for dom_id, used in usage.items():
            leaf = snap.leaves.get(tuple(dom_id))
            if leaf is not None:
                for rname, qty in used.items():
                    leaf.free[rname] = leaf.free.get(rname, 0) - qty
        # link up the tree
        for leaf in list(snap.leaves.values()):
            child = leaf
            for lvl in range(len(levels) - 2, -1, -1):
                pid = child.id[: lvl + 1]
                parent = by_id.get(pid)
                if parent is None:
                    parent = Domain(id=pid, level=lvl)
                    by_id[pid] = parent
                if child.parent is None:
                    child.parent = parent
                    parent.children.append(child)
                child = parent
        for dom in by_id.values():
            snap.domains_per_level[dom.level].append(dom)
            if dom.level == 0:
                snap.roots.append(dom)
        return snap

    # ------------------------------------------------------------------

    def _fill_in_counts(self, per_pod: dict[str, int],
                        assumed: dict[tuple, dict[str, int]] | None = None) -> None:
        """Phase 1 (reference tas_flavor_snapshot.go fillInCounts): compute
        how many pods fit in each domain, bottom-up."""
        for leaf in self.leaves.values():
            fits = None
            for rname, need in per_pod.items():
                if need <= 0:
                    continue
                free = leaf.free.get(rname, 0)
                if assumed:
                    free -= assumed.get(leaf.id, {}).get(rname, 0)
                n = max(0, free) // need
                fits = n if fits is None else min(fits, n)
            leaf.state = fits if fits is not None else 0
        for lvl in range(len(self.levels) - 2, -1, -1):
            for dom in self.domains_per_level[lvl]:
                dom.state = sum(c.state for c in dom.children)

    def _level_index(self, label: Optional[str]) -> Optional[int]:
        if label is None:
            return None
        try:
            return self.levels.index(label)
        except ValueError:
            return None

    def find_topology_assignment(
            self, count: int, per_pod: dict[str, int],
            request: PodSetTopologyRequest,
            assumed: dict[tuple, dict[str, int]] | None = None,
    ) -> tuple[Optional[TopologyAssignment], str]:
        """Phase 1 + 2 (reference tas_flavor_snapshot.go:406-613).

        Returns (assignment at the leaf level, reason-on-failure).
        """
        if not self.levels:
            return None, "no topology levels"
        required_idx = self._level_index(request.required)
        preferred_idx = self._level_index(request.preferred)
        if request.required and required_idx is None:
            return None, f"level {request.required} not in topology"
        if request.preferred and preferred_idx is None:
            return None, f"level {request.preferred} not in topology"
        if self._device_kernel_eligible(request):
            return self._find_device(count, per_pod, request, assumed,
                                     required_idx, preferred_idx)
        self._fill_in_counts(per_pod, assumed)

        if request.unconstrained:
            # any set of leaves; minimize domain count from the top
            total = sum(r.state for r in self.roots)
            if total < count:
                return None, self._fit_message(count, total)
            chosen = self._select_from(
                self._sorted_domains(self.roots, unconstrained=True),
                count, unconstrained=True)
        else:
            if required_idx is not None:
                fit_idx, domain = self._find_fit_at(required_idx, count)
                if domain is None:
                    return None, self._fit_message_level(count, required_idx)
            else:
                start = preferred_idx if preferred_idx is not None else len(self.levels) - 1
                fit_idx, domain = None, None
                for lvl in range(start, -1, -1):
                    fit_idx, domain = self._find_fit_at(lvl, count)
                    if domain is not None:
                        break
                if domain is None:
                    # final fallback: split across root domains
                    total = sum(r.state for r in self.roots)
                    if total < count:
                        return None, self._fit_message(count, total)
                    chosen = self._select_from(
                        self._sorted_domains(self.roots), count)
                    return self._assignment_from(chosen), ""
            chosen = {domain: count}
        return self._assignment_from(chosen), ""

    # -- device kernel path (ops/tas_kernel, TASDeviceKernel gate) -----

    def _device_kernel_eligible(self, request: PodSetTopologyRequest) -> bool:
        """The batched kernel implements all three TAS profiles
        (BestFit default, TASProfileMostFreeCapacity,
        TASProfileLeastFreeCapacity incl. Mixed's unconstrained
        variant — tas_flavor_snapshot.go:551-568)."""
        from .. import features
        return features.enabled("TASDeviceKernel") and bool(self.leaves)

    def _device_profile(self, unconstrained: bool) -> str:
        if self._use_best_fit(unconstrained):
            return "bestfit"
        if self._use_least_free(unconstrained):
            return "leastfree"
        return "mostfree"

    def _find_device(self, count: int, per_pod: dict[str, int],
                     request: PodSetTopologyRequest,
                     assumed: dict[tuple, dict[str, int]] | None,
                     required_idx: Optional[int],
                     preferred_idx: Optional[int],
                     ) -> tuple[Optional[TopologyAssignment], str]:
        """find_topology_assignment on the batched kernel
        (ops/tas_kernel: segment reductions over level-CSR arrays),
        decision-identical to the scalar walk (tests/test_tas_kernel.py
        + test_tas_device_path)."""
        import numpy as np
        from ..ops import tas_kernel as tk

        packed = getattr(self, "_packed_tas", None)
        if packed is None:
            packed = self._packed_tas = tk.pack_tas(self)
        sizes = tuple(packed.level_sizes)
        parents = tuple(packed.parents)
        r_index = {r: i for i, r in enumerate(packed.resource_names)}

        per_pod_vec = np.zeros(max(1, len(packed.resource_names)),
                               dtype=np.int32)
        unknown_requested = False
        for r, v in per_pod.items():
            if v <= 0:
                continue
            ri = r_index.get(r)
            if ri is None:
                unknown_requested = True  # no leaf has it: states all 0
            else:
                per_pod_vec[ri] = v
        leaf_free = packed.leaf_free
        if assumed:
            leaf_free = leaf_free.copy()
            for i, did in enumerate(packed.leaf_ids):
                a = assumed.get(did)
                if a:
                    for r, v in a.items():
                        ri = r_index.get(r)
                        if ri is not None:
                            leaf_free[i, ri] = max(0, leaf_free[i, ri] - v)
        if unknown_requested:
            leaf_free = np.zeros_like(packed.leaf_free)

        def level_states(level: int) -> np.ndarray:
            states = tk.fill_counts(leaf_free, per_pod_vec, parents,
                                    level_sizes=sizes)
            return np.asarray(states[level])

        def total_fit() -> int:
            return int(level_states(0).sum())

        def finish(leaf_counts) -> TopologyAssignment:
            domains = [TopologyDomainAssignment(values=list(did),
                                                count=int(c))
                       for did, c in sorted(
                           (packed.leaf_ids[i], int(c))
                           for i, c in enumerate(np.asarray(leaf_counts))
                           if c)]
            return TopologyAssignment(levels=list(self.levels),
                                      domains=domains)

        profile = self._device_profile(False)
        if request.unconstrained:
            ok, counts = tk.split_across_roots(
                leaf_free, per_pod_vec, parents, count, level_sizes=sizes,
                profile=self._device_profile(True),
                descend_profile=profile)
            if not bool(ok):
                return None, self._fit_message(count, total_fit())
            return finish(counts), ""

        if required_idx is not None:
            ok, counts = tk.best_fit_descend(
                leaf_free, per_pod_vec, parents, count,
                level_sizes=sizes, level=required_idx, profile=profile)
            if not bool(ok):
                # host message reads Domain.state, unfilled on this path:
                # compute the best single-domain fit from kernel states
                best = int(level_states(required_idx).max(initial=0))
                return None, (
                    f"topology {self.flavor!r} allows to fit only {best} "
                    f"out of {count} pod(s) in a single "
                    f"{self.levels[required_idx]!r}")
            return finish(counts), ""

        start = (preferred_idx if preferred_idx is not None
                 else len(self.levels) - 1)
        for lvl in range(start, -1, -1):
            ok, counts = tk.best_fit_descend(
                leaf_free, per_pod_vec, parents, count,
                level_sizes=sizes, level=lvl, profile=profile)
            if bool(ok):
                return finish(counts), ""
        ok, counts = tk.split_across_roots(
            leaf_free, per_pod_vec, parents, count, level_sizes=sizes,
            profile=profile)
        if not bool(ok):
            return None, self._fit_message(count, total_fit())
        return finish(counts), ""

    # -- helpers --

    @staticmethod
    def _domain_order(dom: Domain):
        # default sortedDomains order: state descending, ties by id
        # (reference tas_flavor_snapshot.go:631)
        return (-dom.state, dom.id)

    @staticmethod
    def _use_best_fit(unconstrained: bool = False) -> bool:
        """reference tas_flavor_snapshot.go:551 useBestFitAlgorithm."""
        from .. import features
        if (features.enabled("TASProfileMostFreeCapacity")
                or features.enabled("TASProfileLeastFreeCapacity")
                or (unconstrained and features.enabled("TASProfileMixed"))):
            return False
        return True

    @staticmethod
    def _use_least_free(unconstrained: bool = False) -> bool:
        """reference tas_flavor_snapshot.go:561."""
        from .. import features
        return (features.enabled("TASProfileLeastFreeCapacity")
                or (unconstrained and features.enabled("TASProfileMixed")))

    def _sorted_domains(self, domains: list[Domain],
                        unconstrained: bool = False) -> list[Domain]:
        """reference sortedDomains: state desc, ties by id; the
        least-free profiles reverse the order."""
        out = sorted(domains, key=self._domain_order)
        if self._use_least_free(unconstrained):
            out.reverse()
        return out

    def _find_fit_at(self, level: int, count: int) -> tuple[int, Optional[Domain]]:
        """Best single domain at `level` that fits all pods.

        Default BestFit picks the least spare capacity (reference
        findBestFitDomainIdx); TASProfileMostFreeCapacity picks the most
        free (the top of sortedDomains)."""
        fitting = [d for d in self.domains_per_level[level]
                   if d.state >= count]
        if not fitting:
            return level, None
        if self._use_best_fit() or self._use_least_free():
            return level, min(fitting, key=lambda d: (d.state, d.id))
        return level, min(fitting, key=self._domain_order)

    def _select_from(self, ordered: list[Domain], count: int,
                     unconstrained: bool = False) -> dict[Domain, int]:
        """Multi-domain split over a sortedDomains list (reference
        updateCountsToMinimum, tas_flavor_snapshot.go:571): walk the
        order taking whole domains; under BestFit, once the remainder
        fits a single domain, pick the tightest such domain for it."""
        chosen: dict[Domain, int] = {}
        remaining = count
        best_fit = self._use_best_fit(unconstrained)
        for i, dom in enumerate(ordered):
            if remaining <= 0:
                break
            if best_fit and dom.state >= remaining:
                # optimize the last domain (findBestFitDomainIdx)
                dom = min((d for d in ordered[i:] if d.state >= remaining),
                          key=lambda d: (d.state, d.id))
            if dom.state >= remaining:
                chosen[dom] = chosen.get(dom, 0) + remaining
                return chosen
            if dom.state > 0:
                chosen[dom] = chosen.get(dom, 0) + dom.state
                remaining -= dom.state
        return chosen

    def _assignment_from(self, chosen: dict[Domain, int]) -> TopologyAssignment:
        """Walk chosen domains down to leaves, minimizing leaf-domain count."""
        leaf_counts: dict[tuple, int] = {}
        for dom, cnt in chosen.items():
            self._descend(dom, cnt, leaf_counts)
        domains = [TopologyDomainAssignment(values=list(dom_id), count=cnt)
                   for dom_id, cnt in sorted(leaf_counts.items())]
        return TopologyAssignment(levels=list(self.levels), domains=domains)

    def _descend(self, dom: Domain, cnt: int, out: dict[tuple, int]) -> None:
        if not dom.children:  # leaf
            out[dom.id] = out.get(dom.id, 0) + cnt
            return
        # updateCountsToMinimum over the children at each level
        for child, take in self._select_from(
                self._sorted_domains(dom.children), cnt).items():
            self._descend(child, take, out)

    def _fit_message(self, count: int, total: int) -> str:
        return (f"topology {self.flavor!r} allows to fit only {total} "
                f"out of {count} pod(s)")

    def _fit_message_level(self, count: int, level: int) -> str:
        best = max((d.state for d in self.domains_per_level[level]), default=0)
        return (f"topology {self.flavor!r} allows to fit only {best} "
                f"out of {count} pod(s) in a single {self.levels[level]!r}")
