"""The live cache of admitted state, rebuilt from the event stream.

Capability parity with reference pkg/cache/cache.go:102: holds the cohort
forest of ClusterQueues, resource flavors, admission checks and admitted
workloads; supports optimistic ``assume_workload``/``forget_workload``
(cache.go:610,636) ahead of the durable write; produces per-cycle
snapshots (snapshot.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from .. import hierarchy
from ..api.types import (
    Admission,
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LocalQueue,
    ResourceFlavor,
    StopPolicy,
    Topology,
)
from ..resources import FlavorResourceQuantities
from ..workload import Info, InfoOptions
from .snapshot import Snapshot
from .state import (
    CohortState,
    CQState,
    build_quotas,
    update_cluster_queue_resource_node,
    update_cohort_resource_node,
)
from .tas_cache import TASCache


class Cache:
    def __init__(self, info_options: InfoOptions | None = None,
                 fair_sharing_enabled: bool = False):
        self._lock = threading.RLock()
        self._mgr: hierarchy.Manager[CQState, CohortState] = hierarchy.Manager(CohortState)
        self.resource_flavors: dict[str, ResourceFlavor] = {}
        self.admission_checks: dict[str, AdmissionCheck] = {}
        self.local_queues: dict[str, LocalQueue] = {}
        self.assumed_workloads: set[str] = set()
        self.info_options = info_options or InfoOptions()
        self.fair_sharing_enabled = fair_sharing_enabled
        self.tas = TASCache()
        # Bumped on any spec-level change (CQ/cohort/flavor/check); the
        # solver caches its packed structure tensors against this.
        self.structure_generation = 0
        # workload key → owning CQ name (O(1) duplicate/ownership lookups;
        # the reference keys cache membership the same way, cache.go:536)
        self._wl_owner: dict[str, str] = {}
        # dirty-CQ journal feeding the incremental burst pack: admitted
        # table / usage / assumed-set mutations mark the owning CQ
        # (utils/journal.py); structure edits need no marks — they bump
        # structure_generation, which forces a full repack by key
        from ..utils.journal import PackJournal
        self.pack_journal = PackJournal()

    # ------------------------------------------------------------------
    # ClusterQueues / Cohorts
    # ------------------------------------------------------------------

    def add_or_update_cluster_queue(self, spec: ClusterQueue) -> None:
        with self._lock:
            existing = self._mgr.cluster_queues.get(spec.name)
            if existing is None:
                self._mgr.add_cluster_queue(spec.name, CQState(spec))
            else:
                existing.update_quotas(spec)
            self._mgr.update_cluster_queue_edge(spec.name, spec.cohort)
            self._rebuild()

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            cq = self._mgr.cluster_queues.get(name)
            if cq is not None:
                for key, info in cq.workloads.items():
                    self._tas_apply(info, -1)  # release domain capacity
                    self._wl_owner.pop(key, None)
            self._mgr.delete_cluster_queue(name)
            self._rebuild()

    def add_or_update_cohort(self, spec: Cohort) -> None:
        with self._lock:
            node = self._mgr.add_cohort(spec.name)
            node.payload.spec = spec
            node.payload.resource_node.quotas = build_quotas(spec.resource_groups)
            node.payload.fair_weight_milli = int(
                (spec.fair_sharing.weight if spec.fair_sharing else 1.0) * 1000)
            self._mgr.update_cohort_edge(spec.name, spec.parent_name)
            self._rebuild()

    def delete_cohort(self, name: str) -> None:
        with self._lock:
            self._mgr.delete_cohort(name)
            self._rebuild()

    # ------------------------------------------------------------------
    # Flavors / checks / local queues / topologies
    # ------------------------------------------------------------------

    def add_or_update_resource_flavor(self, flavor: ResourceFlavor) -> None:
        with self._lock:
            self.resource_flavors[flavor.name] = flavor
            if flavor.topology_name:
                self.tas.bind_flavor(flavor)
            self._update_all_statuses()

    def delete_resource_flavor(self, name: str) -> None:
        with self._lock:
            self.resource_flavors.pop(name, None)
            self.tas.unbind_flavor(name)
            self._update_all_statuses()

    def add_or_update_admission_check(self, check: AdmissionCheck) -> None:
        with self._lock:
            self.admission_checks[check.name] = check
            self._update_all_statuses()

    def delete_admission_check(self, name: str) -> None:
        with self._lock:
            self.admission_checks.pop(name, None)
            self._update_all_statuses()

    def add_or_update_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq

    def delete_local_queue(self, lq_key: str) -> None:
        with self._lock:
            self.local_queues.pop(lq_key, None)

    def add_or_update_topology(self, topology: Topology) -> None:
        with self._lock:
            self.tas.add_topology(topology)

    def delete_topology(self, name: str) -> None:
        with self._lock:
            self.tas.delete_topology(name)

    # ------------------------------------------------------------------
    # Workloads (admitted / assumed) — reference cache.go:536-658
    # ------------------------------------------------------------------

    def cluster_queue(self, name: str) -> Optional[CQState]:
        return self._mgr.cluster_queues.get(name)

    def _tas_apply(self, info: Info, sign: int) -> None:
        """Charge/release the workload's topology-domain usage in the
        TAS cache (the reference tracks TAS usage alongside quota in
        cache.AddOrUpdateWorkload; tas_cache usage feeds the per-cycle
        TASFlavorSnapshot free capacity)."""
        adm = info.obj.admission
        if adm is None:
            return
        # per-pod values from the TRANSFORMED totals (workload.py applies
        # resource transformations/exclusions) so charged usage matches
        # what the assigner's _find_tas checks next cycle; total_requests
        # already carries the implicit "pods" resource
        by_name = {psr.name: psr for psr in info.total_requests}
        for a in adm.pod_set_assignments:
            ta = a.topology_assignment
            if ta is None:
                continue
            flavor = next((f for f in a.flavors.values()
                           if f in self.tas.flavors), None)
            if flavor is None:
                continue
            psr = by_name.get(a.name)
            if psr is None or psr.count <= 0:
                continue
            per_pod = {r: v // max(1, psr.count)
                       for r, v in psr.requests.items()}
            per_pod.setdefault("pods", 1)
            for dom in ta.domains:
                self.tas.add_usage(
                    flavor, tuple(dom.values),
                    {r: v * dom.count for r, v in per_pod.items()},
                    sign)

    def add_or_update_workload(self, info: Info) -> bool:
        with self._lock:
            if info.obj.admission is None:
                return False
            # Remove any previous accounting first — the workload may have
            # been re-admitted to a different CQ (reference cache.go
            # UpdateWorkload removes from the old CQ before adding).
            owner = self._find_owner(info)
            if owner is not None:
                self._tas_apply(owner.workloads[info.key], -1)
                owner.remove_workload(owner.workloads[info.key])
                self._wl_owner.pop(info.key, None)
                self.pack_journal.touch(owner.name)
            cq = self._mgr.cluster_queues.get(info.obj.admission.cluster_queue)
            self.pack_journal.touch(info.obj.admission.cluster_queue)
            if cq is None:
                self.assumed_workloads.discard(info.key)
                return False
            info.cluster_queue = cq.name
            cq.add_workload(info)
            self._tas_apply(info, +1)
            self._wl_owner[info.key] = cq.name
            self.assumed_workloads.discard(info.key)
            return True

    def delete_workload(self, info: Info) -> None:
        with self._lock:
            cq = self._find_owner(info)
            if cq is not None:
                self._tas_apply(cq.workloads[info.key], -1)
                cq.remove_workload(cq.workloads[info.key])
                self._wl_owner.pop(info.key, None)
                self.pack_journal.touch(cq.name)
            elif info.key in self.assumed_workloads:
                # the assumed set gates the owner CQ's pending rows
                owned = getattr(info, "cluster_queue", None)
                if owned:
                    self.pack_journal.touch(owned)
                else:
                    self.pack_journal.touch_all()
            self.assumed_workloads.discard(info.key)

    def assume_workload(self, info: Info) -> bool:
        """Optimistic admission before the durable write lands
        (reference cache.go:610)."""
        with self._lock:
            if info.obj.admission is None or info.key in self.assumed_workloads:
                return False
            if self._find_owner(info) is not None:
                return False  # already accounted — never double-count
            cq = self._mgr.cluster_queues.get(info.obj.admission.cluster_queue)
            if cq is None:
                return False
            info.cluster_queue = cq.name
            cq.add_workload(info)
            self._tas_apply(info, +1)
            self._wl_owner[info.key] = cq.name
            self.assumed_workloads.add(info.key)
            self.pack_journal.touch(cq.name)
            return True

    def forget_workload(self, info: Info) -> bool:
        """reference cache.go:636."""
        with self._lock:
            if info.key not in self.assumed_workloads:
                return False
            cq = self._find_owner(info)
            if cq is not None:
                self._tas_apply(cq.workloads[info.key], -1)
                cq.remove_workload(cq.workloads[info.key])
                self._wl_owner.pop(info.key, None)
                self.pack_journal.touch(cq.name)
            else:
                owned = getattr(info, "cluster_queue", None)
                if owned:
                    self.pack_journal.touch(owned)
                else:
                    self.pack_journal.touch_all()
            self.assumed_workloads.discard(info.key)
            return True

    def _find_owner(self, info: Info) -> Optional[CQState]:
        owner = self._wl_owner.get(info.key)
        if owner is not None:
            cq = self._mgr.cluster_queues.get(owner)
            if cq is not None and info.key in cq.workloads:
                return cq
        return None

    # ------------------------------------------------------------------
    # Snapshot — reference snapshot.go:104
    # ------------------------------------------------------------------

    def snapshot(self) -> Snapshot:
        with self._lock:
            cq_map: dict[str, CQState] = {}
            roots = []
            for node in self._mgr.roots():
                roots.append(node.payload.clone_subtree(None, cq_map))
            for name, cq in self._mgr.cluster_queues.items():
                if name not in cq_map:  # cohortless CQ
                    cq_map[name] = cq.clone(parent=None)
            inactive = {name for name, cq in self._mgr.cluster_queues.items()
                        if not cq.active}
            return Snapshot(
                cluster_queues=cq_map,
                roots=roots,
                inactive_cluster_queues=inactive,
                resource_flavors=dict(self.resource_flavors),
                tas_flavors=self.tas.snapshot(),
                fair_sharing_enabled=self.fair_sharing_enabled,
                structure_generation=self.structure_generation,
            )

    # ------------------------------------------------------------------
    # Status / reporting
    # ------------------------------------------------------------------

    def usage(self, cq_name: str) -> FlavorResourceQuantities:
        cq = self._mgr.cluster_queues.get(cq_name)
        return cq.resource_node.usage.clone() if cq else FlavorResourceQuantities()

    def cluster_queue_names(self) -> list[str]:
        return list(self._mgr.cluster_queues)

    def local_queue_usage(self, namespace: str, lq_name: str
                          ) -> FlavorResourceQuantities:
        """Usage aggregated over a LocalQueue's admitted workloads
        (reference cache.go:786 LocalQueueUsage)."""
        out = FlavorResourceQuantities()
        with self._lock:
            lq = self.local_queues.get(f"{namespace}/{lq_name}")
            if lq is None:
                return out
            cq = self._mgr.cluster_queues.get(lq.cluster_queue)
            if cq is None:
                return out
            infos = list(cq.workloads.values())
        for info in infos:
            wl = info.obj
            if wl.namespace == namespace and wl.queue_name == lq_name:
                for fr, v in info.usage().items():
                    out[fr] = out.get(fr, 0) + v
        return out

    def cohort_state(self, name: str) -> Optional[CohortState]:
        node = self._mgr.cohort(name)
        return node.payload if node else None

    # ------------------------------------------------------------------
    # Internal wiring
    # ------------------------------------------------------------------

    def _rebuild(self) -> None:
        """Mirror hierarchy edges into the state payloads and recompute the
        subtree quotas from every root (reference resource_node.go:157)."""
        for node in self._mgr.cohorts.values():
            payload = node.payload
            payload.parent = node.parent.payload if node.parent else None
            payload.child_cohorts = [c.payload for c in node.child_cohorts.values()]
            payload.child_cqs = list(node.child_cqs.values())
        for name, cq in self._mgr.cluster_queues.items():
            parent_node = self._mgr.cq_parent(name)
            cq.parent = parent_node.payload if parent_node else None
        # Cohorts in a parent-edge cycle are unreachable from any root (a
        # cycle member is never parentless); break their mirrored parent
        # pointers so quota queries stay total, and deactivate their CQs.
        reachable: set[str] = set()
        for node in self._mgr.roots():
            for sub in node.walk_subtree():
                reachable.add(sub.name)
            update_cohort_resource_node(node.payload)
        self._cyclic_cohorts = set(self._mgr.cohorts) - reachable
        for name in self._cyclic_cohorts:
            self._mgr.cohorts[name].payload.parent = None
        for name, cq in self._mgr.cluster_queues.items():
            if self._mgr.cq_parent(name) is None:
                update_cluster_queue_resource_node(cq)
        self._update_all_statuses()

    def _update_all_statuses(self) -> None:
        self.structure_generation += 1
        for name, cq in self._mgr.cluster_queues.items():
            reasons = []
            for rg in cq.spec.resource_groups:
                for fq in rg.flavors:
                    if fq.name not in self.resource_flavors:
                        reasons.append(f"FlavorNotFound:{fq.name}")
            for ac in cq.spec.admission_checks:
                check = self.admission_checks.get(ac)
                if check is None or not check.active:
                    reasons.append(f"CheckNotFoundOrInactive:{ac}")
            if cq.spec.stop_policy != StopPolicy.NONE:
                reasons.append("Stopped")
            parent_node = self._mgr.cq_parent(name)
            if parent_node is not None and getattr(self, "_cyclic_cohorts", None):
                if parent_node.name in self._cyclic_cohorts:
                    reasons.append("CohortCycle")
            cq.active = not reasons
            cq.inactive_reasons = reasons
