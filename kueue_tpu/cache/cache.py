"""The live cache of admitted state, rebuilt from the event stream.

Capability parity with reference pkg/cache/cache.go:102: holds the cohort
forest of ClusterQueues, resource flavors, admission checks and admitted
workloads; supports optimistic ``assume_workload``/``forget_workload``
(cache.go:610,636) ahead of the durable write; produces per-cycle
snapshots (snapshot.py).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from .. import hierarchy
from ..features import env_value
from ..api.types import (
    Admission,
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LocalQueue,
    ResourceFlavor,
    StopPolicy,
    Topology,
)
from ..resources import FlavorResourceQuantities
from ..workload import Info, InfoOptions
from .snapshot import Snapshot
from .state import (
    CohortState,
    CQState,
    SnapTag,
    build_quotas,
    update_cluster_queue_resource_node,
    update_cohort_resource_node,
)
from .tas_cache import TASCache


class _SnapCache:
    """Clone forest retained between snapshots for incremental reuse.

    Valid for exactly one ``structure_generation``: spec-level edits
    (CQ/cohort/flavor/check churn, activeness recompute) bump the
    generation and force a full rebuild, so the cache only has to track
    *usage*-level dirt.  A cached root tree is reused verbatim when
    (a) the live side didn't touch any of its CQs since the last drain
    (PackJournal ``snap_dirty`` channel) and (b) no snapshot consumer
    scribbled on the clone (SnapTag)."""

    __slots__ = ("generation", "root_order", "root_clones", "root_tags",
                 "free_clones", "free_tags", "tree_of_cq", "cq_map",
                 "inactive", "flavors")

    def __init__(self, generation: int):
        self.generation = generation
        self.root_order: list[str] = []            # roots() build order
        self.root_clones: dict[str, CohortState] = {}
        self.root_tags: dict[str, SnapTag] = {}
        self.free_clones: dict[str, CQState] = {}  # cohortless CQs
        self.free_tags: dict[str, SnapTag] = {}
        self.tree_of_cq: dict[str, str] = {}       # cq name → root name
        self.cq_map: dict[str, CQState] = {}
        self.inactive: set[str] = set()
        self.flavors: dict[str, ResourceFlavor] = {}


class Cache:
    def __init__(self, info_options: InfoOptions | None = None,
                 fair_sharing_enabled: bool = False):
        self._lock = threading.RLock()
        self._mgr: hierarchy.Manager[CQState, CohortState] = hierarchy.Manager(CohortState)
        self.resource_flavors: dict[str, ResourceFlavor] = {}
        self.admission_checks: dict[str, AdmissionCheck] = {}
        self.local_queues: dict[str, LocalQueue] = {}
        self.assumed_workloads: set[str] = set()
        self.info_options = info_options or InfoOptions()
        self.fair_sharing_enabled = fair_sharing_enabled
        self.tas = TASCache()
        # Bumped on any spec-level change (CQ/cohort/flavor/check); the
        # solver caches its packed structure tensors against this.
        self.structure_generation = 0
        # workload key → owning CQ name (O(1) duplicate/ownership lookups;
        # the reference keys cache membership the same way, cache.go:536)
        self._wl_owner: dict[str, str] = {}
        # dirty-CQ journal feeding the incremental burst pack: admitted
        # table / usage / assumed-set mutations mark the owning CQ
        # (utils/journal.py); structure edits need no marks — they bump
        # structure_generation, which forces a full repack by key
        from ..utils.journal import PackJournal
        self.pack_journal = PackJournal()
        # Parallel host plane (utils/parallel_host.py): the driver hands
        # its HostPool down so _rebuild can fan the per-root quota
        # recomputation out across workers; None/inactive = serial.
        self.host_pool = None
        # Incremental snapshot maintenance: per-cycle snapshot cost is
        # O(arrivals + dirty rows), not O(universe).  The clone forest
        # is retained across cycles and only journal-dirty or
        # consumer-mutated trees are re-cloned.  KUEUE_TPU_SNAP_INCREMENTAL=0
        # restores the old full-rebuild-every-cycle behavior (used by
        # the parity tests).
        # Bulk-apply support: while deferred, topology mutations mark
        # the hierarchy pending instead of re-deriving the quota trees,
        # so applying N ClusterQueues costs one O(N) rebuild, not N
        # (the O(N^2) setup wall at 100k CQs).
        self._rebuild_deferred = False
        self._rebuild_pending = False
        self._snap_cache: Optional[_SnapCache] = None
        self._snap_incremental = env_value(
            "KUEUE_TPU_SNAP_INCREMENTAL").lower() not in ("0", "false")
        self.snapshot_stats: dict[str, int] = {
            "snap_builds": 0, "snap_full": 0, "snap_incremental": 0,
            "snap_trees_recloned": 0, "snap_trees_reused": 0,
            "snap_cqs_recloned": 0, "snap_cqs_reused": 0,
        }

    # ------------------------------------------------------------------
    # ClusterQueues / Cohorts
    # ------------------------------------------------------------------

    def add_or_update_cluster_queue(self, spec: ClusterQueue) -> None:
        with self._lock:
            existing = self._mgr.cluster_queues.get(spec.name)
            if existing is None:
                self._mgr.add_cluster_queue(spec.name, CQState(spec))
            else:
                existing.update_quotas(spec)
            self._mgr.update_cluster_queue_edge(spec.name, spec.cohort)
            self._rebuild()

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            cq = self._mgr.cluster_queues.get(name)
            if cq is not None:
                for key, info in cq.workloads.items():
                    self._tas_apply(info, -1)  # release domain capacity
                    self._wl_owner.pop(key, None)
            self._mgr.delete_cluster_queue(name)
            self._rebuild()

    def add_or_update_cohort(self, spec: Cohort) -> None:
        with self._lock:
            node = self._mgr.add_cohort(spec.name)
            node.payload.spec = spec
            node.payload.resource_node.quotas = build_quotas(spec.resource_groups)
            node.payload.fair_weight_milli = int(
                (spec.fair_sharing.weight if spec.fair_sharing else 1.0) * 1000)
            self._mgr.update_cohort_edge(spec.name, spec.parent_name)
            self._rebuild()

    def delete_cohort(self, name: str) -> None:
        with self._lock:
            self._mgr.delete_cohort(name)
            self._rebuild()

    # ------------------------------------------------------------------
    # Flavors / checks / local queues / topologies
    # ------------------------------------------------------------------

    def add_or_update_resource_flavor(self, flavor: ResourceFlavor) -> None:
        with self._lock:
            self.resource_flavors[flavor.name] = flavor
            if flavor.topology_name:
                self.tas.bind_flavor(flavor)
            self._update_all_statuses()

    def delete_resource_flavor(self, name: str) -> None:
        with self._lock:
            self.resource_flavors.pop(name, None)
            self.tas.unbind_flavor(name)
            self._update_all_statuses()

    def add_or_update_admission_check(self, check: AdmissionCheck) -> None:
        with self._lock:
            self.admission_checks[check.name] = check
            self._update_all_statuses()

    def delete_admission_check(self, name: str) -> None:
        with self._lock:
            self.admission_checks.pop(name, None)
            self._update_all_statuses()

    def add_or_update_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq

    def delete_local_queue(self, lq_key: str) -> None:
        with self._lock:
            self.local_queues.pop(lq_key, None)

    def add_or_update_topology(self, topology: Topology) -> None:
        with self._lock:
            self.tas.add_topology(topology)

    def delete_topology(self, name: str) -> None:
        with self._lock:
            self.tas.delete_topology(name)

    # ------------------------------------------------------------------
    # Workloads (admitted / assumed) — reference cache.go:536-658
    # ------------------------------------------------------------------

    def cluster_queue(self, name: str) -> Optional[CQState]:
        return self._mgr.cluster_queues.get(name)

    def _tas_apply(self, info: Info, sign: int) -> None:
        """Charge/release the workload's topology-domain usage in the
        TAS cache (the reference tracks TAS usage alongside quota in
        cache.AddOrUpdateWorkload; tas_cache usage feeds the per-cycle
        TASFlavorSnapshot free capacity)."""
        adm = info.obj.admission
        if adm is None:
            return
        # per-pod values from the TRANSFORMED totals (workload.py applies
        # resource transformations/exclusions) so charged usage matches
        # what the assigner's _find_tas checks next cycle; total_requests
        # already carries the implicit "pods" resource
        by_name = {psr.name: psr for psr in info.total_requests}
        for a in adm.pod_set_assignments:
            ta = a.topology_assignment
            if ta is None:
                continue
            flavor = next((f for f in a.flavors.values()
                           if f in self.tas.flavors), None)
            if flavor is None:
                continue
            psr = by_name.get(a.name)
            if psr is None or psr.count <= 0:
                continue
            per_pod = {r: v // max(1, psr.count)
                       for r, v in psr.requests.items()}
            per_pod.setdefault("pods", 1)
            for dom in ta.domains:
                self.tas.add_usage(
                    flavor, tuple(dom.values),
                    {r: v * dom.count for r, v in per_pod.items()},
                    sign)

    def add_or_update_workload(self, info: Info) -> bool:
        with self._lock:
            if info.obj.admission is None:
                return False
            # Remove any previous accounting first — the workload may have
            # been re-admitted to a different CQ (reference cache.go
            # UpdateWorkload removes from the old CQ before adding).
            owner = self._find_owner(info)
            if owner is not None:
                self._tas_apply(owner.workloads[info.key], -1)
                owner.remove_workload(owner.workloads[info.key])
                self._wl_owner.pop(info.key, None)
                self.pack_journal.touch(owner.name)
            cq = self._mgr.cluster_queues.get(info.obj.admission.cluster_queue)
            self.pack_journal.touch(info.obj.admission.cluster_queue)
            if cq is None:
                self.assumed_workloads.discard(info.key)
                return False
            info.cluster_queue = cq.name
            cq.add_workload(info)
            self._tas_apply(info, +1)
            self._wl_owner[info.key] = cq.name
            self.assumed_workloads.discard(info.key)
            return True

    def delete_workload(self, info: Info) -> None:
        with self._lock:
            cq = self._find_owner(info)
            if cq is not None:
                self._tas_apply(cq.workloads[info.key], -1)
                cq.remove_workload(cq.workloads[info.key])
                self._wl_owner.pop(info.key, None)
                self.pack_journal.touch(cq.name)
            elif info.key in self.assumed_workloads:
                # the assumed set gates the owner CQ's pending rows
                owned = getattr(info, "cluster_queue", None)
                if owned:
                    self.pack_journal.touch(owned)
                else:
                    self.pack_journal.touch_all()
            self.assumed_workloads.discard(info.key)

    def assume_workload(self, info: Info) -> bool:
        """Optimistic admission before the durable write lands
        (reference cache.go:610)."""
        with self._lock:
            if info.obj.admission is None or info.key in self.assumed_workloads:
                return False
            if self._find_owner(info) is not None:
                return False  # already accounted — never double-count
            cq = self._mgr.cluster_queues.get(info.obj.admission.cluster_queue)
            if cq is None:
                return False
            info.cluster_queue = cq.name
            cq.add_workload(info)
            self._tas_apply(info, +1)
            self._wl_owner[info.key] = cq.name
            self.assumed_workloads.add(info.key)
            self.pack_journal.touch(cq.name)
            return True

    def forget_workload(self, info: Info) -> bool:
        """reference cache.go:636."""
        with self._lock:
            if info.key not in self.assumed_workloads:
                return False
            cq = self._find_owner(info)
            if cq is not None:
                self._tas_apply(cq.workloads[info.key], -1)
                cq.remove_workload(cq.workloads[info.key])
                self._wl_owner.pop(info.key, None)
                self.pack_journal.touch(cq.name)
            else:
                owned = getattr(info, "cluster_queue", None)
                if owned:
                    self.pack_journal.touch(owned)
                else:
                    self.pack_journal.touch_all()
            self.assumed_workloads.discard(info.key)
            return True

    def _find_owner(self, info: Info) -> Optional[CQState]:
        owner = self._wl_owner.get(info.key)
        if owner is not None:
            cq = self._mgr.cluster_queues.get(owner)
            if cq is not None and info.key in cq.workloads:
                return cq
        return None

    # ------------------------------------------------------------------
    # Snapshot — reference snapshot.go:104
    # ------------------------------------------------------------------

    def snapshot(self) -> Snapshot:
        """Per-cycle snapshot.  Incremental: the clone forest from the
        previous snapshot is reused wholesale for every root tree whose
        CQs were neither touched on the live side (PackJournal snapshot
        channel) nor mutated on the clone side (SnapTag) — only dirty
        trees pay the re-clone.  A snapshot is valid until the next
        ``snapshot()`` call (the scheduler's within-cycle use), same as
        the previous full-rebuild contract which already shared Info
        objects with the live store."""
        with self._lock:
            gen = self.structure_generation
            sc = self._snap_cache
            dirty, was_all = self.pack_journal.drain_snapshot()
            if (not self._snap_incremental or sc is None
                    or sc.generation != gen or was_all):
                sc = self._snapshot_full(gen)
            else:
                self._snapshot_refresh(sc, dirty)
            self.snapshot_stats["snap_builds"] += 1
            return Snapshot(
                cluster_queues=dict(sc.cq_map),
                roots=[sc.root_clones[r] for r in sc.root_order],
                inactive_cluster_queues=set(sc.inactive),
                resource_flavors=dict(sc.flavors),
                tas_flavors=self.tas.snapshot(),
                fair_sharing_enabled=self.fair_sharing_enabled,
                structure_generation=gen,
            )

    def _snapshot_full(self, gen: int) -> _SnapCache:
        sc = _SnapCache(gen)
        for node in self._mgr.roots():
            self._snap_clone_root(sc, node)
        for name, cq in self._mgr.cluster_queues.items():
            if name not in sc.cq_map:  # cohortless CQ
                self._snap_clone_free(sc, name, cq)
        sc.inactive = {name for name, cq in self._mgr.cluster_queues.items()
                       if not cq.active}
        sc.flavors = dict(self.resource_flavors)
        self._snap_cache = sc
        self.snapshot_stats["snap_full"] += 1
        return sc

    def _snapshot_refresh(self, sc: _SnapCache, dirty: set) -> None:
        dirty_roots: set[str] = set()
        dirty_free: set[str] = set()
        for name in dirty:
            root = sc.tree_of_cq.get(name)
            if root is not None:
                dirty_roots.add(root)
            elif name in sc.free_clones:
                dirty_free.add(name)
            # else: touch for a CQ unknown at this generation — any
            # add/delete that could explain it bumped the generation
        for rname, tag in sc.root_tags.items():
            if tag.mutated:
                dirty_roots.add(rname)
        for name, tag in sc.free_tags.items():
            if tag.mutated:
                dirty_free.add(name)
        st = self.snapshot_stats
        recloned_before = st["snap_cqs_recloned"]
        for rname in dirty_roots:
            node = self._mgr.cohorts.get(rname)
            if node is not None:
                # same generation → same membership: the re-clone
                # overwrites exactly the stale cq_map/tree_of_cq entries
                self._snap_clone_root(sc, node)
        for name in dirty_free:
            cq = self._mgr.cluster_queues.get(name)
            if cq is not None:
                self._snap_clone_free(sc, name, cq)
        st["snap_incremental"] += 1
        st["snap_trees_reused"] += len(sc.root_clones) - len(dirty_roots)
        st["snap_cqs_reused"] += (
            len(sc.cq_map) - (st["snap_cqs_recloned"] - recloned_before))

    def _snap_clone_root(self, sc: _SnapCache, node) -> None:
        sub: dict[str, CQState] = {}
        clone = node.payload.clone_subtree(None, sub)
        tag = SnapTag()
        for cq in sub.values():
            cq._snap_tag = tag
        name = node.name
        if name not in sc.root_clones:
            sc.root_order.append(name)
        sc.root_clones[name] = clone
        sc.root_tags[name] = tag
        for cq_name in sub:
            sc.tree_of_cq[cq_name] = name
        sc.cq_map.update(sub)
        self.snapshot_stats["snap_trees_recloned"] += 1
        self.snapshot_stats["snap_cqs_recloned"] += len(sub)

    def _snap_clone_free(self, sc: _SnapCache, name: str, cq: CQState) -> None:
        c = cq.clone(parent=None)
        tag = SnapTag()
        c._snap_tag = tag
        sc.free_clones[name] = c
        sc.free_tags[name] = tag
        sc.cq_map[name] = c
        self.snapshot_stats["snap_cqs_recloned"] += 1

    # ------------------------------------------------------------------
    # Status / reporting
    # ------------------------------------------------------------------

    def usage(self, cq_name: str) -> FlavorResourceQuantities:
        cq = self._mgr.cluster_queues.get(cq_name)
        return cq.resource_node.usage.clone() if cq else FlavorResourceQuantities()

    def cluster_queue_names(self) -> list[str]:
        return list(self._mgr.cluster_queues)

    def local_queue_usage(self, namespace: str, lq_name: str
                          ) -> FlavorResourceQuantities:
        """Usage aggregated over a LocalQueue's admitted workloads
        (reference cache.go:786 LocalQueueUsage)."""
        out = FlavorResourceQuantities()
        with self._lock:
            lq = self.local_queues.get(f"{namespace}/{lq_name}")
            if lq is None:
                return out
            cq = self._mgr.cluster_queues.get(lq.cluster_queue)
            if cq is None:
                return out
            infos = list(cq.workloads.values())
        for info in infos:
            wl = info.obj
            if wl.namespace == namespace and wl.queue_name == lq_name:
                for fr, v in info.usage().items():
                    out[fr] = out.get(fr, 0) + v
        return out

    def cohort_state(self, name: str) -> Optional[CohortState]:
        node = self._mgr.cohort(name)
        return node.payload if node else None

    # ------------------------------------------------------------------
    # Internal wiring
    # ------------------------------------------------------------------

    def deferred_rebuild(self):
        """Context manager batching topology mutations: ``_rebuild`` is
        suppressed inside the block and runs exactly once on exit (if
        any mutation asked for it).  Reads inside the block see stale
        quota trees / activeness — callers must not schedule against
        the cache until the block closes."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            with self._lock:
                already = self._rebuild_deferred
                self._rebuild_deferred = True
            try:
                yield self
            finally:
                with self._lock:
                    if not already:
                        self._rebuild_deferred = False
                        if self._rebuild_pending:
                            self._rebuild_pending = False
                            self._rebuild()
        return _ctx()

    def _rebuild(self) -> None:
        """Mirror hierarchy edges into the state payloads and recompute the
        subtree quotas from every root (reference resource_node.go:157)."""
        if self._rebuild_deferred:
            self._rebuild_pending = True
            return
        for node in self._mgr.cohorts.values():
            payload = node.payload
            payload.parent = node.parent.payload if node.parent else None
            payload.child_cohorts = [c.payload for c in node.child_cohorts.values()]
            payload.child_cqs = list(node.child_cqs.values())
        for name, cq in self._mgr.cluster_queues.items():
            parent_node = self._mgr.cq_parent(name)
            cq.parent = parent_node.payload if parent_node else None
        # Cohorts in a parent-edge cycle are unreachable from any root (a
        # cycle member is never parentless); break their mirrored parent
        # pointers so quota queries stay total, and deactivate their CQs.
        reachable: set[str] = set()
        roots = list(self._mgr.roots())
        for node in roots:
            for sub in node.walk_subtree():
                reachable.add(sub.name)
        # Per-root quota recomputation touches only that root's subtree
        # payloads — the cohort forest is the no-shared-state partition —
        # so the host pool can fan the roots out across workers; results
        # are order-free (disjoint writes), the serial loop is the
        # control arm.
        pool = self.host_pool
        if pool is not None and pool.active and len(roots) >= 2:
            pool.run([(lambda p=node.payload:
                       update_cohort_resource_node(p)) for node in roots])
        else:
            for node in roots:
                update_cohort_resource_node(node.payload)
        self._cyclic_cohorts = set(self._mgr.cohorts) - reachable
        for name in self._cyclic_cohorts:
            self._mgr.cohorts[name].payload.parent = None
        loose = [cq for name, cq in self._mgr.cluster_queues.items()
                 if self._mgr.cq_parent(name) is None]
        if pool is not None and pool.active and len(loose) >= 2:
            pool.run([(lambda c=cq:
                       update_cluster_queue_resource_node(c)) for cq in loose])
        else:
            for cq in loose:
                update_cluster_queue_resource_node(cq)
        self._update_all_statuses()

    def _update_all_statuses(self) -> None:
        self.structure_generation += 1
        for name, cq in self._mgr.cluster_queues.items():
            reasons = []
            for rg in cq.spec.resource_groups:
                for fq in rg.flavors:
                    if fq.name not in self.resource_flavors:
                        reasons.append(f"FlavorNotFound:{fq.name}")
            for ac in cq.spec.admission_checks:
                check = self.admission_checks.get(ac)
                if check is None or not check.active:
                    reasons.append(f"CheckNotFoundOrInactive:{ac}")
            if cq.spec.stop_policy != StopPolicy.NONE:
                reasons.append("Stopped")
            parent_node = self._mgr.cq_parent(name)
            if parent_node is not None and getattr(self, "_cyclic_cohorts", None):
                if parent_node.name in self._cyclic_cohorts:
                    reasons.append("CohortCycle")
            cq.active = not reasons
            cq.inactive_reasons = reasons
