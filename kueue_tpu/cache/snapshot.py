"""Per-cycle point-in-time snapshot of admitted state.

Capability parity with reference pkg/cache/snapshot.go: a deep copy of the
cohort forest (usage cloned, quotas shared) that the scheduler mutates
freely during nomination/preemption simulation, plus the packers' input.
The snapshot boundary is what makes the batched TPU solver legal: a cycle
is a pure function of (snapshot, heads).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..resources import FlavorResourceQuantities
from ..workload import Info
from .state import CohortState, CQState


class Snapshot:
    """reference pkg/cache/snapshot.go:104."""

    def __init__(self, cluster_queues: dict[str, CQState],
                 roots: list[CohortState],
                 inactive_cluster_queues: set[str],
                 resource_flavors: dict,
                 tas_flavors: dict | None = None,
                 fair_sharing_enabled: bool = False,
                 structure_generation: int = -1):
        self.cluster_queues = cluster_queues
        self.roots = roots
        self.inactive_cluster_queues = inactive_cluster_queues
        self.resource_flavors = resource_flavors
        self.tas_flavors = tas_flavors or {}
        self.fair_sharing_enabled = fair_sharing_enabled
        self.structure_generation = structure_generation

    def cq(self, name: str) -> Optional[CQState]:
        return self.cluster_queues.get(name)

    def add_workload(self, info: Info) -> None:
        """reference snapshot.go:44."""
        cq = self.cluster_queues.get(info.cluster_queue)
        if cq is not None:
            cq.add_workload(info)

    def remove_workload(self, info: Info) -> None:
        """reference snapshot.go:50."""
        cq = self.cluster_queues.get(info.cluster_queue)
        if cq is not None:
            cq.remove_workload(info)

    def simulate_workload_removal(self, infos: list[Info]) -> Callable[[], None]:
        """Remove a set of workloads, returning a revert closure
        (reference clusterqueue_snapshot.go:73)."""
        for info in infos:
            self.remove_workload(info)

        def revert() -> None:
            for info in infos:
                self.add_workload(info)
        return revert
