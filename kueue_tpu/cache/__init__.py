from .cache import Cache  # noqa: F401
from .snapshot import Snapshot  # noqa: F401
from .state import CohortState, CQState, dominant_resource_share  # noqa: F401
from .tas_cache import NodeInfo, TASCache  # noqa: F401
from .tas_snapshot import TASFlavorSnapshot  # noqa: F401
