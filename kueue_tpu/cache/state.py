"""ClusterQueue / Cohort in-memory state shared by the live cache and snapshots.

Capability parity with reference pkg/cache/clusterqueue.go + cohort.go +
fair_sharing.go.  A ``CQState``/``CohortState`` pair forms the hierarchical
resource tree; the same classes back per-cycle snapshots (cloned usage).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Optional

from ..api.types import (
    ClusterQueue,
    Cohort,
    FlavorFungibility,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceQuota,
    StopPolicy,
)
from ..resources import FlavorResource, FlavorResourceQuantities, Requests
from ..workload import Info
from . import resource_node as rn

MAX_DRS = sys.maxsize  # weight-zero sentinel (reference fair_sharing.go:52)


class SnapTag:
    """Per-root-tree mutation flag attached to *snapshot clones*.

    The incremental snapshot builder (cache.Cache.snapshot) hands out
    cached clone trees across cycles; a cached tree is only reusable if
    the scheduler didn't scribble on it (preemption simulation,
    in-cycle capacity reservation).  Every CQ clone in a cached tree
    shares one tag; the usage mutators flip it, and the builder
    re-clones flipped trees from the live cache.  Live CQStates carry
    ``_snap_tag = None`` so the hot-path cost on the live side is one
    attribute test."""

    __slots__ = ("mutated",)

    def __init__(self):
        self.mutated = False


def build_quotas(resource_groups) -> dict[FlavorResource, ResourceQuota]:
    """Flatten resource groups into the (flavor, resource) → quota map.

    lendingLimit is dropped at build when its gate is off — the
    reference does the same at cache build (scheduler_test.go:748
    disableLendingLimit), keeping the per-cycle hot paths gate-free."""
    import dataclasses
    from .. import features
    lending_on = features.enabled("LendingLimit")
    quotas: dict[FlavorResource, ResourceQuota] = {}
    for rg in resource_groups:
        for fq in rg.flavors:
            for rname, q in fq.resources.items():
                if q.lending_limit is not None and not lending_on:
                    q = dataclasses.replace(q, lending_limit=None)
                quotas[FlavorResource(fq.name, rname)] = q
    return quotas


class CohortState:
    """Cohort node payload (reference pkg/cache/cohort.go)."""

    def __init__(self, name: str):
        self.name = name
        self.spec: Optional[Cohort] = None
        self.resource_node = rn.ResourceNode()
        self.fair_weight_milli: int = 1000
        self.parent: Optional["CohortState"] = None
        self.child_cohorts: list["CohortState"] = []
        self.child_cqs: list["CQState"] = []

    def parent_node(self) -> Optional["CohortState"]:
        return self.parent

    def has_parent(self) -> bool:
        return self.parent is not None

    def root(self) -> "CohortState":
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def subtree_cqs(self) -> list["CQState"]:
        out = list(self.child_cqs)
        for c in self.child_cohorts:
            out.extend(c.subtree_cqs())
        return out

    def clone_subtree(self, parent: Optional["CohortState"],
                      cq_map: dict[str, "CQState"]) -> "CohortState":
        c = CohortState(self.name)
        c.spec = self.spec
        c.resource_node = self.resource_node.clone()
        c.fair_weight_milli = self.fair_weight_milli
        c.parent = parent
        c.child_cohorts = [ch.clone_subtree(c, cq_map) for ch in self.child_cohorts]
        for cq in self.child_cqs:
            cq_clone = cq.clone(parent=c)
            c.child_cqs.append(cq_clone)
            cq_map[cq_clone.name] = cq_clone
        return c


class CQState:
    """ClusterQueue cache entry (reference pkg/cache/clusterqueue.go)."""

    def __init__(self, spec: ClusterQueue):
        self.spec = spec
        self.resource_node = rn.ResourceNode()
        self.parent: Optional[CohortState] = None
        self.workloads: dict[str, Info] = {}
        self.allocatable_generation = 0
        self.active = True
        self.inactive_reasons: list[str] = []
        self.fair_weight_milli = int((spec.fair_sharing.weight if spec.fair_sharing else 1.0) * 1000)
        self.admitted_usage = FlavorResourceQuantities()  # Admitted (vs merely reserving)
        self._snap_tag: Optional[SnapTag] = None
        self.update_quotas(spec)

    # -- identity / config passthroughs --

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def preemption(self) -> PreemptionPolicy:
        return self.spec.preemption

    @property
    def flavor_fungibility(self) -> FlavorFungibility:
        return self.spec.flavor_fungibility

    @property
    def queueing_strategy(self) -> QueueingStrategy:
        return self.spec.queueing_strategy

    def update_quotas(self, spec: ClusterQueue) -> None:
        self.spec = spec
        self.resource_node.quotas = build_quotas(spec.resource_groups)
        self.fair_weight_milli = int((spec.fair_sharing.weight if spec.fair_sharing else 1.0) * 1000)

    # -- tree navigation --

    def parent_node(self) -> Optional[CohortState]:
        return self.parent

    def has_parent(self) -> bool:
        return self.parent is not None

    # -- usage --

    def add_workload(self, info: Info) -> bool:
        """Add and account a workload; refuses duplicates (reference
        clusterqueue.go addWorkload errors on an already-present key)."""
        if info.key in self.workloads:
            return False
        tag = self._snap_tag
        if tag is not None:
            tag.mutated = True
        self.workloads[info.key] = info
        rn.apply_usage(self, info.usage(), +1)
        if info.obj.is_admitted:
            self.admitted_usage.add(info.usage())
        return True

    def remove_workload(self, info: Info) -> None:
        if self.workloads.pop(info.key, None) is None:
            return
        tag = self._snap_tag
        if tag is not None:
            tag.mutated = True
        rn.apply_usage(self, info.usage(), -1)
        if info.obj.is_admitted:
            self.admitted_usage.sub(info.usage())

    def available(self, fr: FlavorResource) -> int:
        return rn.available(self, fr)

    def potential_available(self, fr: FlavorResource) -> int:
        return rn.potential_available(self, fr)

    def fits(self, usage: FlavorResourceQuantities) -> bool:
        """reference clusterqueue_snapshot.go:133 Fits."""
        return all(qty <= self.available(fr) for fr, qty in usage.items())

    def borrowing(self, fr: FlavorResource) -> bool:
        """Usage above this node's own subtree quota for fr."""
        return self.borrowing_with(fr, 0)

    def simulate_usage_addition(self, usage: FlavorResourceQuantities):
        """Apply usage, returning a revert closure (reference
        clusterqueue_snapshot.go SimulateUsageAddition)."""
        tag = self._snap_tag
        if tag is not None:
            tag.mutated = True
        rn.apply_usage(self, usage, +1)
        return lambda: rn.apply_usage(self, usage, -1)

    def simulate_usage_removal(self, usage: FlavorResourceQuantities):
        tag = self._snap_tag
        if tag is not None:
            tag.mutated = True
        rn.apply_usage(self, usage, -1)
        return lambda: rn.apply_usage(self, usage, +1)

    def borrowing_with(self, fr: FlavorResource, val: int) -> bool:
        """Would usage+val exceed this CQ's own subtree quota
        (reference clusterqueue_snapshot.go BorrowingWith)."""
        return self.resource_node.usage.get(fr, 0) + val > self.resource_node.subtree_quota.get(fr, 0)

    def is_borrowing(self) -> bool:
        return any(self.resource_node.usage.get(fr, 0) > self.resource_node.subtree_quota.get(fr, 0)
                   for fr in self.resource_node.usage)

    def clone(self, parent: Optional[CohortState]) -> "CQState":
        c = CQState.__new__(CQState)
        c.spec = self.spec
        c.resource_node = self.resource_node.clone()
        c.parent = parent
        c.workloads = dict(self.workloads)
        c.allocatable_generation = self.allocatable_generation
        c.active = self.active
        c.inactive_reasons = list(self.inactive_reasons)
        c.fair_weight_milli = self.fair_weight_milli
        c.admitted_usage = self.admitted_usage.clone()
        c._snap_tag = None
        return c

    # -- fair sharing (reference pkg/cache/fair_sharing.go:47) --

    def dominant_resource_share(self, wl_req: FlavorResourceQuantities | None = None
                                ) -> tuple[int, str]:
        return dominant_resource_share(self, wl_req)


def dominant_resource_share(node, wl_req: FlavorResourceQuantities | None = None
                            ) -> tuple[int, str]:
    """DRS in [0, 1e6]: max over resources of (usage above subtree quota)
    ·1000 / lendable-in-cohort, ÷ fair weight (reference fair_sharing.go:47)."""
    if not node.has_parent():
        return 0, ""
    if node.fair_weight_milli == 0:
        return MAX_DRS, ""
    r = node.resource_node
    borrowing: dict[str, int] = {}
    for fr in r.subtree_quota:
        borrowed = ((wl_req.get(fr, 0) if wl_req else 0)
                    + r.usage.get(fr, 0) - r.subtree_quota.get(fr, 0))
        if borrowed > 0:
            borrowing[fr.resource] = borrowing.get(fr.resource, 0) + borrowed
    if not borrowing:
        return 0, ""
    lendable = calculate_lendable(node.parent_node())
    drs, d_res = -1, ""
    for rname in borrowing:
        lr = lendable.get(rname, 0)
        if lr > 0:
            ratio = borrowing[rname] * 1000 // lr
            if ratio > drs or (ratio == drs and rname < d_res):
                drs, d_res = ratio, rname
    dws = drs * 1000 // node.fair_weight_milli
    return dws, d_res


def calculate_lendable(node) -> dict[str, int]:
    """Aggregate potential capacity per resource name at the root
    (reference fair_sharing.go:86)."""
    root = node
    while root.has_parent():
        root = root.parent_node()
    lendable: dict[str, int] = {}
    for fr in root.resource_node.subtree_quota:
        lendable[fr.resource] = lendable.get(fr.resource, 0) + rn.potential_available(node, fr)
    return lendable


def update_cluster_queue_resource_node(cq: CQState) -> None:
    """reference resource_node.go:146."""
    cq.allocatable_generation += 1
    sq = FlavorResourceQuantities()
    for fr, quota in cq.resource_node.quotas.items():
        sq[fr] = quota.nominal
    cq.resource_node.subtree_quota = sq


def update_cohort_resource_node(cohort: CohortState) -> None:
    """Accumulate subtree quota/usage root-down (reference resource_node.go:169)."""
    sq = FlavorResourceQuantities()
    usage = FlavorResourceQuantities()
    for fr, quota in cohort.resource_node.quotas.items():
        sq[fr] = quota.nominal
    cohort.resource_node.subtree_quota = sq
    cohort.resource_node.usage = usage
    for child in cohort.child_cohorts:
        update_cohort_resource_node(child)
        _accumulate_from_child(cohort, child.resource_node)
    for child in cohort.child_cqs:
        update_cluster_queue_resource_node(child)
        _accumulate_from_child(cohort, child.resource_node)


def _accumulate_from_child(parent: CohortState, child: rn.ResourceNode) -> None:
    """reference resource_node.go:186."""
    for fr, child_quota in child.subtree_quota.items():
        parent.resource_node.subtree_quota[fr] = (
            parent.resource_node.subtree_quota.get(fr, 0)
            + child_quota - child.guaranteed_quota(fr))
    for fr, child_usage in child.usage.items():
        parent.resource_node.usage[fr] = (
            parent.resource_node.usage.get(fr, 0)
            + max(0, child_usage - child.guaranteed_quota(fr)))
