"""Topology-aware-scheduling cache: flavor → topology tree state.

Capability parity with reference pkg/cache/tas_cache.go + tas_flavor.go.
The full assignment algorithm lives in kueue_tpu.cache.tas_snapshot
(reference tas_flavor_snapshot.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.types import ResourceFlavor, Topology


@dataclass
class NodeInfo:
    """A schedulable node feeding the topology tree."""
    name: str
    labels: dict[str, str] = field(default_factory=dict)
    capacity: dict[str, int] = field(default_factory=dict)  # canonical ints
    ready: bool = True


@dataclass
class FlavorTASInfo:
    flavor_name: str
    topology_name: str
    levels: list[str] = field(default_factory=list)
    node_labels: dict[str, str] = field(default_factory=dict)


class TASCache:
    """reference pkg/cache/tas_cache.go."""

    def __init__(self):
        self.topologies: dict[str, Topology] = {}
        self.flavors: dict[str, FlavorTASInfo] = {}
        self.nodes: dict[str, NodeInfo] = {}
        # usage per flavor per leaf-domain id, canonical ints
        self.usage: dict[str, dict[tuple, dict[str, int]]] = {}

    def add_topology(self, topology: Topology) -> None:
        self.topologies[topology.name] = topology
        for fi in self.flavors.values():
            if fi.topology_name == topology.name:
                fi.levels = list(topology.levels)

    def delete_topology(self, name: str) -> None:
        self.topologies.pop(name, None)
        for fi in self.flavors.values():
            if fi.topology_name == name:
                fi.levels = []

    def bind_flavor(self, flavor: ResourceFlavor) -> None:
        topo = self.topologies.get(flavor.topology_name or "")
        self.flavors[flavor.name] = FlavorTASInfo(
            flavor_name=flavor.name,
            topology_name=flavor.topology_name or "",
            levels=list(topo.levels) if topo else [],
            node_labels=dict(flavor.node_labels),
        )
        self.usage.setdefault(flavor.name, {})

    def unbind_flavor(self, name: str) -> None:
        self.flavors.pop(name, None)
        self.usage.pop(name, None)

    def add_or_update_node(self, node: NodeInfo) -> None:
        self.nodes[node.name] = node

    def delete_node(self, name: str) -> None:
        self.nodes.pop(name, None)

    def add_usage(self, flavor: str, domain: tuple, requests: dict[str, int],
                  sign: int = +1) -> None:
        per_flavor = self.usage.setdefault(flavor, {})
        dom = per_flavor.setdefault(domain, {})
        for rname, qty in requests.items():
            dom[rname] = dom.get(rname, 0) + sign * qty

    def snapshot(self) -> dict:
        """Build per-flavor topology snapshots for a scheduling cycle."""
        from .tas_snapshot import TASFlavorSnapshot
        out = {}
        for fname, info in self.flavors.items():
            if not info.levels:
                continue
            nodes = [n for n in self.nodes.values()
                     if n.ready and all(n.labels.get(k) == v
                                        for k, v in info.node_labels.items())]
            out[fname] = TASFlavorSnapshot.build(
                flavor=fname, levels=info.levels, nodes=nodes,
                usage=self.usage.get(fname, {}))
        return out
