"""PodSetInfo: the payload injected into job pod templates on admission.

Capability parity with reference pkg/podset/podset.go: on admission the
assigned flavors' node labels/taints become node selectors/tolerations on
the job's pod template (``from_assignment``, reference podset.go:56);
admission-check controllers contribute extra updates (``from_update``);
on suspension the original template is restored (``restore``, reference
podset.go:173).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .api.types import (
    PodSet,
    PodSetAssignment,
    ResourceFlavor,
    Toleration,
    TopologyAssignment,
)


class BadPodSetsUpdateError(Exception):
    """Merge conflict between admission-check updates (podset.go:152)."""


@dataclass
class PodSetInfo:
    """reference podset.go:44 PodSetInfo."""
    name: str
    count: int = 0
    node_selector: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    scheduling_gates: list[str] = field(default_factory=list)
    topology_assignment: Optional[TopologyAssignment] = None

    @staticmethod
    def from_assignment(psa: PodSetAssignment, count: int,
                        flavors: dict[str, ResourceFlavor]) -> "PodSetInfo":
        """reference podset.go:56 FromAssignment."""
        info = PodSetInfo(name=psa.name, count=count,
                          topology_assignment=psa.topology_assignment)
        for flavor_name in psa.flavors.values():
            flavor = flavors.get(flavor_name)
            if flavor is None:
                continue
            info.node_selector.update(flavor.node_labels)
            info.tolerations.extend(
                t for t in flavor.tolerations if t not in info.tolerations)
        return info

    @staticmethod
    def from_update(update: dict) -> "PodSetInfo":
        """An admission-check PodSetUpdate (reference podset.go:100)."""
        return PodSetInfo(
            name=update.get("name", ""),
            node_selector=dict(update.get("nodeSelector", {})),
            labels=dict(update.get("labels", {})),
            annotations=dict(update.get("annotations", {})),
            tolerations=list(update.get("tolerations", [])))

    def merge(self, other: "PodSetInfo") -> None:
        """reference podset.go:152 Merge — conflicting keys are an error."""
        for k, v in other.labels.items():
            if self.labels.get(k, v) != v:
                raise BadPodSetsUpdateError(f"conflicting label {k}")
            self.labels[k] = v
        for k, v in other.annotations.items():
            if self.annotations.get(k, v) != v:
                raise BadPodSetsUpdateError(f"conflicting annotation {k}")
            self.annotations[k] = v
        for k, v in other.node_selector.items():
            if self.node_selector.get(k, v) != v:
                raise BadPodSetsUpdateError(f"conflicting nodeSelector {k}")
            self.node_selector[k] = v
        self.tolerations.extend(
            t for t in other.tolerations if t not in self.tolerations)


def merge_podset_infos(base: list[PodSetInfo],
                       updates: list[PodSetInfo]) -> list[PodSetInfo]:
    """Merge admission-check updates into assignment infos by name."""
    by_name = {u.name: u for u in updates}
    for info in base:
        u = by_name.get(info.name)
        if u is not None:
            info.merge(u)
    return base


def podset_infos_from_admission(
        pod_sets: list[PodSet], assignments: list[PodSetAssignment],
        flavors: dict[str, ResourceFlavor]) -> list[PodSetInfo]:
    counts = {ps.name: ps.count for ps in pod_sets}
    return [PodSetInfo.from_assignment(
                psa, psa.count or counts.get(psa.name, 0), flavors)
            for psa in assignments]
