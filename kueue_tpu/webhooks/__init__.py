"""Webhook-equivalent defaulting and validation (reference pkg/webhooks).

Every write into the driver passes through these validators, mirroring
the reference's admission webhooks: workload_webhook.go,
clusterqueue_webhook.go, cohort_webhook.go, resourceflavor_webhook.go.
"""

from .validation import (
    ValidationError,
    default_workload,
    validate_cluster_queue,
    validate_cohort,
    validate_local_queue,
    validate_resource_flavor,
    validate_workload,
    validate_workload_update,
)

__all__ = [
    "ValidationError", "default_workload", "validate_cluster_queue",
    "validate_cohort", "validate_local_queue", "validate_resource_flavor",
    "validate_workload", "validate_workload_update",
]
