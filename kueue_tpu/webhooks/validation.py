"""Validators mirroring reference pkg/webhooks rules."""

from __future__ import annotations

import re
from typing import Optional

from ..api.types import (
    BorrowWithinCohortPolicy,
    ClusterQueue,
    Cohort,
    LocalQueue,
    ReclaimWithinCohort,
    ResourceFlavor,
    Workload,
)

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
MAX_PODSETS = 8


class ValidationError(ValueError):
    def __init__(self, errors: list[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


def _check(errors: list[str]) -> None:
    if errors:
        raise ValidationError(errors)


def _valid_name(name: str) -> bool:
    return bool(name) and len(name) <= 253 and bool(_DNS1123.match(name))


def valid_dns1123_label(name: str) -> bool:
    """validation.IsDNS1123Label: <=63 chars, lowercase alphanumerics
    and dashes, no dots."""
    return bool(name) and len(name) <= 63 and bool(_DNS1123.match(name))


def valid_dns1123_subdomain(name: str) -> bool:
    """validation.IsDNS1123Subdomain: <=253 chars, dot-separated
    DNS-1123 labels (each part capped at 63)."""
    return bool(name) and len(name) <= 253 and all(
        valid_dns1123_label(part) for part in name.split("."))


# ---------------------------------------------------------------------------
# Workload (workload_webhook.go)
# ---------------------------------------------------------------------------

def default_workload(wl: Workload) -> None:
    """Defaulting (workload_webhook.go Default): single unnamed pod set
    becomes "main"; minCounts are dropped while the PartialAdmission
    gate is off (workload_webhook.go:61-64)."""
    if len(wl.pod_sets) == 1 and not wl.pod_sets[0].name:
        wl.pod_sets[0].name = "main"
    from .. import features
    if not features.enabled("PartialAdmission"):
        for ps in wl.pod_sets:
            ps.min_count = None


def validate_workload(wl: Workload) -> None:
    errors: list[str] = []
    if not _valid_name(wl.name):
        errors.append(f"metadata.name: invalid name {wl.name!r}")
    if not wl.pod_sets:
        errors.append("spec.podSets: at least one pod set is required")
    if len(wl.pod_sets) > MAX_PODSETS:
        errors.append(f"spec.podSets: at most {MAX_PODSETS} pod sets")
    seen = set()
    variable_count = 0
    for i, ps in enumerate(wl.pod_sets):
        path = f"spec.podSets[{i}]"
        if not _valid_name(ps.name):
            errors.append(f"{path}.name: invalid name {ps.name!r}")
        if ps.name in seen:
            errors.append(f"{path}.name: duplicate pod set name {ps.name!r}")
        seen.add(ps.name)
        if ps.count < 0:
            errors.append(f"{path}.count: must be >= 0")
        if ps.min_count is not None:
            variable_count += 1
            if not 0 < ps.min_count <= ps.count:
                errors.append(f"{path}.minCount: must be in (0, count]")
        for res, v in ps.requests.items():
            if v < 0:
                errors.append(f"{path}.requests[{res}]: must be >= 0")
    if variable_count > 1:
        # workload_webhook.go:110
        errors.append("spec.podSets: at most one podSet can use minCount")

    if wl.admission is not None:
        ps_names = {ps.name for ps in wl.pod_sets}
        asg_names = {a.name for a in wl.admission.pod_set_assignments}
        if asg_names != ps_names:
            errors.append(
                "status.admission: podSetAssignments must match spec.podSets")
    for rp in wl.reclaimable_pods:
        counts = {ps.name: ps.count for ps in wl.pod_sets}
        if rp.name not in counts:
            errors.append(
                f"status.reclaimablePods[{rp.name}]: unknown pod set")
        elif rp.count > counts[rp.name]:
            errors.append(
                f"status.reclaimablePods[{rp.name}]: count exceeds pod set")
    _check(errors)


def validate_workload_update(new: Workload, old: Workload) -> None:
    """workload_webhook.go:268 ValidateWorkloadUpdate."""
    validate_workload(new)
    errors: list[str] = []
    if old.has_quota_reservation:
        old_ps = [(p.name, p.count, dict(p.requests)) for p in old.pod_sets]
        new_ps = [(p.name, p.count, dict(p.requests)) for p in new.pod_sets]
        if old_ps != new_ps:
            errors.append("spec.podSets: immutable while quota is reserved")
    if old.has_quota_reservation and new.has_quota_reservation:
        old_counts = {rp.name: rp.count for rp in old.reclaimable_pods}
        for rp in new.reclaimable_pods:
            if rp.count < old_counts.get(rp.name, 0):
                errors.append(
                    f"status.reclaimablePods[{rp.name}]: cannot decrease "
                    "while admitted")
    if (new.admission is not None and old.admission is not None
            and new.admission != old.admission):
        errors.append("status.admission: immutable once set (unset first)")
    _check(errors)


# ---------------------------------------------------------------------------
# ClusterQueue (clusterqueue_webhook.go)
# ---------------------------------------------------------------------------

def validate_cluster_queue(cq: ClusterQueue,
                           lending_limit_enabled: bool = True) -> None:
    errors: list[str] = []
    if not _valid_name(cq.name):
        errors.append(f"metadata.name: invalid name {cq.name!r}")
    if cq.cohort and not _valid_name(cq.cohort):
        errors.append(f"spec.cohort: invalid name {cq.cohort!r}")
    if cq.admission_checks and cq.admission_checks_strategy:
        # clusterqueue_webhook.go:132
        errors.append("spec: either admissionChecks or "
                      "admissionChecksStrategy can be set, but not both")
    p = cq.preemption
    if (p is not None
            and p.reclaim_within_cohort == ReclaimWithinCohort.NEVER
            and p.borrow_within_cohort is not None
            and p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER):
        # clusterqueue_webhook.go:124
        errors.append("spec.preemption: reclaimWithinCohort=Never and "
                      "borrowWithinCohort.Policy!=Never")
    seen_flavors: set[str] = set()
    for gi, rg in enumerate(cq.resource_groups):
        path = f"spec.resourceGroups[{gi}]"
        if not rg.covered_resources:
            errors.append(f"{path}.coveredResources: required")
        if not rg.flavors:
            errors.append(f"{path}.flavors: required")
        for fi, fq in enumerate(rg.flavors):
            fpath = f"{path}.flavors[{fi}]"
            if fq.name in seen_flavors:
                errors.append(f"{fpath}.name: duplicate flavor {fq.name!r}")
            seen_flavors.add(fq.name)
            if set(fq.resources) != set(rg.covered_resources):
                # clusterqueue_webhook.go:176
                errors.append(f"{fpath}.resources: must match the names in "
                              "coveredResources")
            for res, q in fq.resources.items():
                rpath = f"{fpath}.resources[{res}]"
                if q.nominal < 0:
                    errors.append(f"{rpath}.nominalQuota: must be >= 0")
                for limit_name, limit in (
                        ("borrowingLimit", q.borrowing_limit),
                        ("lendingLimit", q.lending_limit)):
                    if limit is None:
                        continue
                    if limit < 0:
                        errors.append(f"{rpath}.{limit_name}: must be >= 0")
                    if not cq.cohort:
                        # clusterqueue_webhook.go:204 validateLimit
                        errors.append(f"{rpath}.{limit_name}: must be nil "
                                      "when cohort is empty")
                if (q.lending_limit is not None and lending_limit_enabled
                        and q.lending_limit > q.nominal):
                    # clusterqueue_webhook.go:213
                    errors.append(f"{rpath}.lendingLimit: must be less than "
                                  "or equal to the nominalQuota")
    _check(errors)


# ---------------------------------------------------------------------------
# Cohort / ResourceFlavor / LocalQueue
# ---------------------------------------------------------------------------

def validate_cohort(cohort: Cohort) -> None:
    errors: list[str] = []
    if not _valid_name(cohort.name):
        errors.append(f"metadata.name: invalid name {cohort.name!r}")
    if cohort.parent_name and not _valid_name(cohort.parent_name):
        errors.append(f"spec.parentName: invalid name {cohort.parent_name!r}")
    if cohort.parent_name == cohort.name:
        errors.append("spec.parentName: cohort cannot be its own parent")
    _check(errors)


def validate_resource_flavor(flavor: ResourceFlavor) -> None:
    errors: list[str] = []
    if not _valid_name(flavor.name):
        errors.append(f"metadata.name: invalid name {flavor.name!r}")
    for k in flavor.node_labels:
        if not k or len(k) > 317:
            errors.append(f"spec.nodeLabels: invalid key {k!r}")
    _check(errors)


def validate_local_queue(lq: LocalQueue) -> None:
    errors: list[str] = []
    if not _valid_name(lq.name):
        errors.append(f"metadata.name: invalid name {lq.name!r}")
    if not _valid_name(lq.cluster_queue):
        errors.append(f"spec.clusterQueue: invalid name {lq.cluster_queue!r}")
    _check(errors)
