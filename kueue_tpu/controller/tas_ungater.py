"""Topology ungater (reference pkg/controller/tas/topology_ungater.go:152).

Admitted TAS workloads carry a TopologyAssignment (domains + counts).
Pods of the workload hold a TAS scheduling gate; the ungater assigns pods
to domains **rank-ordered** (completion-index style: pod rank i goes to
the first domain whose cumulative count exceeds i), injects the domain's
node-selector labels, and removes the gate.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from ..api.types import TopologyAssignment, Workload

TAS_SCHEDULING_GATE = "kueue.x-k8s.io/topology"
POD_RANK_ANNOTATION = "kueue.x-k8s.io/pod-rank"

_trailing_index = re.compile(r"(\d+)$")


def pod_rank(pod) -> int:
    """Rank from annotation (batch completion index equivalent) or the
    trailing integer of the pod name (topology_ungater.go rank logic)."""
    rank = getattr(pod, "annotations", {}).get(POD_RANK_ANNOTATION)
    if rank is not None:
        return int(rank)
    match = _trailing_index.search(pod.name)
    return int(match.group(1)) if match else 0


@dataclass
class UngateDecision:
    pod_name: str
    rank: int
    domain_values: list[str]
    node_selector: dict[str, str]


def assign_pods_to_domains(assignment: TopologyAssignment,
                           pods: list) -> list[UngateDecision]:
    """Rank-ordered pod→domain mapping (topology_ungater.go:152)."""
    ordered = sorted(pods, key=pod_rank)
    decisions = []
    di = 0
    used_in_domain = 0
    for pod in ordered:
        while (di < len(assignment.domains)
               and used_in_domain >= assignment.domains[di].count):
            di += 1
            used_in_domain = 0
        if di >= len(assignment.domains):
            break  # more pods than assigned capacity — leave gated
        dom = assignment.domains[di]
        selector = {level: value
                    for level, value in zip(assignment.levels, dom.values)}
        decisions.append(UngateDecision(
            pod_name=pod.name, rank=pod_rank(pod),
            domain_values=list(dom.values), node_selector=selector))
        used_in_domain += 1
    return decisions


class TopologyUngater:
    """Watches admitted TAS workloads and ungates their pods."""

    def __init__(self, driver):
        self.driver = driver
        # workload key → list of gated pod objects (registered by the
        # job integration, e.g. the pod group controller)
        self.gated_pods: dict[str, list] = {}

    def register_pods(self, workload_key: str, pods: list) -> None:
        self.gated_pods.setdefault(workload_key, []).extend(pods)

    def reconcile(self) -> list[UngateDecision]:
        """Ungate pods of every admitted workload with a topology
        assignment.  Returns the decisions applied this pass."""
        applied: list[UngateDecision] = []
        for key, pods in list(self.gated_pods.items()):
            wl = self.driver.workloads.get(key)
            if wl is None or not wl.is_admitted or wl.admission is None:
                continue
            for psa in wl.admission.pod_set_assignments:
                ta = psa.topology_assignment
                if ta is None:
                    continue
                ps_pods = [p for p in pods
                           if getattr(p, "pod_set", "main") == psa.name
                           and TAS_SCHEDULING_GATE in
                           getattr(p, "scheduling_gates", [])]
                for decision in assign_pods_to_domains(ta, ps_pods):
                    for p in ps_pods:
                        if p.name == decision.pod_name:
                            p.node_selector.update(decision.node_selector)
                            p.scheduling_gates.remove(TAS_SCHEDULING_GATE)
                            if getattr(p, "phase", None) == "Pending":
                                p.phase = "Running"
                            break
                    applied.append(decision)
            if all(TAS_SCHEDULING_GATE not in
                   getattr(p, "scheduling_gates", []) for p in pods):
                del self.gated_pods[key]
        return applied
