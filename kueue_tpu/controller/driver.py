"""The control-plane driver: event flow between store, cache, queues, scheduler.

Capability parity with reference cmd/kueue/main.go wiring plus
pkg/controller/core: a durable workload store (the CRD-status equivalent,
§5.4 — restart replays the store), reconciler-equivalent event handlers
keeping cache and queues in sync, admission application, eviction/requeue
handling with backoff, stop policies, and workload finish.

This is the single-process composition root.  The scheduler itself stays a
pure function of (snapshot, heads); everything durable lives here.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import (
    AdmissionCheck,
    AdmissionCheckState,
    ClusterQueue,
    Cohort,
    ConditionStatus,
    LocalQueue,
    ResourceFlavor,
    StopPolicy,
    Topology,
    Workload,
    EVICTED_BY_DEACTIVATION,
    EVICTED_BY_PREEMPTION,
    WL_ADMITTED,
    WL_EVICTED,
    WL_FINISHED,
    WL_QUOTA_RESERVED,
)
from ..cache.cache import Cache
from ..chaos import injector as _chaos
from ..obs import ObsPlane
from ..obs.trace import span as _span
from ..queue.manager import Manager as QueueManager
from ..utils import journal as _journal
from ..queue.cluster_queue import RequeueReason
from ..scheduler.scheduler import Scheduler
from .. import webhooks
from ..workload import (
    Info,
    InfoOptions,
    Ordering,
    next_requeue_state,
    set_finished_condition,
    set_requeued_condition,
    sync_admitted_condition,
    unset_quota_reservation,
    update_requeue_state,
)
from .. import metrics


def _unpack_target_rows(words, cand_rows_g):
    """Bit-packed candidate-slot words -> flattened row ids."""
    import numpy as np
    w = np.asarray(words, dtype=np.uint32)
    set_bits = ((w[:, None] >> np.arange(32, dtype=np.uint32)) & 1) > 0
    wi, bi = np.nonzero(set_bits)
    return cand_rows_g[wi * 32 + bi]


@dataclass
class WaitForPodsReadyConfig:
    """reference apis/config/v1beta1 WaitForPodsReady (:216)."""
    enable: bool = False
    timeout_seconds: float = 300.0
    block_admission: bool = False
    requeuing_backoff_base_seconds: int = 60
    requeuing_backoff_max_seconds: int = 3600
    requeuing_backoff_limit_count: Optional[int] = None
    requeuing_timestamp: str = "Eviction"


class Driver:
    """Single-process manager wiring (reference cmd/kueue/main.go:106)."""

    def __init__(self, clock: Callable[[], float] = time.time,
                 fair_sharing: bool = False,
                 fs_preemption_strategies: list[str] | None = None,
                 info_options: InfoOptions | None = None,
                 wait_for_pods_ready: WaitForPodsReadyConfig | None = None,
                 namespaces: Optional[dict[str, dict[str, str]]] = None,
                 use_device_solver: bool = False,
                 solver_backend: str = "auto",
                 validate: bool = True):
        self.clock = clock
        self.wait_for_pods_ready = wait_for_pods_ready or WaitForPodsReadyConfig()
        ordering = Ordering(
            pods_ready_requeuing_timestamp=self.wait_for_pods_ready.requeuing_timestamp)
        self.cache = Cache(info_options=info_options,
                           fair_sharing_enabled=fair_sharing)
        # parallel host apply/pack plane (utils/parallel_host.py):
        # KUEUE_TPU_HOST_WORKERS>=2 fans the post-cycle host work out by
        # cohort forest; the default (0) is the bit-identical serial arm
        from ..utils.parallel_host import host_pool_from_env
        self.host_pool = host_pool_from_env()
        self.cache.host_pool = self.host_pool
        self.queues = QueueManager(ordering=ordering, clock=clock,
                                   info_options=info_options)
        self.scheduler = Scheduler(
            self.queues, self.cache, fair_sharing=fair_sharing,
            fs_preemption_strategies=fs_preemption_strategies,
            ordering=ordering, clock=clock, namespaces=namespaces)
        if use_device_solver:
            from ..ops.solver import CycleSolver
            self.scheduler.solver = CycleSolver(ordering,
                                                backend=solver_backend)
            shards = self._env_shards()
            if shards > 1:
                try:
                    from ..parallel.sharded import make_mesh
                    mesh = make_mesh(shards)
                    if mesh is not None:
                        self.scheduler.solver.set_mesh(mesh)
                except Exception:
                    pass  # fewer devices than asked: stay serial
        self.scheduler.apply_admission = self._apply_admission
        self.scheduler.preemptor.apply_preemption = self._apply_preemption
        if self.wait_for_pods_ready.enable and self.wait_for_pods_ready.block_admission:
            self.scheduler.admission_blocked = self.admission_blocked
        # durable store: the CRD-status equivalent
        self.workloads: dict[str, Workload] = {}
        self.priority_classes: dict[str, object] = {}
        self.limit_ranges: dict[str, dict[str, object]] = {}
        self.validate = validate
        self.events: list[tuple[str, str, str]] = []  # (kind, key, note)
        self.metrics = metrics.Registry()
        self.scheduler.metrics = self.metrics
        self._burst_solver = None   # lazy BurstSolver (ops/burst.py)
        self._burst_m = 0           # sticky M bucket across burst packs
        self._burst_pack_state = None  # persistent delta-pack records
        self._wal = None            # write-ahead cycle journal (CycleWAL)
        self._bulk_applied_cqs = None  # non-None inside bulk_apply()
        self._cycle_touched = None  # non-None inside cycle_apply()
        # CQs whose interrupted-cycle decision was recovered from the
        # WAL tail: they sit out the first post-recovery cycle so the
        # completed cycle matches the uncrashed one decision-for-decision
        self._resume_mask: set[str] = set()
        # observability plane: event stream + flight recorder, always
        # attached; span tracing opt-in via KUEUE_TPU_OBS_TRACE (obs/)
        self.obs = ObsPlane.from_env(self)

    @staticmethod
    def _env_shards() -> int:
        """KUEUE_TPU_SHARDS=N activates sharded dispatch (0/1 = serial)."""
        from ..features import env_int
        return env_int("KUEUE_TPU_SHARDS")

    @classmethod
    def from_config(cls, cfg, clock: Callable[[], float] = time.time,
                    **kw) -> "Driver":
        """Build a driver from a Configuration (reference cmd/kueue/main.go
        :123-144 config→wiring + feature-gate application)."""
        from .. import features
        from ..workload import ResourceTransformation as _RT
        if cfg.feature_gates:
            features.set_feature_gates(cfg.feature_gates)
        w = cfg.wait_for_pods_ready
        wfpr = WaitForPodsReadyConfig(
            enable=w.enable,
            timeout_seconds=w.timeout_seconds,
            block_admission=w.block_admission,
            requeuing_backoff_base_seconds=(
                w.requeuing_strategy.backoff_base_seconds),
            requeuing_backoff_max_seconds=(
                w.requeuing_strategy.backoff_max_seconds),
            requeuing_backoff_limit_count=(
                w.requeuing_strategy.backoff_limit_count),
            requeuing_timestamp=w.requeuing_strategy.timestamp)
        info_options = InfoOptions(
            excluded_prefixes=list(cfg.resources.exclude_resource_prefixes),
            transformations={
                t.input: _RT(input=t.input, strategy=t.strategy,
                             outputs=dict(t.outputs))
                for t in cfg.resources.transformations})
        return cls(clock=clock,
                   fair_sharing=cfg.fair_sharing.enable,
                   fs_preemption_strategies=list(
                       cfg.fair_sharing.preemption_strategies),
                   info_options=info_options,
                   wait_for_pods_ready=wfpr, **kw)

    # ------------------------------------------------------------------
    # Resource plumbing (reconciler-equivalents)
    # ------------------------------------------------------------------

    def apply_resource_flavor(self, flavor: ResourceFlavor) -> None:
        if self.validate:
            webhooks.validate_resource_flavor(flavor)
        self.cache.add_or_update_resource_flavor(flavor)
        self._wake_all()

    def apply_topology(self, topology: Topology) -> None:
        self.cache.add_or_update_topology(topology)
        self._wake_all()

    def apply_limit_range(self, lr) -> None:
        """Namespace LimitRanges (reference pkg/util/limitrange): defaults
        applied at workload creation, bounds enforced at nomination."""
        from ..limitrange import summarize
        self.limit_ranges.setdefault(lr.namespace, {})[lr.name] = lr
        self.scheduler.limit_range_summaries[lr.namespace] = summarize(
            list(self.limit_ranges[lr.namespace].values()))
        # LimitRange summaries gate pack rows globally (no per-CQ map)
        self.queues.pack_journal.touch_all()
        # a relaxed range can unblock parked workloads
        self._wake_all()

    def apply_workload_priority_class(self, pc) -> None:
        """reference WorkloadPriorityClass (pkg/util/priority)."""
        self.priority_classes[pc.name] = pc

    def resolve_priority_class(self, name: str):
        return self.priority_classes.get(name)

    def apply_admission_check(self, check: AdmissionCheck) -> None:
        self.cache.add_or_update_admission_check(check)
        self._wake_all()

    def apply_cluster_queue(self, spec: ClusterQueue) -> None:
        if self.validate:
            webhooks.validate_cluster_queue(spec)
        self.cache.add_or_update_cluster_queue(spec)
        self.queues.add_cluster_queue(spec)
        if self._bulk_applied_cqs is not None:
            # inside bulk_apply(): activeness sync, inadmissible requeue
            # and status metrics run once over all applied CQs on exit
            self._bulk_applied_cqs.append(spec.name)
        else:
            self._sync_cq_activeness()
            self.queues.queue_inadmissible_workloads([spec.name])
            self.metrics.cluster_queue_status(
                spec.name, self.cache.cluster_queue(spec.name).active)
        if spec.stop_policy == StopPolicy.HOLD_AND_DRAIN:
            self._drain_cluster_queue(spec.name)

    def bulk_apply(self):
        """Context manager for large topology pushes (the CRD re-list on
        startup, scale tests): defers the cache's quota-tree rebuild and
        the per-apply activeness/metrics sync so N ``apply_*`` calls
        cost one O(N) settle on exit instead of N — without it, setup
        is O(N^2) and walls out near 100k CQs.  Scheduling inside the
        block sees stale quota trees; apply everything, then exit."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            outer = self._bulk_applied_cqs is not None
            if not outer:
                self._bulk_applied_cqs = []
            with self.cache.deferred_rebuild():
                yield self
            if not outer:
                names, self._bulk_applied_cqs = \
                    self._bulk_applied_cqs, None
                self._sync_cq_activeness()
                self.queues.queue_inadmissible_workloads(
                    names, pool=self.host_pool)
                for name in names:
                    cq = self.cache.cluster_queue(name)
                    if cq is not None:
                        self.metrics.cluster_queue_status(name, cq.active)
        return _ctx()

    def cycle_apply(self):
        """Context manager batching ONE burst cycle's decision patches:
        every evict/finish inside the block records its CQ instead of
        walking the cohort subtree for an inadmissible requeue, and the
        cache's quota-tree rebuild is deferred — so a cycle with D
        decisions costs one deduped ``queue_inadmissible_workloads``
        pass and one cache settle instead of D of each.  Safe on the
        burst apply path only: the cycle's heads and modeled decisions
        are fixed before the block, and the next cycle's heads are read
        after exit, so the deferred wakeups land at exactly the same
        observable point (the next heads read) as the eager ones.
        Opt-out: ``KUEUE_TPU_CYCLE_BULK_APPLY=0`` makes this a no-op
        passthrough to the classic per-decision path."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            from ..features import env_value
            if (env_value("KUEUE_TPU_CYCLE_BULK_APPLY") == "0"
                    or self._cycle_touched is not None):
                yield self
                return
            self._cycle_touched = []
            try:
                with self.cache.deferred_rebuild():
                    yield self
            finally:
                touched, self._cycle_touched = self._cycle_touched, None
            if touched:
                seen: set = set()
                names = [n for n in touched
                         if not (n in seen or seen.add(n))]
                self.queues.queue_inadmissible_workloads(
                    names, pool=self.host_pool)
        return _ctx()

    def _drain_cluster_queue(self, cq_name: str) -> None:
        """HoldAndDrain evicts admitted workloads (reference
        workload_controller.go:466 ClusterQueueStopped eviction)."""
        from ..api.types import EVICTED_BY_CQ_STOPPED
        for key, wl in list(self.workloads.items()):
            if (wl.admission is not None
                    and wl.admission.cluster_queue == cq_name
                    and wl.has_quota_reservation and not wl.is_finished):
                self._evict(wl, EVICTED_BY_CQ_STOPPED,
                            f"ClusterQueue {cq_name} is stopped")

    def delete_cluster_queue(self, name: str) -> None:
        self.cache.delete_cluster_queue(name)
        self.queues.delete_cluster_queue(name)

    def apply_cohort(self, spec: Cohort) -> None:
        if self.validate:
            webhooks.validate_cohort(spec)
        self.cache.add_or_update_cohort(spec)
        self.queues.update_cohort_edge(spec.name, spec.parent_name)
        self._wake_all()

    def apply_local_queue(self, lq: LocalQueue) -> None:
        if self.validate:
            webhooks.validate_local_queue(lq)
        self.cache.add_or_update_local_queue(lq)
        self.queues.add_local_queue(lq)
        if lq.stop_policy == StopPolicy.HOLD_AND_DRAIN:
            from ..api.types import EVICTED_BY_LQ_STOPPED
            for key, wl in list(self.workloads.items()):
                if (wl.namespace == lq.namespace
                        and wl.queue_name == lq.name
                        and wl.has_quota_reservation
                        and not wl.is_finished):
                    self._evict(wl, EVICTED_BY_LQ_STOPPED,
                                f"LocalQueue {lq.name} is stopped")

    def _sync_cq_activeness(self) -> None:
        for name in self.cache.cluster_queue_names():
            cq = self.cache.cluster_queue(name)
            if cq is not None:
                self.queues.set_cluster_queue_active(name, cq.active)

    def _wake_all(self) -> None:
        self._sync_cq_activeness()
        self.queues.queue_inadmissible_workloads(self.cache.cluster_queue_names())

    # ------------------------------------------------------------------
    # Workload lifecycle (reference core/workload_controller.go)
    # ------------------------------------------------------------------

    def _prepare_workload(self, wl: Workload) -> None:
        """Defaulting + validation + store write — everything
        ``create_workload`` does short of queueing."""
        webhooks.default_workload(wl)
        summary = self.scheduler.limit_range_summaries.get(wl.namespace)
        if summary is not None:
            from ..limitrange import apply_defaults
            for ps in wl.pod_sets:
                ps.requests = apply_defaults(ps.requests, summary)
        if self.validate:
            webhooks.validate_workload(wl)
        if wl.creation_time == 0.0:
            wl.creation_time = self.clock()
        self.workloads[wl.key] = wl

    def create_workload(self, wl: Workload) -> None:
        self._prepare_workload(wl)
        self.queues.add_or_update_workload(wl)
        self.metrics.pending_inc(wl)

    def ingest_workloads(self, wls) -> int:
        """Bulk create for the serving ingest drain: prepare every
        workload, then queue the whole batch under one manager lock
        acquisition (queue.Manager.add_workloads) instead of one per
        workload.  Same per-workload semantics as ``create_workload``;
        returns the batch size."""
        batch = list(wls)
        for wl in batch:
            self._prepare_workload(wl)
        self.queues.add_workloads(batch)
        for wl in batch:
            self.metrics.pending_inc(wl)
        return len(batch)

    def restore_workload(self, wl: Workload) -> None:
        """Crash-recovery replay (SURVEY §5.4): rebuild in-memory state
        from a stored workload — admitted usage goes back into the cache,
        pending workloads back into the queues, like the CRD watch replay
        on reference manager restart."""
        self.workloads[wl.key] = wl
        if wl.is_finished or not wl.is_active:
            return
        if wl.admission is not None and wl.has_quota_reservation:
            info = Info(wl, self.cache.info_options)
            self.cache.add_or_update_workload(info)
        else:
            self.queues.add_or_update_workload(wl)

    def attach_wal(self, wal) -> None:
        """Attach a write-ahead cycle journal (utils.journal.CycleWAL):
        every admit/evict/requeue/finish decision is journaled before
        the store mutation it describes, and each cycle's batch is
        committed at the cycle boundary.  The host pool announces its
        workers to a sharded WAL so segment striping engages (and the
        per-segment commit flushes fan out); with the pool inactive the
        sharded WAL collapses to one hot segment."""
        if self._wal is not None:
            self.host_pool.detach_wal(self._wal)
        self._wal = wal
        if wal is not None:
            self.host_pool.attach_wal(wal)

    def recover_from(self, stored, wal=None) -> int:
        """Crash recovery (SURVEY §5.4 + the WAL): roll the journal's
        uncommitted tail forward over the surviving store — using the
        journaled timestamps, so the replayed status is bit-identical
        to the uncrashed apply — then rebuild cache and queues from the
        rolled-forward store via ``restore_workload``.  ``stored`` is
        the durable workload store of the crashed driver (any iterable
        of Workload); returns the number of tail ops replayed.  The WAL
        stays attached, with its recovered tail committed."""
        store = {wl.key: wl for wl in stored}
        n = 0
        mask: set[str] = set()
        if wal is not None:
            # an admit in the tail means its CQ's head slot for the
            # interrupted cycle was consumed before the crash — that CQ
            # must sit out the cycle's re-run or it would admit its next
            # head a cycle earlier than the uncrashed driver did
            for op in wal.tail:
                if op.get("op") == "admit":
                    mask.add(op["admission"]["cluster_queue"])
            n = wal.replay_tail(store)
            wal.commit()   # the tail is now fully reflected in state
        for wl in store.values():
            self.restore_workload(wl)
        self._wal = wal
        self._resume_mask = mask
        return n

    def delete_workload(self, key: str) -> None:
        wl = self.workloads.pop(key, None)
        if wl is None:
            return
        self.queues.delete_workload(wl)
        if wl.admission is not None:
            self.cache.delete_workload(Info(wl))
            self.queues.queue_inadmissible_workloads([wl.admission.cluster_queue])
        self.events.append(("Deleted", key, ""))
        self.wake_gate_blocked()   # deleting a not-ready blocker opens the gate

    def finish_workload(self, key: str, message: str = "Job finished") -> None:
        """Quota release on completion (reference jobframework finished path)."""
        self.finish_workloads([key], message=message)

    def finish_workloads(self, keys, message: str = "Job finished") -> None:
        """Batched finish: quota released per workload, with ONE
        cohort-wide inadmissible wakeup per touched CQ set instead of a
        subtree walk per workload (manager.go:490 semantics are
        idempotent within a batch — the wakeup sees the post-release
        state either way)."""
        touched: list[str] = []
        seen: set[str] = set()
        any_done = False
        now = self.clock()
        if self._wal is not None:
            live = [k for k in keys
                    if (w := self.workloads.get(k)) is not None
                    and not w.is_finished]
            if live:
                self._wal.log(_journal.finish_op(live, message, now))
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("wal.finish")
        for key in keys:
            wl = self.workloads.get(key)
            if wl is None or wl.is_finished:
                continue
            set_finished_condition(wl, "JobFinished", message, now)
            if wl.admission is not None:
                cq_name = wl.admission.cluster_queue
                was_admitted = wl.is_admitted
                self.cache.delete_workload(Info(wl))
                self.metrics.release_reservation(cq_name)
                if was_admitted:
                    self.metrics.release_admitted(cq_name)
                if cq_name not in seen:
                    seen.add(cq_name)
                    touched.append(cq_name)
            self.queues.delete_workload(wl)
            self.events.append(("Finished", key, message))
            any_done = True
        if touched:
            if self._cycle_touched is not None:
                self._cycle_touched.extend(touched)
            else:
                self.queues.queue_inadmissible_workloads(touched)
        if any_done:
            self.wake_gate_blocked()
        if self._wal is not None:
            self.host_pool.commit_wal(self._wal)

    def update_reclaimable_pods(self, key: str, counts: dict[str, int]) -> None:
        """reference workload.UpdateReclaimablePods (KEP 78): shrink the
        quota charged for pods that finished early."""
        from ..api.types import ReclaimablePod
        wl = self.workloads.get(key)
        if wl is None or wl.is_finished:
            return
        existing = {rp.name: rp.count for rp in wl.reclaimable_pods}
        changed = False
        for name, count in counts.items():
            # reclaim counts only grow (reference validation)
            if count > existing.get(name, 0):
                existing[name] = count
                changed = True
        if not changed:
            return
        # the admitted usage shrinks; the fresh Info below replaces the
        # cached one in the cache CQ, so per-Info burst usage vectors
        # (ops/burst.py admitted_usage_vec) can never go stale
        wl.reclaimable_pods = [ReclaimablePod(name=n, count=c)
                               for n, c in sorted(existing.items())]
        if wl.admission is not None:
            # re-charge the cache with the shrunk usage
            self.cache.add_or_update_workload(Info(wl))
            if wl.admission.cluster_queue:
                self.queues.queue_inadmissible_workloads(
                    [wl.admission.cluster_queue])
        else:
            self.queues.add_or_update_workload(wl)

    def deactivate_workload(self, key: str) -> None:
        wl = self.workloads.get(key)
        if wl is None:
            return
        if self._wal is not None:
            self._wal.log(_journal.deactivate_op(key))
        wl.active = False
        now = self.clock()
        if wl.admission is not None:
            self._evict(wl, EVICTED_BY_DEACTIVATION, "The workload is deactivated")
        self.queues.delete_workload(wl)

    def set_admission_check_state(self, key: str, check: str,
                                  state: AdmissionCheckState,
                                  message: str = "") -> None:
        """Two-phase admission: external controllers flip check states
        (reference workload_controller.go:409)."""
        wl = self.workloads.get(key)
        if wl is None or check not in wl.admission_check_states:
            return
        now = self.clock()
        st = wl.admission_check_states[check]
        st.state = state
        st.message = message
        st.last_transition_time = now
        # check states gate pack rows but mutate in place (no queue or
        # cache write on the pending path) — row-grade dirt: exactly
        # this workload's ok bit can move, the CQ's membership and
        # aggregates cannot.  Structural follow-ons below (admitted
        # sync, eviction) journal their own hard touches, which
        # supersede the row entry at drain time.
        lq = self.queues.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        if lq is not None:
            self.queues.pack_journal.touch_row(lq.cluster_queue, key)
        elif wl.admission is not None:
            self.queues.pack_journal.touch_row(
                wl.admission.cluster_queue, key)
        else:
            self.queues.pack_journal.touch_all()
        if state == AdmissionCheckState.READY:
            if sync_admitted_condition(wl, now):
                cq_name = wl.admission.cluster_queue if wl.admission else ""
                self.metrics.admitted_workload(cq_name,
                                               now - wl.creation_time)
                reserved = wl.conditions.get(WL_QUOTA_RESERVED)
                if reserved is not None:
                    self.metrics.admission_checks_wait(
                        cq_name, now - reserved.last_transition_time)
                if wl.admission is not None:
                    info = Info(wl, self.cache.info_options)
                    self.cache.add_or_update_workload(info)
        elif state in (AdmissionCheckState.RETRY, AdmissionCheckState.REJECTED):
            self._evict(wl, "AdmissionCheck", f"Admission check {check}: {state.value}")
            if state == AdmissionCheckState.REJECTED:
                self.deactivate_workload(key)

    # ------------------------------------------------------------------
    # Scheduler side-effects
    # ------------------------------------------------------------------

    def _apply_admission(self, new_wl: Workload) -> bool:
        """The SSA apply-equivalent: land admission in the store
        (reference scheduler.go applyAdmissionWithSSA)."""
        cur = self.workloads.get(new_wl.key)
        if cur is None or cur.is_finished or not cur.is_active:
            return False
        if self._wal is not None:
            self._wal.log(_journal.admit_op(new_wl))
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("wal.admit")
        self.workloads[new_wl.key] = new_wl
        self.queues.delete_workload(new_wl)
        cq = new_wl.admission.cluster_queue
        now = self.clock()
        self.metrics.quota_reserved(cq, now - new_wl.creation_time)
        if new_wl.is_admitted:
            self.metrics.admitted_workload(cq, now - new_wl.creation_time)
        self.events.append(("QuotaReserved", new_wl.key, cq))
        self.obs.emit("admit", new_wl.key, cq, "QuotaReserved")
        return True

    def _apply_preemption(self, info: Info, reason: str, message: str) -> None:
        """Eviction by preemption: update store, release quota, requeue
        (reference WorkloadReconciler eviction path)."""
        wl = self.workloads.get(info.key)
        if wl is None:
            return
        self._evict(wl, EVICTED_BY_PREEMPTION, message, preempted_reason=reason)
        self.events.append(("Preempted", info.key, reason))
        self.obs.emit("preempt", info.key,
                      getattr(info, "cluster_queue", "") or "", reason,
                      note=message)

    def _evict(self, wl: Workload, reason: str, message: str,
               preempted_reason: str | None = None) -> None:
        from ..workload import (set_evicted_condition,
                                set_pods_ready_condition,
                                set_preempted_condition)
        now = self.clock()
        if self._wal is not None:
            self._wal.log(_journal.evict_op(wl.key, reason, message,
                                            preempted_reason, now))
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("wal.evict")
        cq_name = wl.admission.cluster_queue if wl.admission else ""
        set_evicted_condition(wl, reason, message, now)
        # eviction stops the pods: a stale PodsReady=True must not exempt
        # a future readmission from the timeout or open the gate
        from ..api.types import WL_PODS_READY
        if WL_PODS_READY in wl.conditions:
            set_pods_ready_condition(wl, False, now)
        if preempted_reason is not None:
            set_preempted_condition(wl, preempted_reason, message, now)
        # reset admission check states on eviction
        for st in wl.admission_check_states.values():
            st.state = AdmissionCheckState.PENDING
        if wl.admission is not None:
            was_admitted = wl.is_admitted
            self.cache.delete_workload(Info(wl))
            self.metrics.release_reservation(cq_name)
            if was_admitted:
                self.metrics.release_admitted(cq_name)
            unset_quota_reservation(wl, reason, message, now)
        self.metrics.evicted(cq_name, reason)
        self.obs.emit("evict", wl.key, cq_name, reason, note=message)
        # requeue: back into the pending queues
        set_requeued_condition(wl, reason, message, True, now)
        if wl.is_active:
            self.queues.add_or_update_workload(wl)
            self.obs.emit("requeue", wl.key, cq_name, reason)
        if cq_name:
            if self._cycle_touched is not None:
                self._cycle_touched.append(cq_name)
            else:
                self.queues.queue_inadmissible_workloads([cq_name])
        self.wake_gate_blocked()   # evicting a not-ready blocker opens the gate

    def refresh_resource_metrics(self) -> None:
        """Per-CQ resource gauges + LQ mirrors (reference
        ClusterQueueReconciler.recordResourceMetrics,
        clusterqueue_controller.go:382)."""
        from ..resources import FlavorResource
        for name in self.cache.cluster_queue_names():
            cq = self.cache.cluster_queue(name)
            if cq is None:
                continue
            usage = self.cache.usage(name)
            for rg in cq.spec.resource_groups:
                for fq in rg.flavors:
                    for rname, quota in fq.resources.items():
                        fr = FlavorResource(fq.name, rname)
                        used = usage.get(fr, 0)
                        self.metrics.report_resource_usage(
                            name, fq.name, rname, used, quota.nominal,
                            reservation=used,
                            borrowing_limit=quota.borrowing_limit,
                            lending_limit=quota.lending_limit)
        self.metrics.sample_pending(self.queues)
        self.metrics.obs_sample(self.obs.events.report(),
                                self.obs.flight.recorded_total)
        # LocalQueue mirrors (LocalQueueMetrics feature gate)
        from .. import features
        if features.enabled("LocalQueueMetrics"):
            per_lq: dict[str, list[int]] = {}
            for wl in self.workloads.values():
                key = f"{wl.namespace}/{wl.queue_name}"
                counts = per_lq.setdefault(key, [0, 0, 0])
                if wl.is_finished or not wl.is_active:
                    continue
                if wl.is_admitted:
                    counts[2] += 1
                    counts[1] += 1
                elif wl.has_quota_reservation:
                    counts[1] += 1
                else:
                    counts[0] += 1
            for key, (pending, reserving, admitted) in per_lq.items():
                ns, _, lq = key.partition("/")
                self.metrics.local_queue_counts(ns, lq, pending,
                                                reserving, admitted)

    def check_maximum_execution_times(self) -> list[str]:
        """Deactivate workloads admitted longer than their
        maximumExecutionTimeSeconds (reference workload_controller.go:354).
        Returns the deactivated keys."""
        now = self.clock()
        out = []
        for key, wl in list(self.workloads.items()):
            limit = wl.maximum_execution_time_seconds
            if limit is None or not wl.is_admitted or wl.is_finished:
                continue
            adm = wl.conditions.get(WL_ADMITTED)
            if adm is not None and now - adm.last_transition_time >= limit:
                self.deactivate_workload(key)
                self.events.append(("MaximumExecutionTimeExceeded", key,
                                    f"exceeded {limit}s"))
                out.append(key)
        return out

    def evict_for_pods_ready_timeout(self, key: str) -> None:
        """WaitForPodsReady timeout (reference workload_controller.go:546)."""
        wl = self.workloads.get(key)
        if wl is None or wl.admission is None:
            return
        cfg = self.wait_for_pods_ready
        now = self.clock()
        if self._wal is not None:
            count, requeue_at = next_requeue_state(
                wl, cfg.requeuing_backoff_base_seconds,
                cfg.requeuing_backoff_max_seconds, now)
            self._wal.log(_journal.requeue_op(key, count, requeue_at))
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("wal.requeue")
        update_requeue_state(wl, cfg.requeuing_backoff_base_seconds,
                             cfg.requeuing_backoff_max_seconds, now)
        limit = cfg.requeuing_backoff_limit_count
        if limit is not None and wl.requeue_state.count > limit:
            self.deactivate_workload(key)
            return
        self._evict(wl, "PodsReadyTimeout",
                    f"Exceeded the PodsReady timeout {cfg.timeout_seconds}s")

    # ------------------------------------------------------------------
    # WaitForPodsReady enforcement (reference workload_controller.go:546
    # timeout countdown; scheduler.go:268-279 blockAdmission)
    # ------------------------------------------------------------------

    def set_pods_ready(self, key: str, ready: bool) -> None:
        """Sync a workload's PodsReady condition (the jobframework
        reconciler calls this from the job's pods_ready()); a transition
        to ready wakes the scheduler (cache.podsReadyCond broadcast,
        reference cache.go:214)."""
        if not self.wait_for_pods_ready.enable:
            return  # the reference maintains PodsReady only when enabled
        wl = self.workloads.get(key)
        if wl is None or wl.is_finished:
            return
        from ..workload import set_pods_ready_condition
        if set_pods_ready_condition(wl, ready, self.clock()) and ready:
            self.wake_gate_blocked()

    def wake_gate_blocked(self) -> None:
        """Unpark gate-held entries when the blockAdmission gate opens.

        The gate opens whenever the last admitted-not-ready workload
        stops being one — pods ready, eviction (incl. the PodsReady
        timeout), finish, delete, deactivation — and held entries may be
        parked in ANY cohort, so every gate-opening event must wake all
        of them (the reference blocks in-cycle instead and has no parked
        entries to lose, scheduler.go:277)."""
        cfg = self.wait_for_pods_ready
        if not (cfg.enable and cfg.block_admission):
            return
        if not self.scheduler.gate_parked:
            return  # the gate never held anything: nothing to wake
        if self.pods_ready_for_all_admitted():
            self.scheduler.gate_parked = False
            self.queues.queue_inadmissible_workloads(
                list(self.queues.cluster_queue_names()))
            self.queues.broadcast()

    def pods_ready_for_all_admitted(self) -> bool:
        """reference cache.go:187 PodsReadyForAllAdmittedWorkloads."""
        from ..api.types import WL_PODS_READY
        for wl in list(self.workloads.values()):
            if (wl.is_admitted and wl.is_active and not wl.is_finished
                    and not wl.condition_true(WL_PODS_READY)):
                return False
        return True

    def admission_blocked(self) -> bool:
        """blockAdmission gate: with WaitForPodsReady blocking enabled,
        no new admission while any admitted workload lacks PodsReady
        (reference scheduler.go:268-279; held entries requeue and the
        PodsReady transition wakes them instead of blocking in-cycle)."""
        cfg = self.wait_for_pods_ready
        return (cfg.enable and cfg.block_admission
                and not self.pods_ready_for_all_admitted())

    def enforce_wait_for_pods_ready(self) -> list[str]:
        """Automatic PodsReady deadline tracking: evict every admitted
        workload that exceeded the timeout without reaching PodsReady
        (reference workload_controller.go:546-595 requeue-after timers).
        Runs each cycle and on daemon ticks; returns the evicted keys."""
        cfg = self.wait_for_pods_ready
        if not cfg.enable or not cfg.timeout_seconds:
            return []
        from ..api.types import WL_ADMITTED, WL_PODS_READY
        now = self.clock()
        out = []
        for key, wl in list(self.workloads.items()):
            if (not wl.is_admitted or wl.is_finished
                    or wl.condition_true(WL_PODS_READY)):
                continue
            adm = wl.conditions.get(WL_ADMITTED)
            if adm is None:
                continue
            if now - adm.last_transition_time >= cfg.timeout_seconds:
                self.evict_for_pods_ready_timeout(key)
                out.append(key)
        return out

    # ------------------------------------------------------------------
    # Run loop
    # ------------------------------------------------------------------

    def schedule_once(self):
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("cycle.start")
        if self.wait_for_pods_ready.enable:
            self.enforce_wait_for_pods_ready()
        self.queues.wake_expired_backoffs()
        if self._resume_mask:
            # complete the WAL-recovered interrupted cycle: CQs whose
            # decision already replayed are held back (their popped
            # heads go straight back into the queues), so this cycle's
            # decisions land exactly where the uncrashed run put them
            mask, self._resume_mask = self._resume_mask, set()
            kept = []
            for info in self.queues.heads_nonblocking():
                wl = info.obj
                lq = self.queues.local_queues.get(
                    f"{wl.namespace}/{wl.queue_name}")
                if lq is not None and lq.cluster_queue in mask:
                    self.queues.add_or_update_workload(wl)
                else:
                    kept.append(info)
            stats = self.scheduler.schedule(heads=kept)
        else:
            stats = self.scheduler.schedule()
        self.metrics.admission_attempt(bool(stats.admitted), stats.duration_s)
        if self._wal is not None:
            self.host_pool.commit_wal(self._wal)
        self.obs.record_cycle(stats)
        return stats

    def schedule_burst(self, max_cycles: int, runtime: int = 0,
                       external_finishes: Optional[dict] = None,
                       on_cycle: Optional[Callable] = None,
                       on_cycle_start: Optional[Callable] = None,
                       backend: str = "auto",
                       pipeline: Optional[bool] = None) -> list:
        """Run up to ``max_cycles`` cycles, fusing runs of clean cycles
        into single device dispatches (kueue_tpu.ops.burst) and falling
        back to the normal per-cycle path whenever a cycle needs host
        semantics (preemption, scalar heads) or the modeled heads diverge
        from the live queues.

        ``runtime`` > 0 models fake execution: a workload admitted at
        applied-cycle j is finished at cycle j+runtime (the perf
        harness's contract — reference runner/controller/controller.go
        :113).  ``external_finishes`` maps cycle offsets (relative to
        this call) to workload keys admitted BEFORE the call that finish
        at that offset; the driver performs both kinds of finishes
        itself.  ``on_cycle_start(k)`` / ``on_cycle(k, stats)`` bracket
        each applied cycle (clock advancement, bookkeeping).

        ``pipeline`` (default on; KUEUE_BURST_PIPELINE=0 disables)
        double-buffers the burst boundary: after a window with no
        modeled-dirty cycle is fetched, the NEXT window is dispatched
        speculatively off the kernel's final carry — device-resident,
        no host re-pack — before this window's apply loop starts, so
        pack+dispatch overlap apply instead of landing serially in one
        cycle.  A speculative window is only ever consumed when every
        cycle of the window it chained from applied exactly as modeled
        and the structure generation is unchanged; anything else
        (dirty truncation, heads divergence, clock-order violation,
        vanished preempt target, structure drift) discards it unused
        and the serial pack path decides — decisions are bit-identical
        to pipeline-off by construction.

        Returns the list of per-cycle CycleStats actually applied."""
        import os
        import numpy as np
        from ..ops.burst import (BurstSolver, pack_burst_cached,
                                 K_BURST_LADDER)

        ext = {int(k): list(v) for k, v in
               (external_finishes or {}).items()}
        out: list = []
        burst_ineligible = (
            self.scheduler.fair_sharing
            or (self.wait_for_pods_ready.enable
                and self.wait_for_pods_ready.block_admission))
        if self._burst_solver is None:
            self._burst_solver = BurstSolver(backend=backend)
            shards = self._env_shards()
            if shards > 1:
                self._burst_solver.set_shards(shards)
        self._burst_solver.backend = backend
        solver = self.scheduler.solver
        normal_streak = 0   # cycles to run normally before re-bursting

        from ..api.types import WL_QUOTA_RESERVED

        def _reservation_ts(key):
            wl = self.workloads.get(key)
            if wl is None or not wl.has_quota_reservation:
                return None
            c = wl.conditions.get(WL_QUOTA_RESERVED)
            return c.last_transition_time if c is not None else None

        # a finish obligation is bound to the ADMISSION that scheduled
        # it: a workload preempted and re-admitted in between must get a
        # full new run, not a truncated one (the host harness prunes
        # stale entries the moment the reservation drops)
        sched_ts: dict = {key: _reservation_ts(key)
                          for keys in ext.values() for key in keys}

        def finish_cycle(stats) -> None:
            """Record one applied cycle + its end-of-cycle finishes.

            Finish time is tracked separately on the stats
            (``finish_s``): it is workload-controller work, not
            scheduler-cycle latency — per-cycle benchmarks exclude it
            the same way the per-cycle harness loop does."""
            import time as _time
            k = len(out)
            out.append(stats)
            for key in stats.admitted:
                sched_ts[key] = _reservation_ts(key)
            due = list(ext.pop(k, []))
            if runtime > 0 and k - runtime >= 0:
                due.extend(out[k - runtime].admitted)
            t0 = _time.perf_counter()
            batch = [key for key in due
                     if (wl := self.workloads.get(key)) is not None
                     and wl.has_quota_reservation
                     and _reservation_ts(key) == sched_ts.get(key)]
            if batch:
                self.finish_workloads(batch)
            stats.finish_s = _time.perf_counter() - t0
            if self._wal is not None:
                self.host_pool.commit_wal(self._wal)
            self.obs.record_cycle(stats)
            if on_cycle is not None:
                on_cycle(k, stats)

        def quiescent() -> bool:
            """Nothing can make further cycles non-empty: no eligible
            heads now, no pending backoff timer, and no future finish
            (external or modeled-runtime) that could unpark work."""
            if any(off >= len(out) for off in ext):
                return False
            if runtime > 0 and any(
                    out[j].admitted for j in
                    range(max(0, len(out) - runtime), len(out))):
                return False
            for name in self.queues.cluster_queue_names():
                q = self.queues.queue_for(name)
                if q is None or not q.active:
                    continue
                if len(q.heap):
                    return False     # a head exists right now
                for info in q.inadmissible.values():
                    rs = info.obj.requeue_state
                    if rs is not None and rs.requeue_at is not None:
                        return False  # a backoff timer will fire
            return True

        def normal_cycle(heads=None, advance=True) -> bool:
            """One normal-path cycle; False when the queues were empty."""
            if advance and on_cycle_start is not None:
                on_cycle_start(len(out))
            if heads is None:
                stats = self.schedule_once()
            else:
                stats = self.scheduler.schedule(heads=heads)
                self.metrics.admission_attempt(bool(stats.admitted),
                                               stats.duration_s)
            finish_cycle(stats)
            return bool(stats.admitted or stats.skipped
                        or stats.inadmissible or stats.preempting)

        dirty_backoff = 0
        bstats = self._burst_solver.stats
        if pipeline is None:
            pipeline = os.environ.get("KUEUE_BURST_PIPELINE", "1") != "0"
        spec = None          # speculative BurstHandle for the next window
        last_adm_clock = None
        clock_monotone = True

        def cancel_spec(h, why=""):
            """Discard an in-flight speculative window unfetched — its
            assumptions were invalidated; it must never be applied."""
            if h is not None:
                bstats["burst_spec_cancelled"] += 1
                if os.environ.get("KUEUE_BURST_DEBUG"):
                    import sys as _sys
                    print(f"spec cancel: {why}", file=_sys.stderr)
            return None

        while len(out) < max_cycles:
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.crashpoint("burst.window_boundary")
                if (spec is not None and _chaos.ACTIVE.hit(
                        "burst.force_spec_divergence") is not None):
                    # chaos forces the pipeline cancel path: the
                    # speculative window is discarded unconsumed and the
                    # serial pack decides — bit-identical by the same
                    # argument as every organic cancel
                    bstats["burst_chaos_divergences"] = (
                        bstats.get("burst_chaos_divergences", 0) + 1)
                    spec = cancel_spec(spec, "chaos")
            if (burst_ineligible or solver is None or normal_streak > 0
                    or self._resume_mask):
                # a pending resume mask routes the first post-recovery
                # cycle through schedule_once, which completes the
                # WAL-interrupted cycle before bursting resumes
                spec = cancel_spec(spec, "ineligible/streak/resume")
                if normal_streak > 0 and not burst_ineligible:
                    bstats["burst_suppressed_cycles"] += 1
                normal_streak = max(0, normal_streak - 1)
                if not normal_cycle() and quiescent():
                    break
                continue
            st = solver._structure
            if (st is None
                    or st.generation != self.cache.structure_generation):
                # structure drifted: one snapshot rebuilds the cached
                # tensors; steady-state re-packs skip the snapshot cost
                st = solver._structure_for(self.cache.snapshot(), [])
                spec = cancel_spec(spec, "structure-drift")
            remaining = max_cycles - len(out)
            if spec is not None:
                # pipelined boundary: this window's pack+dispatch
                # already ran, overlapped with the previous apply loop
                handle, spec = spec, None
                plan, K = handle.plan, handle.K
                st = plan.structure
                bstats["burst_overlapped_packs"] += 1
            else:
                K = next((r for r in K_BURST_LADDER if r >= min(
                    remaining, K_BURST_LADDER[-1])), K_BURST_LADDER[-1])
                _t_pack = time.perf_counter()
                with _span("burst.pack"):
                    plan, self._burst_pack_state, _ = pack_burst_cached(
                        st, self.queues, self.cache, self.scheduler,
                        self.clock, state=self._burst_pack_state,
                        min_m=self._burst_m, window=K, stats=bstats)
                bstats["burst_pack_s"] += time.perf_counter() - _t_pack
                bstats["burst_packs"] += 1
                if plan is None:
                    if not normal_cycle() and quiescent():
                        break
                    continue
                self._burst_m = max(self._burst_m, plan.M)
                F = max(1, len(st.fr_index))
                ext_release = np.zeros((K, plan.C, F), dtype=np.int32)
                ext_unpark = np.zeros((K, plan.G), dtype=bool)
                # the kernel must model EVERY release during its window:
                # the caller's external schedule plus the still-pending
                # modeled finishes of cycles applied earlier in this
                # call (a re-pack after truncation starts a fresh
                # release ring)
                sched = {k: list(v) for k, v in ext.items()}
                if runtime > 0:
                    for j in range(max(0, len(out) - runtime), len(out)):
                        due = j + runtime
                        keys = [key for key in out[j].admitted
                                if _reservation_ts(key) is not None
                                and _reservation_ts(key)
                                == sched_ts.get(key)]
                        if keys:
                            sched.setdefault(due, []).extend(keys)
                if not self._fill_burst_finishes(st, plan, sched,
                                                 len(out), K,
                                                 ext_release, ext_unpark):
                    if not normal_cycle() and quiescent():
                        break
                    continue
                with _span("burst.dispatch"):
                    handle = self._burst_solver.dispatch(
                        plan, K, runtime, ext_release, ext_unpark)
                # a fresh pack re-read the live reservation timestamps;
                # candidate ordering inside the kernel assumes they
                # strictly increase across applied cycles (and past
                # every pre-burst reservation) — track it and refuse to
                # apply modeled preempt cycles if violated
                last_adm_clock = plan.max_res_ts
                clock_monotone = True
            # flags-first fetch: block only on the tiny replicated dirty
            # flags (the spec gate's whole input) and park the carry, so
            # the chained next-window dispatch is issued BEFORE the full
            # decision planes are assembled — each shard's decision
            # transfer then overlaps the chained kernel and this
            # window's apply loop instead of serializing ahead of them
            with _span("burst.fetch"):
                dirty, dirty_reason = self._burst_solver.fetch_flags(handle)
            base = len(out)
            # two-slot pipeline: chain the NEXT window off this one's
            # final carry before applying, so its kernel computes while
            # the host applies this window.  Only windows whose model is
            # fully clean can seed a chain, and finish events the carry
            # cannot represent force the serial path: external finishes
            # inside or past the next window, or runtime > K (a PRE-pack
            # admission's finish could then land past this window — the
            # carry only models finishes of in-kernel admissions).
            if os.environ.get("KUEUE_BURST_DEBUG"):
                import sys as _sys
                print(f"spec gate @cycle {base}: remaining={remaining} "
                      f"K={K} runtime={runtime} "
                      f"dirty={bool(np.asarray(dirty).any())} "
                      f"ext_late={any(off >= base + K for off in ext)}",
                      file=_sys.stderr)
            if (pipeline and remaining > K and runtime <= K
                    and not bool(np.asarray(dirty).any())
                    and not any(off >= base + K for off in ext)):
                F = max(1, len(st.fr_index))
                with _span("burst.dispatch"):
                    spec = self._burst_solver.dispatch_next(
                        handle,
                        np.zeros((K, plan.C, F), dtype=np.int32),
                        np.zeros((K, plan.G), dtype=bool))
            with _span("burst.fetch"):
                (head_row, kind, slot, borrows, tgt_words, dirty,
                 dirty_reason) = self._burst_solver.fetch(handle)
            from ..ops import burst as _b
            kind_name = {_b.KIND_ADMIT: "admit", _b.KIND_SKIP: "skip",
                         _b.KIND_PARK: "park", _b.KIND_PREEMPT: "preempt",
                         _b.KIND_RESERVE: "reserve",
                         _b.KIND_OVERLAP_SKIP: "overlap_skip",
                         _b.KIND_PRE_NOFIT: "pre_nofit"}
            cand_rows = plan.arrays["cand_rows"]
            forest_of_cq = plan.arrays["forest_of_cq"]
            st_names = st.cq_names
            applied = 0
            drained = False
            window_complete = False
            for k in range(K):
                if len(out) >= max_cycles:
                    break
                modeled: dict = {}
                has_pre_kind = False
                for ci in np.nonzero(head_row[k] >= 0)[0]:
                    ci = int(ci)
                    key = plan.keys[ci][int(head_row[k, ci])]
                    kd = kind_name.get(int(kind[k, ci]), "park")
                    targets = None
                    if kd == "preempt":
                        rows = _unpack_target_rows(
                            tgt_words[k, ci], cand_rows[forest_of_cq[ci]])
                        targets = []
                        for r in rows:
                            tci, tmi = divmod(int(r), plan.M)
                            targets.append((plan.keys[tci][tmi],
                                            st_names[tci]))
                    if kd in ("preempt", "reserve", "overlap_skip",
                              "pre_nofit"):
                        has_pre_kind = True
                    modeled[key] = (kd, int(slot[k, ci]),
                                    bool(borrows[k, ci]), targets)
                if not dirty[k] and not modeled and quiescent():
                    drained = True
                    if os.environ.get("KUEUE_BURST_DEBUG"):
                        import sys as _sys
                        print(f"win break @k={k}: drained",
                              file=_sys.stderr)
                    break
                # the cycle boundary in schedule_once order: advance the
                # caller's clock FIRST, then fire deadline/backoff timers
                # at the new time, then pop heads
                if on_cycle_start is not None:
                    on_cycle_start(len(out))
                if self.wait_for_pods_ready.enable:
                    self.enforce_wait_for_pods_ready()
                self.queues.wake_expired_backoffs()
                heads = self.queues.heads_nonblocking()
                if dirty[k]:
                    bstats["burst_dirty_cycles"] += 1
                    r = int(dirty_reason[k])
                    if r & _b.DIRTY_PREEMPT:
                        bstats["burst_dirty_preempt"] += 1
                    if r & _b.DIRTY_SCALAR:
                        bstats["burst_dirty_scalar"] += 1
                    if r & _b.DIRTY_RESUME:
                        bstats["burst_dirty_resume"] += 1
                    normal_cycle(heads=heads, advance=False)
                    if applied == 0:
                        dirty_backoff = min(8, max(1, 2 * dirty_backoff))
                        normal_streak = dirty_backoff
                    break   # kernel state is stale past a host cycle
                if has_pre_kind and not clock_monotone:
                    # modeled candidate order may diverge from the host's
                    # reservation-timestamp order: decide on the host
                    if os.environ.get("KUEUE_BURST_DEBUG"):
                        import sys as _sys
                        print(f"win break @k={k}: clock-monotone",
                              file=_sys.stderr)
                    normal_cycle(heads=heads, advance=False)
                    break
                if {h.key for h in heads} != set(modeled):
                    # unmodeled divergence: decide this cycle normally
                    if os.environ.get("KUEUE_BURST_DEBUG"):
                        import sys as _sys
                        print(f"win break @k={k}: heads-mismatch",
                              file=_sys.stderr)
                    normal_cycle(heads=heads, advance=False)
                    break
                if not modeled:
                    # empty cycle: pending finishes may unpark work
                    normal_cycle(heads=[], advance=False)
                    continue
                # one settle per cycle: evict/finish wakeups inside the
                # block collapse into a single deduped requeue pass at
                # exit — before the next heads read, so the observable
                # order matches the eager path decision-for-decision
                with self.cycle_apply():
                    with _span("burst.apply"):
                        stats = self.scheduler.apply_burst_cycle(heads,
                                                                 modeled)
                    if stats is not None:
                        if has_pre_kind:
                            bstats["burst_preempt_cycles"] += 1
                        self.metrics.admission_attempt(
                            bool(stats.admitted), stats.duration_s)
                        if stats.admitted:
                            # the ACTUAL reservation timestamps just
                            # recorded — a resampled clock could tick
                            # between two same-ts admissions and hide
                            # the tie
                            cycle_ts = [
                                t for k2 in stats.admitted
                                if (t := _reservation_ts(k2)) is not None]
                            lo = min(cycle_ts, default=None)
                            if (lo is not None
                                    and last_adm_clock is not None
                                    and lo <= last_adm_clock):
                                clock_monotone = False
                            if len(set(cycle_ts)) > 1:
                                # >1 distinct timestamp inside ONE
                                # cycle: the clock ticked mid-admission,
                                # so modeled preempt ordering can no
                                # longer mirror the host's
                                # candidatesOrdering tie-break
                                clock_monotone = False
                            hi = max(cycle_ts, default=None)
                            if hi is not None:
                                last_adm_clock = (
                                    hi if last_adm_clock is None
                                    else max(last_adm_clock, hi))
                        finish_cycle(stats)
                if stats is None:
                    # a modeled preempt target has no live admitted
                    # counterpart: the model and the real state diverged
                    # — abandon the window and re-decide on the host
                    # (outside cycle_apply: the host cycle must see the
                    # eagerly-settled queue state)
                    bstats["burst_target_divergences"] += 1
                    normal_cycle(heads=heads, advance=False)
                    break
                applied += 1
                normal_streak = 0
                dirty_backoff = 0
                if _chaos.ACTIVE is not None:
                    _chaos.ACTIVE.crashpoint("burst.mid_window")
            else:
                window_complete = True
            if spec is not None and not window_complete:
                # the window was truncated (dirty / divergence / clock):
                # live state no longer matches the carry the speculative
                # window chained from — it must never be applied
                spec = cancel_spec(spec, "window-truncated")
            if drained:
                spec = cancel_spec(spec, "drained")
                break
        spec = cancel_spec(spec, "end-of-call")
        return out

    def _fill_burst_finishes(self, st, plan, ext: dict, base: int, K: int,
                             ext_release, ext_unpark) -> bool:
        """Feed the external finish schedule to the kernel: row-backed
        workloads get their ``death0`` cycle set (the kernel releases
        their exact usage and frees the row — preemption-aware), keys
        without rows fall back to the aggregated [K, C, F] release
        tensors.  False when a fallback release isn't representable
        (run normal cycles instead).  Release vectors are cached per
        admission (an Info build + usage walk per workload is too hot
        for re-packs)."""
        from ..workload import Info
        from ..ops.burst import admitted_usage_vec
        death = plan.arrays["death0"]
        row_of_key = plan.row_of_key or {}
        scale_of = {r: int(st.resource_scale[i])
                    for i, r in enumerate(st.resource_names)}
        F = ext_release.shape[2]
        for off, keys in ext.items():
            k = off - base
            if k < 0 or k >= K:
                continue
            for key in keys:
                wl = self.workloads.get(key)
                if wl is None or wl.admission is None:
                    continue
                loc = row_of_key.get(key)
                if loc is not None and plan.arrays["adm0"][loc]:
                    death[loc] = min(int(death[loc]), k)
                    continue
                ci = st.cq_index.get(wl.admission.cluster_queue)
                if ci is None:
                    return False
                # the live cache Info carries the per-Info usage cache
                # (a throwaway Info would rebuild the usage walk every
                # re-pack)
                cq_live = self.cache.cluster_queue(
                    wl.admission.cluster_queue)
                info = (cq_live.workloads.get(key)
                        if cq_live is not None else None)
                if info is None:
                    info = Info(wl, self.cache.info_options)
                uv = admitted_usage_vec(info, st, scale_of, F)
                if uv is None:
                    return False
                ext_release[k, ci] += uv[0]
                if uv[0].any():
                    # zero-usage finishes release nothing the kernel
                    # can observe (matches the death-row path, which
                    # only unparks on released usage); their wakeup
                    # reaches the host through the heads-mismatch break
                    ext_unpark[k,
                               int(plan.arrays["forest_of_cq"][ci])] = True
        return True

    def run(self, stop_event, heads_timeout: float = 0.2) -> None:
        """Daemon mode: the long-running admission loop over blocking
        ``queues.heads()`` with the speed-signal backoff (reference
        scheduler.go:143 Start driven by wait.UntilWithBackoff).  Blocks
        until ``stop_event`` is set; producers on other threads create
        workloads through the normal Driver API and the loop admits them
        as they arrive."""
        def on_cycle(stats):
            self.metrics.admission_attempt(bool(stats.admitted),
                                           stats.duration_s)
            self.obs.record_cycle(stats)

        def on_tick():
            if self.wait_for_pods_ready.enable:
                self.enforce_wait_for_pods_ready()
            self.queues.wake_expired_backoffs()

        self.scheduler.run(stop_event, heads_timeout=heads_timeout,
                           on_cycle=on_cycle, on_tick=on_tick)

    def run_until_settled(self, max_cycles: int = 1000):
        """Run cycles until a fixed point: no admissions/preemptions AND the
        queue state fingerprint repeats (a cycle that merely parks a blocked
        head still makes progress)."""
        all_stats = []
        prev_fp = None
        for _ in range(max_cycles):
            stats = self.schedule_once()
            all_stats.append(stats)
            if stats.admitted or stats.preempting:
                prev_fp = None
                continue
            fp = self._queue_fingerprint()
            if fp == prev_fp:
                break
            prev_fp = fp
        return all_stats

    def _queue_fingerprint(self):
        out = []
        for name in sorted(self.queues.cluster_queue_names()):
            q = self.queues.queue_for(name)
            out.append((name, tuple(sorted(q.heap.keys())),
                        tuple(sorted(q.inadmissible))))
        return tuple(out)

    # -- introspection --

    @property
    def stats(self) -> dict:
        """One-stop counter snapshot shared by the perf harness, the
        chaos report, and the open-loop traffic runner: incremental
        snapshot reuse, queue depth / requeue-storm accounting, and the
        burst solver's dispatch counters when one is live."""
        q = self.queues
        out = {
            "snapshot": dict(self.cache.snapshot_stats),
            "queue": {
                "ready_cqs": len(q._ready),
                "armed_timer_cqs": len(q._timers),
                "requeue_storm_last": q.requeue_storm_last,
                "requeue_storm_peak": q.requeue_storm_peak,
                "requeue_storms_total": q.requeue_storms_total,
                "requeue_unparked_total": q.requeue_unparked_total,
            },
            "admission_attempts": {
                "success": int(self.metrics.counters.get(
                    ("kueue_admission_attempts_total", "success"), 0)),
                "inadmissible": int(self.metrics.counters.get(
                    ("kueue_admission_attempts_total", "inadmissible"), 0)),
            },
        }
        if self._burst_solver is not None:
            out["burst"] = dict(self._burst_solver.stats)
            # streaming-pack host-cost block: the kueue_pack_* series
            # (arena occupancy/growth, row/rank patches, dtype-tighten
            # savings) split out of the flat solver counters
            bs = out["burst"]
            out["pack"] = {k: bs[k] for k in (
                "stream_packs", "stream_full_packs", "stream_pack_bails",
                "stream_pack_s", "pack_last_ms", "pack_row_patches",
                "pack_rows_verified",
                "pack_rank_patches", "pack_arena_growth_events",
                "pack_arena_planes", "pack_arena_bytes",
                "pack_arena_used_bytes", "pack_tighten_bytes_saved",
                "pack_tighten_widened", "burst_launch_bytes_h2d")
                if k in bs}
            # cohort-forest compression block: packed vs compressed
            # admitted rows + the compressible-CQ census (kueue_agg_*)
            agg = {k: bs[k] for k in (
                "agg_rows_compressed", "agg_rows_packed", "agg_heads",
                "agg_cqs_compressible") if k in bs}
            if agg:
                out["agg"] = agg
            # head-only packing block: rows charged to the kernel's
            # 2^19 composite-key budget vs budget-exempt rank context
            hp = {k: bs[k] for k in (
                "head_pack_budget_rows", "head_pack_exempt_rows")
                if k in bs}
            if hp:
                out["head_pack"] = hp
        from ..utils.heap import REPAIR_STATS
        out["heap_repair"] = dict(REPAIR_STATS)
        from ..utils.parallel_host import POOL_STATS
        out["host_pool"] = dict(POOL_STATS,
                                host_pool_workers=self.host_pool.workers)
        if self._wal is not None and hasattr(self._wal, "stats"):
            out["wal"] = dict(self._wal.stats)
            if "wal_shards" in out["wal"]:
                out["wal_shard"] = {
                    "wal_shards": out["wal"]["wal_shards"],
                    "wal_shard_skew": out["wal"]["wal_shard_skew"]}
        solver = self.scheduler.solver
        if solver is not None and hasattr(solver, "stats"):
            ss = solver.stats
            out["flavor_walk"] = {
                "host_cycles": ss.get("host_cycles", 0),
                "scalar_heads": ss.get("scalar_heads", 0),
                "scalar_reasons": dict(ss.get("scalar_reasons", {})),
                "resume_heads": ss.get("resume_heads", 0),
                "walk_stop_heads": ss.get("walk_stop_heads", 0),
                "native_ff_fallbacks": ss.get("native_ff_fallbacks", 0),
            }
        self.metrics.burst_solver_sample(out.get("burst"),
                                         out.get("flavor_walk"))
        self.metrics.pack_sample(out.get("pack"), out.get("wal"))
        self.metrics.scale_opt_sample(out.get("agg"), out["heap_repair"],
                                      out.get("wal_shard"),
                                      out.get("head_pack"),
                                      out["host_pool"])
        # distributed-run blocks, attached by the harness that owns the
        # processes/clients (ProcFederation, dist_soak): summed
        # HttpWorkerClient accounting and the supervisor's report
        rpc_clients = getattr(self, "rpc_clients", None)
        if rpc_clients:
            agg_rpc: dict[str, int] = {}
            for c in rpc_clients:
                for k, v in c.stats.items():
                    agg_rpc[k] = agg_rpc.get(k, 0) + int(v)
            out["rpc"] = agg_rpc
            self.metrics.rpc_sample(agg_rpc)
        dist_stats = getattr(self, "dist_stats", None)
        if dist_stats:
            out["dist"] = dict(dist_stats)
            self.metrics.dist_sample(
                dist_stats.get("by_role", {}),
                proxy_stats=dist_stats.get("proxy"),
                shard_depths=dist_stats.get("shard_depths"))
        out["obs"] = self.obs.report()
        return out

    def admitted_keys(self) -> set[str]:
        """Workloads currently holding quota (reserved and not finished)."""
        return {k for k, wl in self.workloads.items()
                if wl.condition_true(WL_QUOTA_RESERVED) and not wl.is_finished}

    def workload(self, key: str) -> Optional[Workload]:
        return self.workloads.get(key)
