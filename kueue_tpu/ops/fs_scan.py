"""Device fair-sharing admission: the tournament + admit loop as ONE scan.

Round 3 left fair-sharing cycles permanently classify-only: the batched
``TournamentDRS`` computed per-round DRS values but the admission loop —
tournament winner selection, fit re-check, usage mutation, repeat —
stayed on the host because the within-cycle ordering is data-dependent on
DRS (verdict r3 item 3).  This module runs the WHOLE loop as one jitted
``lax.scan`` over rounds, so plain-admission fair-sharing cycles become
fully device-decided (FULL mode).

Reference semantics reproduced exactly (fair_sharing_iterator.go):

- Per round, the first remaining entry in heads order is taken; a
  parentless CQ's entry wins immediately (the iterator yields it), else
  the **tournament** runs over that entry's cohort tree: at every cohort
  node, the surviving candidate minimizes (DRS of its child-of-this-node
  ancestor with the entry's usage added, then priority desc, timestamp
  asc, then structural child order) — runTournament/entryComparer.less
  (:121,:167).
- DRS (fair_sharing.go:47-82): max over resources of borrowed-above-
  subtree-quota × 1000 // lendable-in-parent, then × 1000 // fairWeight;
  0 when not borrowing or at a root, MAX when weight is zero.  The
  int32-scaled tensors preserve the exact host values because every
  quantity of one resource shares the per-resource scale and
  floor((a/s)·1000/(b/s)) == floor(a·1000/b); the packer refuses shapes
  whose intermediate products could overflow int32 (host falls back).
- The winner is processed like the host admit loop: NO_FIT entries are
  discarded, fit entries re-check chain-local availability against the
  mutated usage (scheduler.go:372) and either admit (usage charged up
  the ancestor chain) or skip.

Decision parity is enforced against the host tournament path by
tests/test_fs_device.py and the fair-sharing conformance tables.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .quota_kernel import available_at, add_usage_chain

INF_I32 = np.int32(2**31 - 1)
MAX_DRS_I32 = np.int32(2**31 - 2)   # weight-zero sentinel (host MAX_DRS)


@dataclass
class FSStatics:
    """Fair-sharing structure tensors, cached per structure generation."""
    sq_mask: np.ndarray       # [N, F] bool: fr present in subtree_quota
    lendable_r: np.ndarray    # [N, R] int32 scaled lendable-in-parent
    onehot: np.ndarray        # [F, R] int32 fr -> resource
    node_level: np.ndarray    # [N] int32 (roots = 0)
    child_order: np.ndarray   # [N] int32 rank among parent's children
    n_levels: int
    drs_bound_base: int       # max scaled borrowing the statics allow


def build_fs_statics(snapshot, st) -> Optional[FSStatics]:
    """Build the static FS tensors for a PackedStructure.

    Returns None when the scaled DRS math could overflow int32 for ANY
    conceivable usage below the structure's quota ceilings — the
    scheduler then keeps the host tournament."""
    from .cycle import available_all_np
    N, F = st.subtree_quota.shape
    C = len(st.cq_names)
    R = len(st.resource_names)

    fr_to_r = np.zeros(F, dtype=np.int64)
    for fr, fi in st.fr_index.items():
        fr_to_r[fi] = st.r_index[fr.resource]
    onehot = (fr_to_r[:, None] == np.arange(R)[None, :]).astype(np.int32)

    # subtree-quota presence masks + child enumeration order from the
    # snapshot (cohorts before CQs, list order — _fs_tournament)
    from .packing import _iter_nodes
    cq_names, cohorts = _iter_nodes(snapshot)
    if list(cq_names) != list(st.cq_names):
        return None
    nodes = [snapshot.cluster_queues[n] for n in cq_names] + cohorts
    sq_mask = np.zeros((N, F), dtype=bool)
    for ni, node in enumerate(nodes):
        for fr in node.resource_node.subtree_quota:
            fi = st.fr_index.get(fr)
            if fi is not None:
                sq_mask[ni, fi] = True

    node_index: dict[int, int] = {id(n): i for i, n in enumerate(nodes)}
    child_order = np.zeros(N, dtype=np.int32)
    for ci, cohort in enumerate(cohorts):
        rank = 0
        for ch in cohort.child_cohorts:
            i = node_index.get(id(ch))
            if i is not None:
                child_order[i] = rank
                rank += 1
        for cq in cohort.child_cqs:
            i = node_index.get(id(cq))
            if i is not None:
                child_order[i] = rank
                rank += 1

    node_level = np.zeros(N, dtype=np.int32)
    for ni in range(N):
        lvl, p = 0, int(st.parent[ni])
        while p >= 0:
            lvl += 1
            p = int(st.parent[p])
        node_level[ni] = lvl
    n_levels = int(node_level.max()) + 1

    # lendable: potentialAvailable of the parent, masked to the frs of
    # the root's subtree quota, summed per resource (fair_sharing.go:86)
    potential = available_all_np(
        np.zeros((N, F), np.int64), st.subtree_quota, st.guaranteed,
        st.borrow_cap, st.has_borrow_limit, st.parent, st.depth)
    root_of = np.arange(N)
    for ni in range(N):
        cur = ni
        while st.parent[cur] >= 0:
            cur = int(st.parent[cur])
        root_of[ni] = cur
    p_safe = np.maximum(st.parent, 0)
    masked = np.where(sq_mask[root_of] & (st.parent >= 0)[:, None],
                      potential[p_safe], 0)
    lendable64 = masked @ onehot.astype(np.int64)                # [N, R]
    if lendable64.max(initial=0) > INF_I32:
        return None
    lendable_r = lendable64.astype(np.int32)

    # overflow ceiling: the largest borrowing any usage below the quota
    # plane could show is bounded by the total subtree quota (borrowing
    # never exceeds what parents can lend)
    drs_bound_base = int(np.abs(st.subtree_quota.astype(np.int64)).sum())
    return FSStatics(sq_mask=sq_mask, lendable_r=lendable_r,
                     onehot=onehot, node_level=node_level,
                     child_order=child_order, n_levels=n_levels,
                     drs_bound_base=drs_bound_base)


def fs_bounds_ok(statics: FSStatics, usage0, u_e) -> bool:
    """True when every intermediate DRS product stays inside int32.

    Structural bound: the device path only ever adds FIT-checked entry
    usage to usage from admitted workloads, so borrowing never exceeds
    the parent's lendable capacity and ratio <= 1000; the remaining
    products are borrowing*1000 (bounded by both total usage and max
    lendable) and ratio*1000 (<= 10^6).  The kernel additionally clamps
    ratio so a violated assumption can't wrap."""
    b = (int(np.abs(usage0.astype(np.int64)).max(initial=0))
         + int(np.abs(u_e.astype(np.int64)).sum(axis=0).max(initial=0)))
    lend_max = int(statics.lendable_r.astype(np.int64).max(initial=0))
    return (min(b, lend_max) * 1000 < 2**31) and (b < 2**31)


@partial(jax.jit, static_argnames=("depth", "n_levels"))
def fs_admit_scan(usage0, subtree, sq_mask, guaranteed, borrow_cap,
                  has_blim, parent, node_level, weights, lendable_r,
                  onehot, child_order,
                  wl_cq, u_e, nofit, prio, ts_rank, valid,
                  *, depth: int, n_levels: int):
    """The fair-sharing cycle as one scan: W rounds of tournament +
    admit.  Returns (order [W] winner per round or -1, admitted [W],
    processed [W]) in head order; a fit head with ``processed`` and not
    ``admitted`` lost capacity in-cycle (skip)."""
    N, F = usage0.shape
    W = wl_cq.shape[0]
    L = depth
    cidx = jnp.arange(W, dtype=jnp.int32)
    cq_safe = jnp.maximum(wl_cq, 0)
    # static per entry: the path from its CQ to the root
    paths = [cq_safe]
    for _ in range(L - 1):
        prev = paths[-1]
        nxt = jnp.where(prev >= 0, parent[jnp.maximum(prev, 0)], -1)
        paths.append(jnp.where(paths[-1] >= 0, nxt, -1))
    path = jnp.stack(paths, axis=1)                   # [W, L]
    parentless = parent[cq_safe] < 0

    def round_step(carry, _):
        usage, remaining = carry

        # -- DRS of every remaining entry at every path level ---------
        drs_lv = []
        carry_u = u_e                                  # [W, F]
        for lvl in range(L):
            node = path[:, lvl]
            alive = node >= 0
            ns = jnp.maximum(node, 0)
            has_par = alive & (parent[ns] >= 0)
            u_after = usage[ns] + carry_u
            borrowed = jnp.maximum(0, u_after - subtree[ns]) * sq_mask[ns]
            borrowing_r = borrowed @ onehot            # [W, R]
            has_borrow = jnp.any(borrowing_r > 0, axis=1)
            lend = lendable_r[ns]
            qual = (borrowing_r > 0) & (lend > 0)
            # borrowing <= lendable in every reachable state (fit-checked
            # additions over admitted usage); the clamp guards the int32
            # product if that invariant is ever violated
            safe_b = jnp.minimum(borrowing_r, jnp.maximum(lend, 1))
            ratio = jnp.where(qual,
                              safe_b * 1000 // jnp.maximum(lend, 1),
                              -1)
            drs_raw = jnp.max(ratio, axis=1)
            w = weights[ns]
            core = drs_raw * 1000 // jnp.maximum(w, 1)
            dws = jnp.where(has_borrow, core, 0)
            dws = jnp.where(w == 0, MAX_DRS_I32, dws)
            dws = jnp.where(has_par, dws, 0)
            drs_lv.append(dws)
            local_avail = jnp.maximum(0, guaranteed[ns] - usage[ns])
            carry_u = jnp.where(alive[:, None],
                                jnp.maximum(0, carry_u - local_avail),
                                carry_u)
        drs = jnp.stack(drs_lv, axis=1)                # [W, L]

        # -- tournament: bottom-up winner propagation -----------------
        # node_winner[n] = index of the best remaining entry in n's
        # subtree; promoted level by level with 4-key scatter-argmin
        # (drs at the child node, priority desc, ts asc, child order)
        any_remaining = jnp.any(remaining & valid)
        e0 = jnp.argmax(remaining & valid).astype(jnp.int32)

        # only live entries scatter; padded/consumed rows target the
        # out-of-bounds drop bucket (each CQ holds at most one head)
        tgt0 = jnp.where(remaining & valid, cq_safe, N)
        node_winner = jnp.full(N, -1, dtype=jnp.int32).at[tgt0].set(
            cidx, mode="drop")
        cq_lv = node_level[cq_safe]                    # [W]

        for lvl in range(n_levels - 1, 0, -1):
            # promote winners of level-`lvl` nodes into their parents
            is_l = node_level == lvl
            src = jnp.arange(N)
            has_w = is_l & (node_winner >= 0) & (parent >= 0)
            e = jnp.maximum(node_winner, 0)
            # the winner's drs AT the child node: path index = depth of
            # the entry's CQ minus the node's level
            li = jnp.clip(cq_lv[e] - lvl, 0, L - 1)
            k_drs = jnp.where(has_w, drs[e, li], INF_I32)
            k_prio = jnp.where(has_w, -prio[e], INF_I32)
            k_ts = jnp.where(has_w, ts_rank[e], INF_I32)
            k_ord = jnp.where(has_w, child_order[src], INF_I32)
            p_s = jnp.maximum(parent, 0)
            tgt = jnp.where(has_w, p_s, N)             # drop bucket N
            m1 = jnp.full(N + 1, INF_I32, jnp.int32).at[tgt].min(k_drs)
            ok1 = has_w & (k_drs == m1[tgt])
            m2 = jnp.full(N + 1, INF_I32, jnp.int32).at[tgt].min(
                jnp.where(ok1, k_prio, INF_I32))
            ok2 = ok1 & (k_prio == m2[tgt])
            m3 = jnp.full(N + 1, INF_I32, jnp.int32).at[tgt].min(
                jnp.where(ok2, k_ts, INF_I32))
            ok3 = ok2 & (k_ts == m3[tgt])
            m4 = jnp.full(N + 1, INF_I32, jnp.int32).at[tgt].min(
                jnp.where(ok3, k_ord, INF_I32))
            ok4 = ok3 & (k_ord == m4[tgt])
            promoted = jnp.full(N + 1, -1, jnp.int32).at[tgt].max(
                jnp.where(ok4, node_winner, -1))
            node_winner = jnp.where(
                (node_level == lvl - 1) & (promoted[:N] >= 0),
                promoted[:N], node_winner)

        # root of e0's tree
        root = cq_safe[e0]
        for _ in range(L - 1):
            p = parent[root]
            root = jnp.where(p >= 0, jnp.maximum(p, 0), root)
        tw = node_winner[root]
        winner = jnp.where(parentless[e0] | (tw < 0), e0, tw)
        winner = jnp.where(any_remaining, winner, -1)

        # -- process the winner (host admit-loop semantics) -----------
        ws = jnp.maximum(winner, 0)
        is_live = winner >= 0
        w_cq = cq_safe[ws]
        avail = available_at(usage, subtree, guaranteed, borrow_cap,
                             has_blim, parent, w_cq, depth)
        w_u = u_e[ws]                                  # [F]
        rel = w_u > 0
        fits = jnp.all(jnp.where(rel, w_u <= avail, True))
        can_admit = is_live & ~nofit[ws] & fits
        delta = jnp.where(can_admit, w_u, 0)
        usage = add_usage_chain(usage, jnp.where(can_admit, w_cq, -1),
                                delta, guaranteed, parent, depth)
        remaining = remaining.at[ws].set(
            jnp.where(is_live, False, remaining[ws]))
        return (usage, remaining), (winner, can_admit)

    remaining0 = valid
    (_, _), (order, admit_o) = jax.lax.scan(
        round_step, (usage0, remaining0), None, length=W)
    z = jnp.zeros(W, dtype=bool)
    sel = jnp.maximum(order, 0)
    live = order >= 0
    admitted = z.at[sel].max(admit_o & live)
    processed = z.at[sel].max(live)
    return order, admitted, processed
