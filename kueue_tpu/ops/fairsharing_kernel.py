"""Batched fair-sharing DRS (SURVEY §7 stage 6).

``drs_components`` computes, for every node at once, the two tensors the
DominantResourceShare needs (reference fair_sharing.go:47-82
dominantResourceShare + calculateLendable): borrowed-above-subtree-quota
per (node, resource) and the parent's lendable capacity per
(node, resource) — one one-hot matmul over [N, F] instead of a per-CQ
tree walk.  The final exact int64 ratio/weight division happens host-side
(``compute_all_drs``), keeping the kernel int32/TPU-native.

``TournamentDRS`` is the admission-tournament backend (reference
fair_sharing_iterator.go computeDRS): it packs the snapshot once per
cycle into unscaled int64 node tensors, maintains the usage tensor
incrementally as the admit loop mutates the snapshot, and computes every
remaining entry's DRS at every cohort level in ONE vectorized pass per
tournament round — replacing the per-entry simulate/revert walk that made
the tournament O(heads²·tree) in Python."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.state import MAX_DRS
from .quota_kernel import available_all


@partial(jax.jit, static_argnames=("depth", "n_resources"))
def drs_components(usage, subtree, guaranteed, borrow_cap, has_blim, parent,
                   fr_to_resource, wl_req=None, *,
                   n_resources: int, depth: int):
    """Returns (borrowing [N,R], lendable [N,R]) int32.

    fr_to_resource: [F] int32 mapping flavor-resource columns to resource
    index; wl_req: optional [N, F] additive usage (the fair-sharing
    iterator's computeDRS adds the entry's usage to its CQ)."""
    onehot = jax.nn.one_hot(fr_to_resource, n_resources, dtype=usage.dtype)

    total_usage = usage if wl_req is None else usage + wl_req
    borrowed = jnp.maximum(0, total_usage - subtree)                # [N, F]
    borrowing_r = borrowed @ onehot                                 # [N, R]

    # lendable: potentialAvailable of each node's parent, summed per
    # resource (calculateLendable, fair_sharing.go:86)
    potential = available_all(jnp.zeros_like(usage), subtree, guaranteed,
                              borrow_cap, has_blim, parent, depth)
    lendable_all = potential @ onehot
    parent_safe = jnp.maximum(parent, 0)
    lendable_r = jnp.where((parent >= 0)[:, None],
                           lendable_all[parent_safe], 0)            # [N, R]
    return borrowing_r, lendable_r


class TournamentDRS:
    """Batched computeDRS for the fair-sharing admission tournament
    (reference fair_sharing_iterator.go:157-221).

    Packs the snapshot's cohort forest once per cycle into unscaled int64
    tensors (host numpy — no int32 scaling concerns), then per tournament
    round computes every remaining entry's DominantResourceShare at every
    level of its CQ→root path in one vectorized pass, bit-matching
    cache.state.dominant_resource_share.  ``note_add`` mirrors the admit
    loop's ``simulate_usage_addition`` chain-adds into the usage tensor so
    no per-round repack is needed."""

    _NO_LIMIT = np.int64(2) ** 61

    def __init__(self, snapshot):
        from .packing import _iter_nodes
        cq_names, cohorts = _iter_nodes(snapshot)
        nodes = [snapshot.cluster_queues[n] for n in cq_names] + cohorts
        self.names: list[str] = list(cq_names) + [c.name for c in cohorts]
        self.cq_index = {n: i for i, n in enumerate(cq_names)}
        self.stale = False
        N = len(nodes)

        frs = set()
        for node in nodes:
            rn = node.resource_node
            frs.update(rn.subtree_quota)
            frs.update(rn.usage)
            frs.update(rn.quotas)
        fr_list = sorted(frs)
        self.fr_index = {fr: i for i, fr in enumerate(fr_list)}
        self.F = F = max(1, len(fr_list))
        res_names = sorted({fr.resource for fr in fr_list})
        r_index = {r: i for i, r in enumerate(res_names)}
        R = max(1, len(res_names))
        fr_to_r = np.zeros(F, dtype=np.int64)
        for fr, fi in self.fr_index.items():
            fr_to_r[fi] = r_index[fr.resource]
        self.onehot = (fr_to_r[:, None]
                       == np.arange(R)[None, :]).astype(np.int64)  # [F,R]

        parent = np.full(N, -1, dtype=np.int64)
        subtree = np.zeros((N, F), dtype=np.int64)
        sq_mask = np.zeros((N, F), dtype=bool)
        guaranteed = np.zeros((N, F), dtype=np.int64)
        borrow_cap = np.full((N, F), self._NO_LIMIT, dtype=np.int64)
        has_blim = np.zeros((N, F), dtype=bool)
        u = np.zeros((N, F), dtype=np.int64)
        weights = np.zeros(N, dtype=np.int64)
        cohort_idx = {id(c): len(cq_names) + i for i, c in enumerate(cohorts)}
        for ni, node in enumerate(nodes):
            p = node.parent
            parent[ni] = cohort_idx[id(p)] if p is not None else -1
            weights[ni] = getattr(node, "fair_weight_milli", 1000)
            rn = node.resource_node
            for fr, v in rn.subtree_quota.items():
                fi = self.fr_index[fr]
                subtree[ni, fi] = v
                sq_mask[ni, fi] = True
            for fr, v in rn.usage.items():
                u[ni, self.fr_index[fr]] = v
            for fr, q in rn.quotas.items():
                fi = self.fr_index[fr]
                g = rn.guaranteed_quota(fr)
                guaranteed[ni, fi] = g
                if q.borrowing_limit is not None:
                    has_blim[ni, fi] = True
                    borrow_cap[ni, fi] = (rn.subtree_quota.get(fr, 0) - g
                                          + q.borrowing_limit)

        depth = 1
        for ni in range(N):
            d, p = 1, int(parent[ni])
            while p >= 0:
                d += 1
                p = int(parent[p])
            depth = max(depth, d)
        self.depth = depth
        self.parent = parent
        self.subtree = subtree
        self.sq_mask = sq_mask
        self.guaranteed = guaranteed
        self.u = u
        self.weights = weights

        # lendable at node n: potentialAvailable(parent(n), fr) summed per
        # resource over the frs of root(n)'s subtree quota
        # (calculate_lendable, fair_sharing.go:86) — static per cycle
        from .cycle import available_all_np
        potential = available_all_np(np.zeros((N, F), dtype=np.int64),
                                     subtree, guaranteed, borrow_cap,
                                     has_blim, parent, depth)
        root_of = np.arange(N)
        for ni in range(N):
            cur = ni
            while parent[cur] >= 0:
                cur = int(parent[cur])
            root_of[ni] = cur
        p_safe = np.maximum(parent, 0)
        masked = np.where(sq_mask[root_of] & (parent >= 0)[:, None],
                          potential[p_safe], 0)
        self.lendable_r = masked @ self.onehot                     # [N, R]

    def u_vec(self, usage) -> Optional[np.ndarray]:
        """FlavorResourceQuantities → [F] int64, or None on unknown fr."""
        vec = np.zeros(self.F, dtype=np.int64)
        for fr, v in usage.items():
            fi = self.fr_index.get(fr)
            if fi is None:
                return None
            vec[fi] += v
        return vec

    def note_add(self, cq_name: str, usage) -> None:
        """Mirror a snapshot ``simulate_usage_addition`` into the usage
        tensor (add_usage bubbling, resource_node.go:123)."""
        ci = self.cq_index.get(cq_name)
        if ci is None:
            return
        carry = self.u_vec(usage)
        if carry is None:
            self.stale = True  # unseen fr: callers fall back per-entry
            return
        cur = ci
        while cur >= 0:
            local_avail = np.maximum(0, self.guaranteed[cur] - self.u[cur])
            self.u[cur] += carry
            carry = np.maximum(0, carry - local_avail)
            if not carry.any():
                break
            cur = int(self.parent[cur])

    def drs_for(self, cq_is: np.ndarray, u_es: np.ndarray
                ) -> tuple[np.ndarray, np.ndarray]:
        """DRS with each entry's usage added, at every path level.

        cq_is: [W] node indices; u_es: [W, F] entry usage.  Returns
        (paths [W, L] node index or -1, drs [W, L]) where drs[j, l] is the
        DominantResourceShare of paths[j, l] after adding entry j's usage
        along its chain — the value computeDRS keys by
        (parent(paths[j, l]), workload)."""
        W = len(cq_is)
        L = self.depth
        paths = np.full((W, L), -1, dtype=np.int64)
        drs = np.zeros((W, L), dtype=np.int64)
        cur = cq_is.astype(np.int64)
        carry = u_es.copy()
        for level in range(L):
            alive = cur >= 0
            cur_s = np.maximum(cur, 0)
            par = self.parent[cur_s]
            has_par = alive & (par >= 0)
            u_after = self.u[cur_s] + carry                      # [W, F]
            borrowed = (np.maximum(0, u_after - self.subtree[cur_s])
                        * self.sq_mask[cur_s])
            borrowing_r = borrowed @ self.onehot                 # [W, R]
            has_borrow = (borrowing_r > 0).any(axis=1)
            lend = self.lendable_r[cur_s]
            qual = (borrowing_r > 0) & (lend > 0)
            ratio = np.where(qual,
                             borrowing_r * 1000 // np.maximum(lend, 1), -1)
            drs_raw = ratio.max(axis=1, initial=-1)
            w = self.weights[cur_s]
            core = drs_raw * 1000 // np.maximum(w, 1)
            dws = np.where(has_borrow, core, 0)
            dws = np.where(w == 0, MAX_DRS, dws)
            dws = np.where(has_par, dws, 0)
            drs[:, level] = dws
            paths[:, level] = np.where(alive, cur, -1)
            local_avail = np.maximum(0, self.guaranteed[cur_s]
                                     - self.u[cur_s])
            carry = np.where(alive[:, None],
                             np.maximum(0, carry - local_avail), carry)
            cur = np.where(alive, par, -1)
        return paths, drs


def compute_all_drs(snapshot) -> dict[str, int]:
    """DRS for every ClusterQueue and cohort in one device pass; parity
    with cache.state.dominant_resource_share (requires exact packing)."""
    from .packing import PackedCycle, _iter_nodes, pack_cycle
    packed = pack_cycle(snapshot, [])
    r_idx = {r: i for i, r in enumerate(packed.resource_names)}
    F = packed.usage0.shape[1]
    fr_to_resource = np.zeros(F, dtype=np.int32)
    for fr, fi in packed.fr_index.items():
        fr_to_resource[fi] = r_idx[fr.resource]
    borrowing, lendable = drs_components(
        packed.usage0, packed.subtree_quota, packed.guaranteed,
        packed.borrow_cap, packed.has_borrow_limit, packed.parent,
        fr_to_resource, n_resources=len(packed.resource_names),
        depth=packed.depth)
    borrowing = np.asarray(borrowing, dtype=np.int64)
    lendable = np.asarray(lendable, dtype=np.int64)
    # per-resource scaling cancels in the ratio only for exact packs;
    # scale back up to raw units to keep host parity regardless
    scale = packed.resource_scale.astype(np.int64)                  # [R]
    borrowing *= scale[None, :]
    lendable *= scale[None, :]

    _, cohorts = _iter_nodes(snapshot)
    names = list(packed.cq_names) + [c.name for c in cohorts]
    weights = packed.fair_weight_milli
    parent = packed.parent
    out: dict[str, int] = {}
    for i, name in enumerate(names):
        if parent[i] < 0:
            out[name] = 0
            continue
        if weights[i] == 0:
            out[name] = MAX_DRS
            continue
        if not (borrowing[i] > 0).any():
            out[name] = 0       # not borrowing at all (fair_sharing.go:63)
            continue
        drs = -1
        for r in range(borrowing.shape[1]):
            if borrowing[i, r] > 0 and lendable[i, r] > 0:
                drs = max(drs, int(borrowing[i, r]) * 1000
                          // int(lendable[i, r]))
        out[name] = drs * 1000 // int(weights[i])
    return out
