"""Batched fair-sharing DRS (SURVEY §7 stage 6).

``drs_components`` computes, for every node at once, the two tensors the
DominantResourceShare needs (reference fair_sharing.go:47-82
dominantResourceShare + calculateLendable): borrowed-above-subtree-quota
per (node, resource) and the parent's lendable capacity per
(node, resource) — one one-hot matmul over [N, F] instead of a per-CQ
tree walk.  The final exact int64 ratio/weight division happens host-side
(``compute_all_drs``), keeping the kernel int32/TPU-native.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.state import MAX_DRS
from .quota_kernel import available_all


@partial(jax.jit, static_argnames=("depth", "n_resources"))
def drs_components(usage, subtree, guaranteed, borrow_cap, has_blim, parent,
                   fr_to_resource, wl_req=None, *,
                   n_resources: int, depth: int):
    """Returns (borrowing [N,R], lendable [N,R]) int32.

    fr_to_resource: [F] int32 mapping flavor-resource columns to resource
    index; wl_req: optional [N, F] additive usage (the fair-sharing
    iterator's computeDRS adds the entry's usage to its CQ)."""
    onehot = jax.nn.one_hot(fr_to_resource, n_resources, dtype=usage.dtype)

    total_usage = usage if wl_req is None else usage + wl_req
    borrowed = jnp.maximum(0, total_usage - subtree)                # [N, F]
    borrowing_r = borrowed @ onehot                                 # [N, R]

    # lendable: potentialAvailable of each node's parent, summed per
    # resource (calculateLendable, fair_sharing.go:86)
    potential = available_all(jnp.zeros_like(usage), subtree, guaranteed,
                              borrow_cap, has_blim, parent, depth)
    lendable_all = potential @ onehot
    parent_safe = jnp.maximum(parent, 0)
    lendable_r = jnp.where((parent >= 0)[:, None],
                           lendable_all[parent_safe], 0)            # [N, R]
    return borrowing_r, lendable_r


def compute_all_drs(snapshot) -> dict[str, int]:
    """DRS for every ClusterQueue and cohort in one device pass; parity
    with cache.state.dominant_resource_share (requires exact packing)."""
    from .packing import PackedCycle, _iter_nodes, pack_cycle
    packed = pack_cycle(snapshot, [])
    r_idx = {r: i for i, r in enumerate(packed.resource_names)}
    F = packed.usage0.shape[1]
    fr_to_resource = np.zeros(F, dtype=np.int32)
    for fr, fi in packed.fr_index.items():
        fr_to_resource[fi] = r_idx[fr.resource]
    borrowing, lendable = drs_components(
        packed.usage0, packed.subtree_quota, packed.guaranteed,
        packed.borrow_cap, packed.has_borrow_limit, packed.parent,
        fr_to_resource, n_resources=len(packed.resource_names),
        depth=packed.depth)
    borrowing = np.asarray(borrowing, dtype=np.int64)
    lendable = np.asarray(lendable, dtype=np.int64)
    # per-resource scaling cancels in the ratio only for exact packs;
    # scale back up to raw units to keep host parity regardless
    scale = packed.resource_scale.astype(np.int64)                  # [R]
    borrowing *= scale[None, :]
    lendable *= scale[None, :]

    _, cohorts = _iter_nodes(snapshot)
    names = list(packed.cq_names) + [c.name for c in cohorts]
    weights = packed.fair_weight_milli
    parent = packed.parent
    out: dict[str, int] = {}
    for i, name in enumerate(names):
        if parent[i] < 0:
            out[name] = 0
            continue
        if weights[i] == 0:
            out[name] = MAX_DRS
            continue
        if not (borrowing[i] > 0).any():
            out[name] = 0       # not borrowing at all (fair_sharing.go:63)
            continue
        drs = -1
        for r in range(borrowing.shape[1]):
            if borrowing[i, r] > 0 and lendable[i, r] > 0:
                drs = max(drs, int(borrowing[i, r]) * 1000
                          // int(lendable[i, r]))
        out[name] = drs * 1000 // int(weights[i])
    return out
