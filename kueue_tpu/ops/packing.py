"""Snapshot → packed device arrays.

The deterministic codec from a cache Snapshot + cycle heads into static-
shaped integer tensors (SURVEY §7 stage 1).  Axes:

- N: quota nodes = ClusterQueues then Cohorts (parent-pointer forest)
- F: distinct (flavor, resource) pairs appearing in any quota
- W: cycle heads, padded to a bucket size (power of two) to bound
  recompilation
- S: flavor slots per resource group (max flavor-list length)
- R: distinct resource names

The codec is split in two so the per-cycle cost is O(usage + heads), not
O(cluster):

- ``PackedStructure`` — everything derived from specs (quota tensors,
  flavor slots, the cohort forest, int32 scaling).  Rebuilt only when the
  cache's structure generation changes (a CQ/cohort/flavor apply), and
  cached by the solver across cycles.
- ``pack_cycle`` — fills the per-cycle usage [N, F] and workload [W, R]
  tensors against a cached structure.

Quantities are canonical integers scaled per-resource so that everything
fits int32 (TPU-native); per-cycle values that don't divide the cached
scale mark the pack inexact and the solver defers to the host (int64
milli-quanta on TPU is hard part (e) in SURVEY §7).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..cache.snapshot import Snapshot
from ..cache.state import CohortState, CQState
from ..resources import FlavorResource
from ..workload import Info

INT_INF = np.int64(2**62)  # "no limit" sentinel before scaling
I32_MAX = 2**31 - 1
_LIMIT = I32_MAX // 64     # ×64 headroom for sums across the tree


@dataclass
class PackedStructure:
    """Static cluster structure: valid while the cache structure
    generation is unchanged (no CQ/cohort/flavor spec edits)."""
    generation: int
    cq_names: list[str]
    cohort_names: list[str]
    node_count: int                      # N = len(cq_names) + cohorts
    parent: np.ndarray                   # [N] int32, -1 for roots
    depth: int
    fr_index: dict[FlavorResource, int]  # (flavor, resource) -> F
    resource_names: list[str]            # R axis
    r_index: dict[str, int]
    resource_scale: np.ndarray           # [R] int64 divisor per resource
    scale_is_one: bool
    exact_static: bool                   # static tensors scaled losslessly

    subtree_quota: np.ndarray            # [N, F] int32 (scaled)
    guaranteed: np.ndarray               # [N, F] int32
    borrow_cap: np.ndarray               # [N, F] int32
    has_borrow_limit: np.ndarray         # [N, F] bool
    nominal_cq: np.ndarray               # [C, F] int32
    nominal_plus_blimit_cq: np.ndarray   # [C, F] int32 (INT "inf" when unlimited)
    slot_fr: np.ndarray                  # [C, S, R] int32 F-index or -1
    slot_valid: np.ndarray               # [C, S] bool
    slot_count_cq: np.ndarray            # [C] int32: len(rg.flavors)
    cq_can_preempt_borrow: np.ndarray    # [C] bool
    cq_wcb_borrow: np.ndarray            # [C] bool: whenCanBorrow == Borrow
    cq_wcp_preempt: np.ndarray           # [C] bool: whenCanPreempt == Preempt
    fair_weight_milli: np.ndarray        # [N] int32
    forest_of_node: np.ndarray           # [N] int32
    n_forests: int
    cq_index: dict[str, int] = field(default_factory=dict)
    cq_covers_pods: set = field(default_factory=set)


@dataclass
class PackedCycle:
    """A cycle = structure + per-cycle usage and workload tensors."""
    structure: PackedStructure

    usage0: np.ndarray                   # [N, F] int32: usage at snapshot time
    wl_count: int                        # true number of heads (<= W)
    wl_cq: np.ndarray                    # [W] int32 CQ index (-1 pad)
    wl_requests: np.ndarray              # [W, R] int32 total requests (scaled)
    wl_priority: np.ndarray              # [W] int32
    wl_timestamp: np.ndarray             # [W] float64 queue-order timestamp
    wl_keys: list[str] = field(default_factory=list)
    exact: bool = True                   # scaled comparisons are lossless

    # --- structure passthroughs (stable codec surface) ---
    @property
    def cq_names(self): return self.structure.cq_names
    @property
    def node_count(self): return self.structure.node_count
    @property
    def parent(self): return self.structure.parent
    @property
    def depth(self): return self.structure.depth
    @property
    def fr_index(self): return self.structure.fr_index
    @property
    def resource_names(self): return self.structure.resource_names
    @property
    def resource_scale(self): return self.structure.resource_scale
    @property
    def subtree_quota(self): return self.structure.subtree_quota
    @property
    def guaranteed(self): return self.structure.guaranteed
    @property
    def borrow_cap(self): return self.structure.borrow_cap
    @property
    def has_borrow_limit(self): return self.structure.has_borrow_limit
    @property
    def nominal_cq(self): return self.structure.nominal_cq
    @property
    def slot_fr(self): return self.structure.slot_fr
    @property
    def slot_valid(self): return self.structure.slot_valid
    @property
    def cq_can_preempt_borrow(self): return self.structure.cq_can_preempt_borrow
    @property
    def cq_wcb_borrow(self): return self.structure.cq_wcb_borrow
    @property
    def cq_wcp_preempt(self): return self.structure.cq_wcp_preempt
    @property
    def fair_weight_milli(self): return self.structure.fair_weight_milli
    @property
    def forest_of_node(self): return self.structure.forest_of_node
    @property
    def n_forests(self): return self.structure.n_forests


def _bucket(n: int, minimum: int = 8) -> int:
    return max(minimum, 1 << math.ceil(math.log2(max(1, n))))


def scatter_pad(n: int, minimum: int = 8) -> int:
    """Padded row count for a dirty-row scatter onto device-resident
    state: every distinct update size is an XLA compilation, so the
    count is bucketed to powers of two and the tail padded with
    repeated last-row writes (idempotent — same index, same value)."""
    return _bucket(n, minimum=minimum)


def scaled_usage_row(st: PackedStructure, cq_live) -> Optional[np.ndarray]:
    """One CQ's live usage scaled onto the packed flavor-resource axis:
    [F] int32, or None when not exactly representable (unknown
    flavor-resource, a remainder under the scale, or int32 overflow) —
    any None fails the whole burst pack, matching the host path."""
    F = max(1, len(st.fr_index))
    row = np.zeros(F, dtype=np.int32)
    scale = st.resource_scale
    for fr, v in cq_live.resource_node.usage.items():
        fi = st.fr_index.get(fr)
        if fi is None:
            return None
        if st.scale_is_one:
            q_ = int(v)
        else:
            s = int(scale[st.r_index[fr.resource]])
            q_, rem = divmod(int(v), s)
            if rem:
                return None
        if q_ > I32_MAX:
            return None
        row[fi] = q_
    return row


def coarse_bucket(n: int, ladder: tuple[int, ...]) -> int:
    """Smallest ladder rung >= n (last rung if none).  Coarse ladders
    keep the number of DISTINCT compiled shapes small — each new shape
    is a full XLA compilation (~1s on this CPU) that would otherwise
    land inside a scheduling cycle."""
    for rung in ladder:
        if n <= rung:
            return rung
    return ladder[-1]


def _iter_nodes(snapshot: Snapshot):
    """CQs first, then cohorts (stable order)."""
    cq_names = sorted(snapshot.cluster_queues)
    cohorts: list[CohortState] = []
    seen = set()

    def walk(c: CohortState):
        if id(c) in seen:
            return
        seen.add(id(c))
        cohorts.append(c)
        for ch in c.child_cohorts:
            walk(ch)

    for root in snapshot.roots:
        walk(root)
    # cohorts reachable only via CQ parents (defensive)
    for name in cq_names:
        c = snapshot.cluster_queues[name].parent
        while c is not None and id(c) not in seen:
            walk(c)
            c = c.parent
    return cq_names, cohorts


def snapshot_fair_sharing(snapshot: Snapshot) -> bool:
    return bool(getattr(snapshot, "fair_sharing_enabled", False))


def _snapshot_nodes(snapshot: Snapshot, structure: PackedStructure):
    """Resolve the structure's node order against a fresh snapshot, or
    None if the topology changed under us (caller rebuilds)."""
    by_name: dict[str, CohortState] = {}

    def walk(c: CohortState):
        by_name[c.name] = c
        for ch in c.child_cohorts:
            walk(ch)

    for root in snapshot.roots:
        walk(root)
    nodes = []
    for name in structure.cq_names:
        cq = snapshot.cluster_queues.get(name)
        if cq is None:
            return None
        nodes.append(cq)
    for name in structure.cohort_names:
        c = by_name.get(name)
        if c is None:
            return None
        nodes.append(c)
    return nodes


def _choose_scale(max_val: int, gcd_val: int) -> tuple[int, bool]:
    """Pick a per-resource divisor so max_val/scale fits int32 with
    headroom.  Prefer a scale dividing every observed static value (exact);
    fall back to a power of two marked inexact (hard part (e))."""
    if max_val <= _LIMIT:
        return 1, True
    need = -(-max_val // _LIMIT)          # ceil
    p2 = 1
    while p2 < need:
        p2 *= 2
    cand = math.gcd(int(gcd_val), p2 * (1 << 20))  # pow2 component of gcd
    if cand >= need and max_val // cand <= _LIMIT:
        return cand, True
    if gcd_val >= need and max_val // gcd_val <= _LIMIT:
        return int(gcd_val), True
    scale = p2
    while max_val // scale > _LIMIT:
        scale *= 2
    return scale, gcd_val % scale == 0


def pack_structure(snapshot: Snapshot, heads: list[Info] = (),
                   generation: int = -1) -> PackedStructure:
    """Build the static structure tensors from a snapshot.  ``heads``
    (optional) contributes request quantities to the scale choice so a
    one-shot pack stays exact."""
    cq_names, cohorts = _iter_nodes(snapshot)
    cohort_names = [c.name for c in cohorts]
    cq_idx = {n: i for i, n in enumerate(cq_names)}
    C = len(cq_names)
    N = C + len(cohorts)

    nodes: list = [snapshot.cluster_queues[n] for n in cq_names] + cohorts

    # F axis: quota frs ∪ current usage frs
    frs: set[FlavorResource] = set()
    for node in nodes:
        frs.update(node.resource_node.quotas)
        frs.update(node.resource_node.usage)
    fr_list = sorted(frs)
    fr_index = {fr: i for i, fr in enumerate(fr_list)}
    F = max(1, len(fr_list))

    cq_covers_pods = {
        name for name in cq_names
        if any("pods" in rg.covered_resources
               for rg in snapshot.cluster_queues[name].spec.resource_groups)}

    resource_names = sorted({fr.resource for fr in fr_list}
                            | {r for h in heads for psr in h.total_requests
                               for r in psr.requests}
                            | ({"pods"} if cq_covers_pods else set()))
    r_index = {r: i for i, r in enumerate(resource_names)}
    R = max(1, len(resource_names))

    # resource scaling to int32
    max_per_resource = np.zeros(R, dtype=np.int64)
    gcd_per_resource = np.zeros(R, dtype=np.int64)

    def note(r: str, v: int):
        if r in r_index and v < INT_INF:
            i = r_index[r]
            av = abs(int(v))
            max_per_resource[i] = max(max_per_resource[i], av)
            gcd_per_resource[i] = math.gcd(int(gcd_per_resource[i]), av)

    for node in nodes:
        for fr, q in node.resource_node.quotas.items():
            note(fr.resource, q.nominal)
            if q.borrowing_limit is not None:
                note(fr.resource, q.borrowing_limit)
        for fr, v in node.resource_node.subtree_quota.items():
            note(fr.resource, v)
        for fr, v in node.resource_node.usage.items():
            note(fr.resource, v)
    for h in heads:
        for psr in h.total_requests:
            for r, v in psr.requests.items():
                note(r, v)

    scale = np.ones(R, dtype=np.int64)
    exact_static = True
    for i in range(R):
        s, ok = _choose_scale(int(max_per_resource[i]),
                              int(gcd_per_resource[i]))
        scale[i] = s
        exact_static = exact_static and ok
    scale_is_one = bool((scale == 1).all())

    def scaled(r: str, v) -> int:
        if v >= INT_INF:
            return int(_LIMIT)
        s = int(scale[r_index[r]])
        return int(v) // s if v >= 0 else -((-int(v)) // s)

    # node tensors
    subtree = np.zeros((N, F), dtype=np.int32)
    guaranteed = np.zeros((N, F), dtype=np.int32)
    borrow_cap = np.full((N, F), int(_LIMIT), dtype=np.int32)
    has_blim = np.zeros((N, F), dtype=bool)
    parent = np.full(N, -1, dtype=np.int32)
    nominal_cq = np.zeros((C, F), dtype=np.int32)
    nominal_plus_blimit = np.full((C, F), int(_LIMIT), dtype=np.int32)
    fair_weight = np.full(N, 1000, dtype=np.int32)

    cohort_idx = {id(c): C + i for i, c in enumerate(cohorts)}
    for ni, node in enumerate(nodes):
        p = node.parent
        parent[ni] = cohort_idx[id(p)] if p is not None else -1
        fair_weight[ni] = getattr(node, "fair_weight_milli", 1000)
        rn = node.resource_node
        for fr, fi in fr_index.items():
            sq = rn.subtree_quota.get(fr, 0)
            subtree[ni, fi] = scaled(fr.resource, sq)
            guaranteed[ni, fi] = scaled(fr.resource, rn.guaranteed_quota(fr))
            q = rn.quotas.get(fr)
            if ni < C and q is not None:
                nominal_cq[ni, fi] = scaled(fr.resource, q.nominal)
                if q.borrowing_limit is not None:
                    nominal_plus_blimit[ni, fi] = scaled(
                        fr.resource, q.nominal + q.borrowing_limit)
            if q is not None and q.borrowing_limit is not None:
                has_blim[ni, fi] = True
                stored = sq - rn.guaranteed_quota(fr)
                borrow_cap[ni, fi] = scaled(fr.resource,
                                            stored + q.borrowing_limit)

    # depth + forest partition (each parent-pointer root is independent)
    depth = 1
    forest_of_node = np.zeros(N, dtype=np.int32)
    root_forest: dict[int, int] = {}
    for ni in range(N):
        d, p, cur = 1, parent[ni], ni
        while p >= 0:
            d += 1
            cur = p
            p = parent[p]
        depth = max(depth, d)
        forest_of_node[ni] = root_forest.setdefault(cur, len(root_forest))
    n_forests = max(1, len(root_forest))

    # flavor slots per CQ
    S = 1
    for name in cq_names:
        for rg in snapshot.cluster_queues[name].spec.resource_groups:
            S = max(S, len(rg.flavors))
    slot_fr = np.full((C, S, R), -1, dtype=np.int32)
    slot_valid = np.zeros((C, S), dtype=bool)
    slot_count = np.zeros(C, dtype=np.int32)
    cq_can_preempt_borrow = np.zeros(C, dtype=bool)
    cq_wcb_borrow = np.zeros(C, dtype=bool)
    cq_wcp_preempt = np.zeros(C, dtype=bool)
    from ..api.types import (BorrowWithinCohortPolicy,
                             FlavorFungibilityPolicy, ReclaimWithinCohort)
    for ci, name in enumerate(cq_names):
        spec = snapshot.cluster_queues[name].spec
        p = spec.preemption
        cq_can_preempt_borrow[ci] = (
            p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER
            or (snapshot_fair_sharing(snapshot)
                and p.reclaim_within_cohort != ReclaimWithinCohort.NEVER))
        ff = spec.flavor_fungibility
        cq_wcb_borrow[ci] = (
            ff.when_can_borrow == FlavorFungibilityPolicy.BORROW)
        cq_wcp_preempt[ci] = (
            ff.when_can_preempt == FlavorFungibilityPolicy.PREEMPT)
    for ci, name in enumerate(cq_names):
        cq = snapshot.cluster_queues[name]
        for rg in cq.spec.resource_groups:
            slot_count[ci] = max(slot_count[ci], len(rg.flavors))
            for si, fq in enumerate(rg.flavors):
                exists = fq.name in snapshot.resource_flavors
                slot_valid[ci, si] = slot_valid[ci, si] or exists
                for rname in rg.covered_resources:
                    if rname in r_index:
                        fr = FlavorResource(fq.name, rname)
                        if fr in fr_index and exists:
                            slot_fr[ci, si, r_index[rname]] = fr_index[fr]

    return PackedStructure(
        generation=generation, cq_names=cq_names, cohort_names=cohort_names,
        node_count=N, parent=parent, depth=depth, fr_index=fr_index,
        resource_names=resource_names, r_index=r_index,
        resource_scale=scale, scale_is_one=scale_is_one,
        exact_static=exact_static,
        subtree_quota=subtree, guaranteed=guaranteed, borrow_cap=borrow_cap,
        has_borrow_limit=has_blim, nominal_cq=nominal_cq,
        nominal_plus_blimit_cq=nominal_plus_blimit,
        slot_fr=slot_fr, slot_valid=slot_valid, slot_count_cq=slot_count,
        cq_can_preempt_borrow=cq_can_preempt_borrow,
        cq_wcb_borrow=cq_wcb_borrow, cq_wcp_preempt=cq_wcp_preempt,
        fair_weight_milli=fair_weight, forest_of_node=forest_of_node,
        n_forests=n_forests, cq_index=cq_idx, cq_covers_pods=cq_covers_pods,
    )


def pack_cycle(snapshot: Snapshot, heads: list[Info], ordering=None,
               structure: Optional[PackedStructure] = None
               ) -> Optional[PackedCycle]:
    """Fill the per-cycle tensors.  With a cached ``structure`` this is
    O(usage entries + heads); without one the structure is built fresh
    (one-shot codec, used by tests/probes).

    Returns None when the cached structure no longer describes the
    snapshot (new flavor-resource or node appeared) — the caller rebuilds
    and retries."""
    fresh = structure is None
    if fresh:
        structure = pack_structure(snapshot, heads)
    st = structure
    nodes = _snapshot_nodes(snapshot, st)
    if nodes is None:
        return None

    N, F = st.node_count, max(1, len(st.fr_index))
    R = len(st.resource_names)
    scale = st.resource_scale
    exact = st.exact_static

    usage0 = np.zeros((N, F), dtype=np.int32)
    if st.scale_is_one:
        for ni, node in enumerate(nodes):
            for fr, v in node.resource_node.usage.items():
                fi = st.fr_index.get(fr)
                if fi is None:
                    return None
                usage0[ni, fi] = v
    else:
        for ni, node in enumerate(nodes):
            for fr, v in node.resource_node.usage.items():
                fi = st.fr_index.get(fr)
                if fi is None:
                    return None
                s = int(scale[st.r_index[fr.resource]])
                q, rem = divmod(int(v), s)
                if rem:
                    exact = False
                    q += 1  # conservative ceil
                usage0[ni, fi] = q

    W = _bucket(len(heads))
    wl_cq = np.full(W, -1, dtype=np.int32)
    # accumulate in int64: a cached structure's scale was chosen without
    # this cycle's requests, so scaled sums may exceed int32 — that marks
    # the pack inexact (host fallback) instead of wrapping
    wl_requests64 = np.zeros((W, R), dtype=np.int64)
    wl_priority = np.zeros(W, dtype=np.int32)
    wl_timestamp = np.zeros(W, dtype=np.float64)
    wl_keys = []
    for wi, h in enumerate(heads):
        wl_keys.append(h.key)
        wl_cq[wi] = st.cq_index.get(h.cluster_queue, -1)
        covers_pods = h.cluster_queue in st.cq_covers_pods
        for psr in h.total_requests:
            for r, v in psr.requests.items():
                # the implicit "pods" request only participates when the
                # head's CQ covers it (flavorassigner.go:226)
                if r == "pods" and not covers_pods:
                    continue
                ri = st.r_index.get(r)
                if ri is None:
                    return None
                if st.scale_is_one:
                    wl_requests64[wi, ri] += int(v)
                else:
                    s = int(scale[ri])
                    q, rem = divmod(int(v), s)
                    if rem:
                        exact = False
                        q += 1
                    wl_requests64[wi, ri] += q
        wl_priority[wi] = h.obj.priority
        wl_timestamp[wi] = (ordering.queue_order_timestamp(h.obj)
                            if ordering is not None else h.obj.creation_time)
    if wl_requests64.max(initial=0) > _LIMIT:
        exact = False
        np.clip(wl_requests64, None, _LIMIT, out=wl_requests64)
    wl_requests = wl_requests64.astype(np.int32)

    return PackedCycle(
        structure=st, usage0=usage0,
        wl_count=len(heads), wl_cq=wl_cq, wl_requests=wl_requests,
        wl_priority=wl_priority, wl_timestamp=wl_timestamp, wl_keys=wl_keys,
        exact=exact,
    )


# ---------------------------------------------------------------------------
# Dtype tightening of packed planes (host→device transfer compression)
# ---------------------------------------------------------------------------

# Planes the serial burst launch may narrow below int32 when their value
# range permits.  Only *rank/index/request* planes qualify: sentinel
# planes (wl_rank's INF_I32, death0's I32_MAX) and the chained scan-state
# 9-tuple are excluded — a chained window receives the previous window's
# device outputs, so alternating their dtypes would recompile every
# boundary.  Quota planes holding _LIMIT-scaled sums stay int32 too.
TIGHTEN_PLANES = ("wl_req", "wl_cycle_rank", "wl_prio", "wl_uidrank",
                  "parent", "node_level", "nominal_cq", "slot_fr",
                  "forest_of_cq", "members", "cand_rows", "cand_lmem",
                  "self_lmem")

_WIDTH_DT = {1: np.int8, 2: np.int16, 4: np.int32}


class TightenState:
    """Sticky per-plane narrow widths.  Widths only ever widen: a plane
    that once overflowed int16 stays int32 for the solver's lifetime,
    so the jit cache sees at most a couple of dtype signatures per
    plane instead of oscillating (every signature is a compilation)."""
    __slots__ = ("width", "widen_events")

    def __init__(self):
        self.width: dict[str, int] = {}
        self.widen_events = 0


def _needed_width(arr: np.ndarray) -> int:
    if arr.size == 0:
        return 1
    lo, hi = int(arr.min()), int(arr.max())
    if -128 <= lo and hi <= 127:
        return 1
    if -32768 <= lo and hi <= 32767:
        return 2
    return 4


def tighten_arrays(arrays: dict, state: TightenState,
                   stats: dict = None) -> dict:
    """Return a shallow copy of ``arrays`` with the TIGHTEN_PLANES
    narrowed to the smallest sticky width their values fit (range
    measured per call — the assert is the measurement; overflow never
    truncates, it widens).  The input dict is never mutated: plan
    arrays keep their reference int32 dtypes for parity checks and the
    resident scatter path."""
    out = dict(arrays)
    saved = 0
    for name in TIGHTEN_PLANES:
        a = out.get(name)
        if a is None or a.dtype != np.int32:
            continue
        need = _needed_width(a)
        prev = state.width.get(name)
        if prev is not None and need > prev:
            state.widen_events += 1
            if stats is not None:
                stats["pack_tighten_widened"] = (
                    stats.get("pack_tighten_widened", 0) + 1)
        width = max(need, prev or 1)
        state.width[name] = width
        if width < 4:
            out[name] = a.astype(_WIDTH_DT[width])
            saved += a.nbytes - out[name].nbytes
    if stats is not None and saved:
        stats["pack_tighten_bytes_saved"] = (
            stats.get("pack_tighten_bytes_saved", 0) + saved)
    return out
