"""Snapshot → packed device arrays.

The deterministic codec from a cache Snapshot + cycle heads into static-
shaped integer tensors (SURVEY §7 stage 1).  Axes:

- N: quota nodes = ClusterQueues then Cohorts (parent-pointer forest)
- F: distinct (flavor, resource) pairs appearing in any quota
- W: cycle heads, padded to a bucket size (power of two) to bound
  recompilation
- S: flavor slots per resource group (max flavor-list length)
- R: distinct resource names

Quantities are canonical integers scaled per-resource so that everything
fits int32 (TPU-native); the packer asserts exact divisibility and falls
back to ceil-scaling requests (conservative) otherwise.  int64 milli-quanta
on TPU is hard part (e) in SURVEY §7.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..cache.snapshot import Snapshot
from ..cache.state import CohortState, CQState
from ..resources import FlavorResource
from ..workload import Info

INT_INF = np.int64(2**62)  # "no limit" sentinel before scaling
I32_MAX = 2**31 - 1


@dataclass
class PackedCycle:
    # --- static cluster structure ---
    cq_names: list[str]
    node_count: int                      # N = len(cq_names) + cohorts
    parent: np.ndarray                   # [N] int32, -1 for roots
    depth: int                           # max tree depth (levels of parent hops)
    fr_index: dict[FlavorResource, int]  # (flavor, resource) -> F
    resource_names: list[str]            # R axis
    resource_scale: np.ndarray           # [R] int64 divisor per resource

    subtree_quota: np.ndarray            # [N, F] int32 (scaled)
    guaranteed: np.ndarray               # [N, F] int32
    borrow_cap: np.ndarray               # [N, F] int32: stored_in_parent + blimit (clipped)
    has_borrow_limit: np.ndarray         # [N, F] bool
    usage0: np.ndarray                   # [N, F] int32: usage at snapshot time

    # flavor machinery: per CQ, per resource, ordered flavor slots -> F index
    slot_fr: np.ndarray                  # [C, S, R] int32 F-index or -1
    slot_valid: np.ndarray               # [C, S] bool (flavor exists & allowed)
    nominal_cq: np.ndarray               # [C, F] int32 (for preempt classification)
    cq_can_preempt_borrow: np.ndarray    # [C] bool: canPreemptWhileBorrowing

    # --- per-cycle workloads ---
    wl_count: int                        # true number of heads (<= W)
    wl_cq: np.ndarray                    # [W] int32 CQ index (-1 pad)
    wl_requests: np.ndarray              # [W, R] int32 total requests (scaled)
    wl_priority: np.ndarray              # [W] int32
    wl_timestamp: np.ndarray             # [W] float64 queue-order timestamp
    wl_keys: list[str] = field(default_factory=list)
    exact: bool = True                   # scaled comparisons are lossless
    fair_weight_milli: np.ndarray = None  # [N] int32 (fair sharing)
    forest_of_node: np.ndarray = None    # [N] int32 root-forest id
    n_forests: int = 0


def _bucket(n: int, minimum: int = 8) -> int:
    return max(minimum, 1 << math.ceil(math.log2(max(1, n))))


def _iter_nodes(snapshot: Snapshot):
    """CQs first, then cohorts (stable order)."""
    cq_names = sorted(snapshot.cluster_queues)
    cohorts: list[CohortState] = []
    seen = set()

    def walk(c: CohortState):
        if id(c) in seen:
            return
        seen.add(id(c))
        cohorts.append(c)
        for ch in c.child_cohorts:
            walk(ch)

    for root in snapshot.roots:
        walk(root)
    # cohorts reachable only via CQ parents (defensive)
    for name in cq_names:
        c = snapshot.cluster_queues[name].parent
        while c is not None and id(c) not in seen:
            walk(c)
            c = c.parent
    return cq_names, cohorts


def snapshot_fair_sharing(snapshot: Snapshot) -> bool:
    return bool(getattr(snapshot, "fair_sharing_enabled", False))


def pack_cycle(snapshot: Snapshot, heads: list[Info],
               ordering=None) -> PackedCycle:
    cq_names, cohorts = _iter_nodes(snapshot)
    cq_idx = {n: i for i, n in enumerate(cq_names)}
    cohort_idx = {id(c): len(cq_names) + i for i, c in enumerate(cohorts)}
    C = len(cq_names)
    N = C + len(cohorts)

    # F axis
    frs: set[FlavorResource] = set()
    for name in cq_names:
        cq = snapshot.cluster_queues[name]
        frs.update(cq.resource_node.quotas)
        frs.update(cq.resource_node.usage)
    for c in cohorts:
        frs.update(c.resource_node.quotas)
        frs.update(c.resource_node.usage)
    fr_list = sorted(frs)
    fr_index = {fr: i for i, fr in enumerate(fr_list)}
    F = max(1, len(fr_list))

    # CQs whose resource groups cover the implicit "pods" resource get
    # requests[pods] = pod count injected (flavorassigner.go:226).
    cq_covers_pods = {
        name for name in cq_names
        if any("pods" in rg.covered_resources
               for rg in snapshot.cluster_queues[name].spec.resource_groups)}

    resource_names = sorted({fr.resource for fr in fr_list}
                            | {r for h in heads for psr in h.total_requests
                               for r in psr.requests}
                            | ({"pods"} if cq_covers_pods else set()))
    r_index = {r: i for i, r in enumerate(resource_names)}
    R = max(1, len(resource_names))

    # resource scaling to int32
    max_per_resource = np.zeros(R, dtype=np.int64)
    gcd_per_resource = np.zeros(R, dtype=np.int64)

    def note(r: str, v: int):
        if r in r_index and v < INT_INF:
            i = r_index[r]
            av = abs(int(v))
            max_per_resource[i] = max(max_per_resource[i], av)
            gcd_per_resource[i] = math.gcd(int(gcd_per_resource[i]), av)

    nodes: list = [snapshot.cluster_queues[n] for n in cq_names] + cohorts
    for node in nodes:
        for fr, q in node.resource_node.quotas.items():
            note(fr.resource, q.nominal)
            if q.borrowing_limit is not None:
                note(fr.resource, q.borrowing_limit)
        for fr, v in node.resource_node.subtree_quota.items():
            note(fr.resource, v)
        for fr, v in node.resource_node.usage.items():
            note(fr.resource, v)
    for h in heads:
        for psr in h.total_requests:
            for r, v in psr.requests.items():
                note(r, v)

    # Exact scaling: divide by the GCD of every observed quantity, so
    # scaled comparisons are bit-identical to the host's (hard part (e),
    # SURVEY §7).  If even GCD scaling can't fit int32 (with ×64 headroom
    # for sums across the tree), fall back to lossy power-of-two scaling
    # and mark the pack inexact — the solver then defers to the host.
    scale = np.ones(R, dtype=np.int64)
    exact = True
    limit = I32_MAX // 64
    for i in range(R):
        if max_per_resource[i] <= limit:
            continue
        scale[i] = max(1, int(gcd_per_resource[i]))
        while max_per_resource[i] // scale[i] > limit:
            scale[i] *= 2
            exact = False

    def scaled(r: str, v) -> int:
        if v >= INT_INF:
            return int(I32_MAX // 64)
        s = int(scale[r_index[r]])
        return int(v) // s if v >= 0 else -((-int(v)) // s)

    def scaled_ceil(r: str, v) -> int:
        if v >= INT_INF:
            return int(I32_MAX // 64)
        s = int(scale[r_index[r]])
        return -((-int(v)) // s)

    # node tensors
    subtree = np.zeros((N, F), dtype=np.int32)
    guaranteed = np.zeros((N, F), dtype=np.int32)
    borrow_cap = np.full((N, F), int(I32_MAX // 64), dtype=np.int32)
    has_blim = np.zeros((N, F), dtype=bool)
    usage0 = np.zeros((N, F), dtype=np.int32)
    parent = np.full(N, -1, dtype=np.int32)
    nominal_cq = np.zeros((C, F), dtype=np.int32)

    fair_weight = np.full(N, 1000, dtype=np.int32)
    for ni, node in enumerate(nodes):
        p = node.parent
        parent[ni] = cohort_idx[id(p)] if p is not None else -1
        fair_weight[ni] = getattr(node, "fair_weight_milli", 1000)
        rn = node.resource_node
        for fr, fi in fr_index.items():
            sq = rn.subtree_quota.get(fr, 0)
            subtree[ni, fi] = scaled(fr.resource, sq)
            guaranteed[ni, fi] = scaled(fr.resource, rn.guaranteed_quota(fr))
            usage0[ni, fi] = scaled_ceil(fr.resource, rn.usage.get(fr, 0))
            q = rn.quotas.get(fr)
            if ni < C and q is not None:
                nominal_cq[ni, fi] = scaled(fr.resource, q.nominal)
            if q is not None and q.borrowing_limit is not None:
                has_blim[ni, fi] = True
                stored = sq - rn.guaranteed_quota(fr)
                borrow_cap[ni, fi] = scaled(fr.resource,
                                            stored + q.borrowing_limit)

    # depth + forest partition (each parent-pointer root is independent)
    depth = 1
    forest_of_node = np.zeros(N, dtype=np.int32)
    root_forest: dict[int, int] = {}
    for ni in range(N):
        d, p, cur = 1, parent[ni], ni
        while p >= 0:
            d += 1
            cur = p
            p = parent[p]
        depth = max(depth, d)
        forest_of_node[ni] = root_forest.setdefault(cur, len(root_forest))
    n_forests = max(1, len(root_forest))

    # flavor slots per CQ
    S = 1
    for name in cq_names:
        for rg in snapshot.cluster_queues[name].spec.resource_groups:
            S = max(S, len(rg.flavors))
    slot_fr = np.full((C, S, R), -1, dtype=np.int32)
    slot_valid = np.zeros((C, S), dtype=bool)
    cq_can_preempt_borrow = np.zeros(C, dtype=bool)
    from ..api.types import BorrowWithinCohortPolicy, ReclaimWithinCohort
    for ci, name in enumerate(cq_names):
        p = snapshot.cluster_queues[name].spec.preemption
        cq_can_preempt_borrow[ci] = (
            p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER
            or (snapshot_fair_sharing(snapshot)
                and p.reclaim_within_cohort != ReclaimWithinCohort.NEVER))
    for ci, name in enumerate(cq_names):
        cq = snapshot.cluster_queues[name]
        for rg in cq.spec.resource_groups:
            for si, fq in enumerate(rg.flavors):
                exists = fq.name in snapshot.resource_flavors
                slot_valid[ci, si] = slot_valid[ci, si] or exists
                for rname in rg.covered_resources:
                    if rname in r_index:
                        fr = FlavorResource(fq.name, rname)
                        if fr in fr_index and exists:
                            slot_fr[ci, si, r_index[rname]] = fr_index[fr]

    # workloads
    W = _bucket(len(heads))
    wl_cq = np.full(W, -1, dtype=np.int32)
    wl_requests = np.zeros((W, R), dtype=np.int32)
    wl_priority = np.zeros(W, dtype=np.int32)
    wl_timestamp = np.zeros(W, dtype=np.float64)
    wl_keys = []
    for wi, h in enumerate(heads):
        wl_keys.append(h.key)
        wl_cq[wi] = cq_idx.get(h.cluster_queue, -1)
        for psr in h.total_requests:
            for r, v in psr.requests.items():
                # the implicit "pods" request only participates when the
                # head's CQ covers it (flavorassigner.go:226)
                if r == "pods" and h.cluster_queue not in cq_covers_pods:
                    continue
                wl_requests[wi, r_index[r]] += scaled_ceil(r, v)
        wl_priority[wi] = h.obj.priority
        wl_timestamp[wi] = (ordering.queue_order_timestamp(h.obj)
                            if ordering is not None else h.obj.creation_time)

    return PackedCycle(
        cq_names=cq_names, node_count=N, parent=parent, depth=depth,
        fr_index=fr_index, resource_names=resource_names,
        resource_scale=scale,
        subtree_quota=subtree, guaranteed=guaranteed,
        borrow_cap=borrow_cap, has_borrow_limit=has_blim, usage0=usage0,
        slot_fr=slot_fr, slot_valid=slot_valid, nominal_cq=nominal_cq,
        cq_can_preempt_borrow=cq_can_preempt_borrow,
        wl_count=len(heads), wl_cq=wl_cq, wl_requests=wl_requests,
        wl_priority=wl_priority, wl_timestamp=wl_timestamp, wl_keys=wl_keys,
        exact=exact, fair_weight_milli=fair_weight,
        forest_of_node=forest_of_node, n_forests=n_forests,
    )
