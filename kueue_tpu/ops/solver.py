"""Host wrapper for the device cycle solver.

Packs a (snapshot, heads) pair, invokes the jitted batched cycle
(kueue_tpu.ops.cycle), and converts results back into Assignment objects
compatible with the scalar scheduler path.  Falls back (returns None) when
the cycle needs semantics not yet on device: preemption candidates, TAS
requests, fair sharing, non-default fungibility, multi-resource-group CQs,
or admission-check strategies — the host path then runs, keeping decisions
bit-identical.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..api.types import FlavorFungibility, FlavorFungibilityPolicy
from ..cache.snapshot import Snapshot
from ..workload import Info, Ordering
from ..scheduler.flavorassigner import (
    Assignment,
    FlavorAssignmentDecision,
    Mode,
    PodSetAssignmentResult,
)
from ..resources import FlavorResource, Requests
from .packing import pack_cycle
from .cycle import solve_cycle

_DEFAULT_FF = FlavorFungibility()


class CycleSolver:
    """Batched solver for pure-Fit cycles.

    backend="device" runs the jitted JAX kernel (TPU/CPU via XLA);
    backend="native" runs the C++ core (kueue_tpu/native) — identical
    decisions either way."""

    def __init__(self, ordering: Ordering | None = None,
                 backend: str = "device"):
        self.ordering = ordering or Ordering()
        self.backend = backend
        self.stats = {"device_cycles": 0, "host_fallbacks": 0}

    # -- eligibility ---------------------------------------------------

    def _supported(self, snapshot: Snapshot, heads: list[Info]) -> bool:
        for h in heads:
            if len(h.obj.pod_sets) > 1:
                # the host can split flavors across pod sets; the device
                # currently solves the summed request against one flavor
                return False
            last = h.last_assignment
            if last is not None and last.pending_flavors:
                # effective fungibility resume state: the host would start
                # the flavor walk mid-list (flavorassigner.go:359-366);
                # the device always scans from slot 0
                cq = snapshot.cq(h.cluster_queue)
                if (cq is not None and
                        last.cluster_queue_generation >= cq.allocatable_generation):
                    return False
            for ps in h.obj.pod_sets:
                if ps.topology_request is not None:
                    return False
                if ps.min_count is not None:
                    return False
                if ps.node_selector or ps.required_node_affinity or ps.tolerations:
                    return False  # affinity/taint matching stays on host
        for name, cq in snapshot.cluster_queues.items():
            if len(cq.spec.resource_groups) > 1:
                return False
            ff = cq.spec.flavor_fungibility
            if (ff.when_can_borrow != _DEFAULT_FF.when_can_borrow
                    or ff.when_can_preempt != _DEFAULT_FF.when_can_preempt):
                return False
            for rg in cq.spec.resource_groups:
                for fq in rg.flavors:
                    flavor = snapshot.resource_flavors.get(fq.name)
                    if flavor is None:
                        return False
                    if flavor.node_taints or flavor.topology_name:
                        return False
        return True

    # -- solve ---------------------------------------------------------

    def try_solve(self, snapshot: Snapshot, heads: list[Info]
                  ) -> Optional[dict[str, Assignment]]:
        """Returns {workload_key: Fit Assignment} for admitted heads, or
        None when the host path must run."""
        if not heads or not self._supported(snapshot, heads):
            self.stats["host_fallbacks"] += 1
            return None
        packed = pack_cycle(snapshot, heads, self.ordering)
        if not packed.exact:
            # lossy int32 scaling could deny fits the host grants
            self.stats["host_fallbacks"] += 1
            return None
        if self.backend == "native":
            from .. import native
            fit_slot0, borrows0, preempt_possible = native.classify_cycle(
                packed)
        else:
            (_admitted, _slots, _borrows, preempt_possible,
             fit_slot0, borrows0) = solve_cycle(
                packed.usage0, packed.subtree_quota, packed.guaranteed,
                packed.borrow_cap, packed.has_borrow_limit, packed.parent,
                packed.nominal_cq, packed.slot_fr, packed.slot_valid,
                packed.cq_can_preempt_borrow,
                packed.wl_cq, packed.wl_requests, packed.wl_priority,
                packed.wl_timestamp, depth=packed.depth, run_scan=False)
            fit_slot0 = np.asarray(fit_slot0)
            borrows0 = np.asarray(borrows0)
            preempt_possible = np.asarray(preempt_possible)
        n = packed.wl_count
        if preempt_possible[:n].any():
            # preemption semantics stay on host for now
            self.stats["host_fallbacks"] += 1
            return None
        self.stats["device_cycles"] += 1

        out: dict[str, Assignment] = {}
        for wi in range(n):
            if fit_slot0[wi] < 0:
                continue
            h = heads[wi]
            cq = snapshot.cq(h.cluster_queue)
            rg = cq.spec.resource_groups[0]
            covers_pods = "pods" in rg.covered_resources
            flavor_name = rg.flavors[int(fit_slot0[wi])].name
            assignment = Assignment()
            assignment.borrowing = bool(borrows0[wi])
            assignment.last_state.cluster_queue_generation = cq.allocatable_generation
            for psr in h.total_requests:
                # mirror the host's implicit "pods" handling
                # (flavorassigner.go:226 / _assign_flavors)
                reqs = dict(psr.requests)
                if covers_pods:
                    reqs["pods"] = psr.count
                else:
                    reqs.pop("pods", None)
                ps_res = PodSetAssignmentResult(
                    name=psr.name, requests=Requests(reqs),
                    count=psr.count)
                for res in reqs:
                    ps_res.flavors[res] = FlavorAssignmentDecision(
                        name=flavor_name, mode=Mode.FIT,
                        borrow=bool(borrows0[wi]))
                    fr = FlavorResource(flavor_name, res)
                    assignment.usage[fr] = (assignment.usage.get(fr, 0)
                                            + reqs[res])
                assignment.pod_sets.append(ps_res)
            out[h.key] = assignment
        return out
