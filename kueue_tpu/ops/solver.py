"""Host wrapper for the device cycle solver.

Per cycle the solver:

1. packs (snapshot, heads) against a CACHED ``PackedStructure`` — the
   static cluster tensors are rebuilt only when the cache structure
   generation changes, so the per-cycle cost is O(usage + heads);
2. runs the vectorized nominate (``ops.cycle.classify_np``) on the host
   for heads whose shape the batched math covers (single resource group,
   single PodSet, plain flavors, default fungibility); the remaining
   heads are marked SCALAR — the scheduler runs the real host
   FlavorAssigner walk for those few and attaches the resulting
   assignment, so multi-resource-group CQs, multi-PodSet workloads,
   taints/affinity, fungibility policies, resume state, partial
   admission, and TAS all stay inside a device-decided cycle;
3. dispatches the sequential admit scan (``ops.cycle.admit_scan``) as ONE
   jitted program, routed to the accelerator for large cycles and to the
   XLA CPU backend for small ones (a tunneled-TPU round trip costs ~100 ms
   flat, so small cycles can't amortize it — the kernel is identical on
   both backends).  The scan consumes per-head (flavor-resource, amount)
   decision pairs — the assignment.Usage map the reference admit loop
   re-checks (scheduler.go:372) — so HOW a head was classified (vector or
   scalar) is invisible to the kernel.

Fair-sharing cycles use ``classify`` for nominate but keep the host
admit loop (the tournament's within-cycle ordering is data-dependent on
DRS — see Scheduler._fair_sharing_iterator).  The solver falls back
entirely (returns None) for inexact int32 scaling, unrepresentable packs
(a flavor-resource or node unknown to the cached structure after one
rebuild), and scalar assignments whose usage can't be encoded exactly —
the host path then runs, keeping decisions bit-identical.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..api.types import FlavorFungibility, FlavorFungibilityPolicy
from ..features import env_value
from ..cache.snapshot import Snapshot
from ..workload import Info, Ordering
from ..scheduler.flavorassigner import (
    Assignment,
    AssignmentClusterQueueState,
    FlavorAssignmentDecision,
    Mode,
    PodSetAssignmentResult,
)
from ..resources import FlavorResource, Requests
from .packing import (PackedCycle, PackedStructure, _bucket, coarse_bucket,
                      pack_cycle, pack_structure)
from .cycle import (admit_scan, admit_scan_forests, admit_scan_preempt,
                    classify_np, cycle_order_np, decision_pairs_from_slots)

# A flat admit scan is one lax.scan step per head; the forest-parallel
# variant processes one head per cohort forest per step.  Below this head
# count the flat scan's lower per-step cost wins.
_FOREST_MIN_HEADS = 64

_DEFAULT_FF = FlavorFungibility()

# coarse shape ladders for the preempt scan's target tensors: each
# distinct (T, MT) is one XLA compilation (see packing.coarse_bucket)
T_LADDER = (64, 512, 4096)
MT_LADDER = (4, 16)


@dataclass
class ClassifiedCycle:
    """Phase-1 output: fixed per-head assignments for one cycle."""
    packed: PackedCycle
    heads: list[Info]
    snapshot: Snapshot
    fit_slot0: np.ndarray        # [W] int32, -1 = no fit
    borrows0: np.ndarray         # [W] bool
    preempt0: np.ndarray         # [W] bool (no fit, preempt-capable)
    preempt_slot0: np.ndarray    # [W] int32
    preempt_borrows0: np.ndarray  # [W] bool
    preempt_res_fit: np.ndarray  # [W, R] bool
    preempt_slot_count: np.ndarray = None  # [W] int32 preempt-capable slots
    preempt_stopped0: np.ndarray = None    # [W] bool: the fungibility walk
                                           # policy-stopped ON the preempt
                                           # slot (choice is final — no
                                           # reclaim-oracle dependence)
    # heads the vectorized math can't classify: the scheduler runs the
    # host FlavorAssigner walk for these and attaches the assignment
    scalar_mask: np.ndarray = None         # [W] bool
    host_assignments: dict = None          # {wi: Assignment}
    host_pairs: dict = None                # {wi: [(F-index, amount)]}

    @property
    def n(self) -> int:
        return self.packed.wl_count


@dataclass
class PackedTargets:
    """Per-cycle preemption-target tensors for the admit scan."""
    preempt_mask: np.ndarray     # [W] bool
    tgt_mat: np.ndarray          # [W, MT] int32 universe indices, -1 pad
    tu_cq: np.ndarray            # [T] int32 node index
    tu_delta: np.ndarray         # [T, F] int32 scaled usage


@dataclass
class DeviceCycleFinal:
    """Full-cycle device decisions, in cycle order."""
    order: np.ndarray            # [n] head indices, cycle order
    admitted: np.ndarray         # [n] bool (head order)
    reserve_mask: np.ndarray     # [n] bool (head order)
    preempting: np.ndarray = None    # [n] bool: issued preemptions
    overlap_skip: np.ndarray = None  # [n] bool: overlapping targets


@dataclass
class DispatchHandle:
    """An in-flight admit scan: the dispatch has been issued (or decided
    unnecessary) and the host is free to do per-head work while the device
    executes; ``CycleSolver.fetch`` blocks for the decisions."""
    order: np.ndarray
    rmask: np.ndarray            # [W] bool
    n: int
    pending: object = None       # jax array(s) still on device, or None
    admitted: Optional[np.ndarray] = None  # resolved decisions [W]
    preempting: Optional[np.ndarray] = None
    overlap_skip: Optional[np.ndarray] = None
    fit_mask: Optional[np.ndarray] = None  # [W] bool: vector + scalar fits
    route: str = ""   # "accel" | "cpu" | "native" | "no_fit" | "singleton"


# Calibration sidecar schema: bump whenever the table's key layout or
# the measurement protocol changes, so a sidecar written by an older
# build is rejected (re-measured) instead of mis-routing cycles.
CALIB_SCHEMA = 2


class CycleSolver:
    """Batched solver for the admission cycle.

    backend="auto" routes the admit scan to the accelerator when the
    cycle is big enough to amortize the dispatch round-trip, else to the
    XLA CPU backend; "cpu"/"accel" force a backend; "native" runs both
    the classify AND the admit loop in the C++ core (kueue_tpu/native;
    preempt-target cycles keep the jitted scan).  Identical decisions on
    every backend."""

    def __init__(self, ordering: Ordering | None = None,
                 backend: str = "auto",
                 accel_min_heads: int | None = None):
        from ..compilecache import enable as _enable_compile_cache
        _enable_compile_cache()
        self.ordering = ordering or Ordering()
        if backend == "device":      # legacy alias
            backend = "auto"
        self.backend = backend
        if accel_min_heads is None:
            accel_min_heads = int(
                env_value("KUEUE_TPU_ACCEL_MIN_HEADS"))
        self.accel_min_heads = accel_min_heads
        # Disjoint cycle counters: every cycle with heads lands in exactly
        # one of full/classify/host (bench derives shares from these).
        self.stats = {
            "full_cycles": 0,         # fully device-decided cycles
            "fs_full_cycles": 0,      # fair-sharing cycles decided in-scan
            "fs_noop_skips": 0,       # FS cycles with no fit head: the
                                      # tournament dispatch was skipped
            "fs_noop_reuses": 0,      # no-op FS cycles whose per-head
                                      # walks were fingerprint-reused
            "classify_cycles": 0,     # device nominate + host admit loop
            "host_cycles": 0,         # pure host fallback (classify=None)
            "reserve_entries": 0,
            # dispatch routing within full cycles (also disjoint):
            "accel_dispatches": 0,    # admit scan ran on the accelerator
            "cpu_dispatches": 0,      # admit scan ran on the XLA CPU backend
            "native_dispatches": 0,   # admit loop ran in the C++ core
            "native_calibration_failures": 0,
            "skipped_dispatches": 0,  # no fit head -> scan provably no-op
            "singleton_dispatches": 0,  # <=1 entry/forest -> no contention
            "structure_rebuilds": 0,
            "calibration_loaded": 0,  # router table reloaded from disk
            "scalar_heads": 0,        # heads classified by the host walk
            # flavor-walk telemetry (heterogeneous fast path):
            "scalar_reasons": {},     # {reason: count} for scalar heads
            "resume_heads": 0,        # heads entering the walk mid-list
            "walk_stop_heads": 0,     # heads whose walk policy-stopped
            "native_ff_fallbacks": 0,  # native classify skipped: the C++
                                       # core is first-fit-only and the
                                       # cycle has non-default fungibility
                                       # or a resumed head
        }
        self._structure: Optional[PackedStructure] = None
        self._potential0 = None
        # optional jax.sharding.Mesh: when set, admit scans dispatch as
        # mesh-sharded programs (parallel/sharded.py admit_scan_fns)
        self.mesh = None
        self._sharded_fns: dict = {}
        self._devices_resolved = False
        self._cpu_dev = None
        self._accel_dev = None
        # measured per-backend admit-scan wall times, filled by warmup:
        # {("cpu"|"accel", kernel, bucket): seconds}
        self.calibration: dict[tuple, float] = {}
        self.rtt_s: Optional[float] = None  # measured accel round-trip

    # -- device routing ------------------------------------------------

    def _resolve_devices(self):
        if self._devices_resolved:
            return
        import jax
        try:
            self._cpu_dev = jax.devices("cpu")[0]
            default = jax.devices()[0]
            self._accel_dev = default if default.platform != "cpu" else None
        except RuntimeError:
            # a registered accelerator plugin that can't initialize (e.g.
            # no tunnel) must not take the CPU path down with it
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            self._cpu_dev = jax.devices("cpu")[0]
            self._accel_dev = None
        self._devices_resolved = True

    def set_mesh(self, mesh) -> None:
        """Route production admit scans through mesh-sharded programs
        (verdict r3 item 5: the sharded cycle is the production path,
        not a dryrun-only artifact)."""
        self.mesh = mesh
        self._sharded_fns = {}
        self.stats.setdefault("sharded_dispatches", 0)
        self.stats.setdefault("sharded_preempt_dispatches", 0)
        self.stats.setdefault("sharded_fs_dispatches", 0)

    def _sharded_for(self, depth: int):
        fns = self._sharded_fns.get(depth)
        if fns is None:
            from ..parallel.sharded import admit_scan_fns
            fns = admit_scan_fns(self.mesh, depth)
            self._sharded_fns[depth] = fns
        return fns

    @staticmethod
    def _pad_rows(a, n_new, fill):
        a = np.asarray(a)
        if a.shape[0] == n_new:
            return a
        pad = np.full((n_new - a.shape[0],) + a.shape[1:], fill,
                      dtype=a.dtype)
        return np.concatenate([a, pad], axis=0)

    def _mesh_pad(self, args, order, st, pmask=None, pre_fr=None,
                  pre_amt=None, tgt_mat=None, forest_of_node=None):
        """Pad the sharded axes to mesh-divisible sizes.

        GSPMD requires dim0 of a tensor sharded over an axis to divide
        the axis size; real clusters rarely oblige (e.g. 35 quota nodes
        on a cq=2 mesh).  Padded nodes are inert (zero quota, parent -1,
        never referenced by a head); padded heads are invalid
        (wl_cq=-1, all masks false) and fetch slices decisions to the
        real head count.  The structure-static tensors (args[1..7] and
        forest_of_node) are padded once per (structure, mesh) and
        cached on the structure; only the per-cycle tensors pay the
        concatenate each dispatch."""
        mesh_cq = self.mesh.shape["cq"]
        mesh_wl = self.mesh.shape["wl"]

        def up(n, m):
            return -(-n // m) * m

        N = args[0].shape[0]
        C = args[6].shape[0]
        W = args[8].shape[0]
        Np, Cp, Wp = up(N, mesh_cq), up(C, mesh_cq), up(W, mesh_wl)
        if (Np, Cp, Wp) == (N, C, W):
            return (args, order, pmask, pre_fr, pre_amt, tgt_mat,
                    forest_of_node)

        rows = self._pad_rows
        key = (mesh_wl, mesh_cq)
        cached = getattr(st, "_mesh_pad_statics", None)
        if cached is None or cached[0] != key:
            statics = (
                rows(args[1], Np, 0), rows(args[2], Np, 0),
                rows(args[3], Np, 0), rows(args[4], Np, False),
                rows(args[5], Np, -1),
                rows(args[6], Cp, 0), rows(args[7], Cp, 0),
                rows(st.forest_of_node, Np, 0))
            st._mesh_pad_statics = cached = (key, statics)
        statics = cached[1]
        args = (
            (rows(args[0], Np, 0),) + statics[:7]
            + (rows(args[8], Wp, -1), rows(args[9], Wp, -1),
               rows(args[10], Wp, 0), rows(args[11], Wp, False),
               rows(args[12], Wp, -1), rows(args[13], Wp, 0),
               rows(args[14], Wp, False), rows(args[15], Wp, False)))
        order = np.concatenate(
            [np.asarray(order),
             np.arange(W, Wp, dtype=np.asarray(order).dtype)])
        if pmask is not None:
            pmask = rows(pmask, Wp, False)
        if pre_fr is not None:
            pre_fr = rows(pre_fr, Wp, -1)
        if pre_amt is not None:
            pre_amt = rows(pre_amt, Wp, 0)
        if tgt_mat is not None:
            tgt_mat = rows(tgt_mat, Wp, -1)
        if forest_of_node is not None:
            forest_of_node = statics[7]
        return args, order, pmask, pre_fr, pre_amt, tgt_mat, forest_of_node

    def _pick_device(self, n_heads: int):
        self._resolve_devices()
        if self.backend in ("cpu", "native"):
            return self._cpu_dev
        if self.backend == "accel":
            return self._accel_dev or self._cpu_dev
        # auto without calibration: a tunneled-accelerator round trip can
        # be ~100 ms flat; only big cycles amortize it
        if self._accel_dev is not None and n_heads >= self.accel_min_heads:
            return self._accel_dev
        return self._cpu_dev

    def _route_device(self, kernel: str, W: int, mfw: Optional[int]):
        """Pick the backend for one scan dispatch.

        With warmup calibration the choice is MEASURED: the backend whose
        steady-state (dispatch + readback) wall time for this (kernel,
        bucket) was lower.  Co-located accelerators (sub-ms dispatch) win
        everything; a tunneled chip (~100 ms RTT) wins only when the scan
        compute itself exceeds the tunnel latency.  Falls back to the
        accel_min_heads heuristic when uncalibrated."""
        self._resolve_devices()
        if self.backend in ("cpu", "native"):
            return self._cpu_dev
        if self.backend == "accel":
            return self._accel_dev or self._cpu_dev
        if self._accel_dev is None:
            return self._cpu_dev
        key_len = mfw if mfw is not None else W
        t_cpu = self.calibration.get(("cpu", kernel, W, key_len))
        t_acc = self.calibration.get(("accel", kernel, W, key_len))
        if t_cpu is not None and t_acc is not None:
            return self._accel_dev if t_acc < t_cpu else self._cpu_dev
        return self._pick_device(W)

    def warmup(self, snapshot: Snapshot, max_heads: int) -> None:
        """One-time setup outside the hot loop: resolve backends (a
        tunneled TPU client can take tens of seconds to connect), compile
        the admit scan for every head-count bucket up to ``max_heads`` on
        BOTH backends, and record each combination's steady-state wall
        time — the router dispatches each cycle to whichever backend
        measured faster.  Shapes only — no scheduling state is touched."""
        import time as _time
        import jax
        from .packing import _bucket
        self._resolve_devices()
        if self._accel_dev is not None:
            # measured accel round trip: tiny transfer + readback
            one = np.zeros(8, np.int32)
            with jax.default_device(self._accel_dev):
                f = jax.jit(lambda x: x + 1)
                jax.device_get(f(one))
                t0 = _time.perf_counter()
                jax.device_get(f(one))
                self.rtt_s = _time.perf_counter() - t0
        st = self._structure_for(snapshot, [])
        N, F = st.subtree_quota.shape
        C, S, R = st.slot_fr.shape
        # a persisted calibration for this (machine, backend, structure
        # shape) short-circuits the whole measurement + eager-compile
        # pass — a second cold process reaches its first cycle in
        # seconds, with kernels lazily reloaded from the persistent
        # XLA cache on first use (verdict r4 item 5: warmup <20s cold)
        from .. import compilecache
        import hashlib
        accel_kind = (getattr(self._accel_dev, "device_kind", "none")
                      if self._accel_dev is not None else "none")
        fp_src = repr((jax.__version__, accel_kind, self.backend,
                       N, F, C, S, R, st.depth, st.n_forests,
                       _bucket(max_heads)))
        fp = hashlib.sha1(fp_src.encode()).hexdigest()[:16]
        calib_name = f"calibration-{fp}.json"
        loaded = compilecache.load_json(calib_name)
        if loaded is not None and (
                loaded.get("schema") != CALIB_SCHEMA
                or loaded.get("fingerprint") != fp_src):
            # a sidecar from another build (or a fingerprint-hash
            # collision) would route cycles by numbers measured in a
            # different world: reject it and re-measure
            self.stats["calibration_rejected"] = 1
            loaded = None
        measure = loaded is None
        if not measure:
            self.calibration.update(
                {tuple(k): v for k, v in loaded.get("calibration", [])})
            self.stats["calibration_loaded"] = 1
            # do NOT return: the shape walk below still runs with
            # measure=False so every hot kernel shape is eagerly
            # compiled (one rep, timings discarded) — an evicted XLA
            # cache entry must cost warmup seconds, never a live cycle
        W = 8
        buckets = []
        while True:
            buckets.append(W)
            if W >= _bucket(max_heads):
                break
            W *= 2
        for W in buckets:
            args = (
                np.zeros((N, F), np.int32), st.subtree_quota, st.guaranteed,
                st.borrow_cap, st.has_borrow_limit, st.parent,
                st.nominal_cq, st.nominal_plus_blimit_cq,
                np.full(W, -1, np.int32),
                np.full((W, R), -1, np.int32), np.zeros((W, R), np.int32),
                np.zeros(W, bool),
                np.full((W, R), -1, np.int32), np.zeros((W, R), np.int32),
                np.zeros(W, bool), np.zeros(W, bool),
                np.arange(W, dtype=np.int32))
            devs = [self._cpu_dev]
            if (self._accel_dev is not None
                    and self.backend in ("auto", "accel")):
                devs.append(self._accel_dev)
            # forest scan lengths for this bucket: 4 .. bucket(max CQs
            # per forest); None when forest decomposition doesn't apply
            mfw_ladder = None
            if self._forests_apply(W, st.n_forests):
                per_forest = np.bincount(
                    st.forest_of_node[:len(st.cq_names)],
                    minlength=st.n_forests)
                top = _bucket(int(per_forest.max()), minimum=4)
                mfw_ladder, mfw = [], 4
                while True:
                    mfw_ladder.append(mfw)
                    if mfw >= top:
                        break
                    mfw *= 2
            for dev in devs:
                # repeat dispatch+readback: the first executions through a
                # tunneled accelerator are several times slower than
                # steady state (transport warm-up), and the readback path
                # is distinct from block_until_ready; the LAST rep's time
                # is the calibration sample
                name = "accel" if dev is self._accel_dev else "cpu"
                reps = (3 if dev is self._accel_dev else 2) if measure else 1
                with jax.default_device(dev):
                    if mfw_ladder is None:
                        for _ in range(reps):
                            t0 = _time.perf_counter()
                            jax.device_get(admit_scan(*args, depth=st.depth))
                            dt = _time.perf_counter() - t0
                        if measure:
                            self.calibration[(name, "flat", W, W)] = dt
                        continue
                    for mfw in mfw_ladder:
                        for _ in range(reps):
                            t0 = _time.perf_counter()
                            jax.device_get(admit_scan_forests(
                                *args, st.forest_of_node, depth=st.depth,
                                n_forests=st.n_forests, max_forest_wl=mfw))
                            dt = _time.perf_counter() - t0
                        if measure:
                            self.calibration[(name, "forest", W, mfw)] = dt
            # native core timing: the sequential C++ admit loop competes
            # in the same calibration table, so the router picks the
            # fastest of native / XLA-CPU / accel per bucket (nothing to
            # eager-compile — it is AOT C++ — so skipped when loaded)
            if measure and self.backend == "auto":
                try:
                    from .. import native
                    if native.available():
                        # worst-case-shaped sample: every head fits with
                        # ALL R decision pairs valid, so the sequential
                        # loop pays its full per-entry cost — a sparse
                        # sample made native look cheaper than real
                        # cycles and mis-routed the drain bench
                        n_cq = len(st.cq_names)
                        busy_cq = (np.arange(W)
                                   % max(n_cq, 1)).astype(np.int32)
                        busy_fr = np.tile(
                            (np.arange(R) % F).astype(np.int32), (W, 1))
                        busy_amt = np.ones((W, R), np.int32)
                        for _ in range(2):
                            t0 = _time.perf_counter()
                            native.admit_scan_raw(
                                *args[:8], busy_cq, busy_fr, busy_amt,
                                np.ones(W, bool), args[12], args[13],
                                np.zeros(W, bool), np.zeros(W, bool),
                                args[16])
                            dt = _time.perf_counter() - t0
                        if mfw_ladder is None:
                            self.calibration[("native", "flat", W, W)] = dt
                        else:
                            for mfw in mfw_ladder:
                                self.calibration[
                                    ("native", "forest", W, mfw)] = dt
                except Exception:
                    # routing falls back to the XLA backends; surfaced
                    # so a broken native build can't hide (weak r3 #5)
                    self.stats["native_calibration_failures"] += 1

            # first padded-K bucket (scalar heads with more decision
            # pairs than R, _build_pair_tensors): compile so a
            # multi-PodSet head can't stall a cycle on compilation
            Kpad = _bucket(R + 1, minimum=R if R >= 8 else 8)
            kargs = (args[:9]
                     + (np.full((W, Kpad), -1, np.int32),
                        np.zeros((W, Kpad), np.int32), args[11],
                        np.full((W, Kpad), -1, np.int32),
                        np.zeros((W, Kpad), np.int32))
                     + args[14:])
            for dev in devs:
                with jax.default_device(dev):
                    jax.device_get(admit_scan(*kargs, depth=st.depth))

            # warm every (T, MT) rung that can appear at this head count
            # (an in-scan preemption universe is at most a few targets
            # per head x heads); only the SMALLEST T's timing feeds the
            # router calibration — it is the common case, and routing
            # tiny scans by large-T timings would favor the tunnel
            t_top = coarse_bucket(4 * W, T_LADDER)
            for T in [t for t in T_LADDER if t <= t_top]:
                mts = MT_LADDER if T == T_LADDER[0] else MT_LADDER[:1]
                for MT in mts:
                    pargs = args[:-1] + (
                        np.zeros(W, bool),
                        np.full((W, R), -1, np.int32),
                        np.zeros((W, R), np.int32),
                        np.full((W, MT), -1, np.int32),
                        np.zeros(T, np.int32),
                        np.zeros((T, F), np.int32), args[-1])
                    for dev in devs:
                        name = "accel" if dev is self._accel_dev else "cpu"
                        reps = (3 if dev is self._accel_dev
                                else 2) if measure else 1
                        with jax.default_device(dev):
                            for _ in range(reps):
                                t0 = _time.perf_counter()
                                jax.device_get(admit_scan_preempt(
                                    *pargs, depth=st.depth))
                                dt = _time.perf_counter() - t0
                        if (measure and T == T_LADDER[0]
                                and MT == MT_LADDER[0]):
                            self.calibration[(name, "preempt", W, W)] = dt

        # batched preemption search: compile the (S, K) rungs a run of
        # this size can hit (S <= 2 specs per head; K rungs beyond 128
        # are rare enough to compile on first use)
        from .preemption_kernel import minimal_preemptions_batch
        from .preemption_solver import _ForestPlanes, K_LADDER, S_LADDER
        try:
            planes = _ForestPlanes(st)
        except ValueError:
            planes = None
        if planes is not None:
            st._preempt_planes = planes
            NL = planes.NL
            s_top = coarse_bucket(2 * max_heads, S_LADDER)
            with jax.default_device(self._cpu_dev):
                for S in [s for s in S_LADDER if s <= s_top]:
                    for K in K_LADDER[:2]:
                        jax.device_get(minimal_preemptions_batch(
                            np.zeros((S, NL, F), np.int32),
                            np.zeros((S, NL, F), np.int32),
                            np.zeros((S, NL, F), np.int32),
                            np.full((S, NL, F), 2**30, np.int32),
                            np.zeros((S, NL, F), bool),
                            np.full((S, NL), -1, np.int32),
                            np.full(S, -1, np.int32),
                            np.zeros((S, F), np.int32),
                            np.zeros((S, F), bool),
                            np.full((S, K), -1, np.int32),
                            np.zeros((S, K, F), np.int32),
                            np.zeros((S, K), bool), np.zeros((S, K), bool),
                            np.zeros(S, bool), np.zeros(S, bool),
                            depth=st.depth))

        if measure:
            compilecache.save_json(calib_name, {
                "schema": CALIB_SCHEMA,
                "fingerprint": fp_src,
                "calibration": [[list(k), v]
                                for k, v in self.calibration.items()]})

    # -- structure cache -----------------------------------------------

    def _structure_for(self, snapshot: Snapshot,
                       heads: list[Info]) -> PackedStructure:
        gen = getattr(snapshot, "structure_generation", -1)
        st = self._structure
        if st is None or st.generation != gen or gen < 0:
            st = pack_structure(snapshot, heads, generation=gen)
            st.cq_vector_ok = self._cq_vector_ok(snapshot, st)
            self._structure = st
            self._potential0 = None
            self.stats["structure_rebuilds"] += 1
        return st

    # -- eligibility ---------------------------------------------------

    def _cq_vector_ok(self, snapshot: Snapshot,
                      st: PackedStructure) -> np.ndarray:
        """Per-CQ: can the vectorized classify reproduce the host flavor
        walk for heads of this CQ?  Requires a single resource group and
        plain flavors (existing, no taints, no node labels, no topology)
        — everything else routes the head to the scalar host walk instead
        (flavorassigner.go:499-640).  Any FlavorFungibility policy is
        fine: the walk (stop rules + resume index) runs in the vector
        math itself (classify_np / the fused burst kernel)."""
        ok = np.zeros(len(st.cq_names), dtype=bool)
        for ci, name in enumerate(st.cq_names):
            cq = snapshot.cluster_queues[name]
            if len(cq.spec.resource_groups) != 1:
                continue
            plain = True
            for rg in cq.spec.resource_groups:
                for fq in rg.flavors:
                    flavor = snapshot.resource_flavors.get(fq.name)
                    if (flavor is None or flavor.node_taints
                            or flavor.node_labels or flavor.topology_name):
                        plain = False
                        break
            ok[ci] = plain
        return ok

    def _scalar_mask(self, snapshot: Snapshot, heads: list[Info],
                     st: PackedStructure) -> np.ndarray:
        """Per-head: True → the head needs the scalar host walk (the
        vectorized classify's assumptions don't hold).  A mid-list
        fungibility resume state is NOT a scalar reason anymore: it
        becomes the head's vector start slot (``resume_start``)."""
        mask = np.zeros(len(heads), dtype=bool)
        cq_ok = st.cq_vector_ok
        reasons = self.stats["scalar_reasons"]
        for wi, h in enumerate(heads):
            ci = st.cq_index.get(h.cluster_queue, -1)
            if ci < 0 or not cq_ok[ci]:
                mask[wi] = True
                reasons["cq_shape"] = reasons.get("cq_shape", 0) + 1
                continue
            if len(h.obj.pod_sets) != 1:
                # the host can split flavors across pod sets and accounts
                # earlier pod sets' usage in later walks
                mask[wi] = True
                reasons["multi_podset"] = reasons.get("multi_podset", 0) + 1
                continue
            ps = h.obj.pod_sets[0]
            if ps.topology_request is not None:
                mask[wi] = True
                reasons["topology"] = reasons.get("topology", 0) + 1
        return mask

    def _start_slots(self, snapshot: Snapshot, heads: list[Info],
                     st: PackedStructure) -> np.ndarray:
        """Per-head flavor-walk start slot from the fungibility resume
        state (flavorassigner.go:359-366): a head whose last attempt
        stopped mid-list resumes at last_tried_flavor_idx + 1, unless the
        CQ's quota changed since (allocatable_generation moved on)."""
        start = np.zeros(len(heads), dtype=np.int32)
        for wi, h in enumerate(heads):
            s = resume_start(h, snapshot.cq(h.cluster_queue),
                             h.cluster_queue in st.cq_covers_pods)
            if s:
                start[wi] = s
                self.stats["resume_heads"] += 1
        return start

    # -- phase 1 -------------------------------------------------------

    def classify(self, snapshot: Snapshot,
                 heads: list[Info]) -> Optional[ClassifiedCycle]:
        """Pack + vectorized nominate.  None → run the host path.

        Heads the vector math can't cover are flagged in ``scalar_mask``
        (their vector rows are cleared); the scheduler host-walks those
        and attaches the assignments via ``attach_host_assignment``."""
        if not heads:
            return None
        st = self._structure_for(snapshot, heads)
        packed = pack_cycle(snapshot, heads, self.ordering, structure=st)
        if packed is None:
            # topology drifted under an unchanged generation (defensive):
            # rebuild once and retry
            self._structure = None
            st = self._structure_for(snapshot, heads)
            packed = pack_cycle(snapshot, heads, self.ordering, structure=st)
            if packed is None:
                return None
        if not packed.exact:
            # lossy int32 scaling could deny fits the host grants
            return None
        scalar = self._scalar_mask(snapshot, heads, st)
        start = self._start_slots(snapshot, heads, st)
        if self._potential0 is None or self._potential0.shape != packed.usage0.shape:
            from .cycle import available_all_np
            self._potential0 = available_all_np(
                np.zeros_like(packed.usage0), st.subtree_quota, st.guaranteed,
                st.borrow_cap, st.has_borrow_limit, st.parent, st.depth)

        W = packed.wl_cq.shape[0]
        start_pad = np.zeros(W, dtype=np.int32)
        start_pad[:len(heads)] = start
        # the C++ classify core is first-fit-only: any non-default
        # fungibility policy or mid-list resume routes to classify_np
        ff_default = (bool(st.cq_wcb_borrow.all())
                      and not bool(st.cq_wcp_preempt.any()))
        if self.backend == "native" and (not ff_default or start.any()):
            self.stats["native_ff_fallbacks"] += 1
        if self.backend == "native" and ff_default and not start.any():
            from .. import native
            fit_slot0, borrows0, preempt0 = native.classify_cycle(packed)
            n = packed.wl_count
            R = len(st.resource_names)
            out = {
                "fit_slot0": np.asarray(fit_slot0),
                "borrows0": np.asarray(borrows0),
                "preempt0": np.asarray(preempt0),
                "preempt_slot0": np.full(W, -1, np.int32),
                "preempt_borrows0": np.zeros(W, bool),
                "preempt_res_fit": np.ones((W, R), bool),
                "preempt_slot_count": np.zeros(W, np.int32),
                "preempt_stopped0": np.zeros(W, bool),
            }
            if out["preempt0"][:n].any():
                # the C++ core covers fit/borrow/preempt-possible; the
                # preempt-slot details come from the numpy pass on demand
                det = classify_np(packed, potential0=self._potential0)
                for k in ("preempt_slot0", "preempt_borrows0",
                          "preempt_res_fit", "preempt_slot_count",
                          "preempt_stopped0"):
                    out[k] = det[k]
        else:
            out = classify_np(packed, potential0=self._potential0,
                              start_slot=start_pad)
        n = packed.wl_count
        # partial admission: a min_count head whose FULL counts fit is
        # decision-identical to a plain head; otherwise the host runs the
        # PodSetReducer binary search (podset_reducer.go) — scalar walk
        for wi in range(n):
            if scalar[wi] or out["fit_slot0"][wi] >= 0:
                continue
            if any(ps.min_count is not None and ps.min_count < ps.count
                   for ps in heads[wi].obj.pod_sets):
                scalar[wi] = True
        if scalar.any():
            # clear the vector rows for scalar heads: their decisions come
            # from the attached host assignments instead
            sm = np.zeros(W, dtype=bool)
            sm[:n] = scalar
            out = dict(out)
            out["fit_slot0"] = np.where(sm, -1, out["fit_slot0"]).astype(np.int32)
            out["borrows0"] = out["borrows0"] & ~sm
            out["preempt0"] = out["preempt0"] & ~sm
            out["preempt_slot0"] = np.where(sm, -1, out["preempt_slot0"]).astype(np.int32)
            out["preempt_borrows0"] = out["preempt_borrows0"] & ~sm
            out["preempt_stopped0"] = out["preempt_stopped0"] & ~sm
            self.stats["scalar_heads"] += int(scalar.sum())
        else:
            sm = np.zeros(W, dtype=bool)
        self.stats["walk_stop_heads"] += int(
            np.count_nonzero(out["preempt_stopped0"][:n]))
        return ClassifiedCycle(
            packed=packed, heads=heads, snapshot=snapshot,
            fit_slot0=out["fit_slot0"], borrows0=out["borrows0"],
            preempt0=out["preempt0"], preempt_slot0=out["preempt_slot0"],
            preempt_borrows0=out["preempt_borrows0"],
            preempt_res_fit=out["preempt_res_fit"],
            preempt_slot_count=out["preempt_slot_count"],
            preempt_stopped0=out["preempt_stopped0"],
            scalar_mask=sm, host_assignments={}, host_pairs={})

    # -- scalar-head decisions -----------------------------------------

    def attach_host_assignment(self, cls: ClassifiedCycle, wi: int,
                               assignment) -> bool:
        """Record a host-walked head's assignment for the admit scan.

        The assignment's usage map becomes the head's decision pairs.
        Returns False when the usage can't be represented in the cached
        structure (unknown flavor-resource or inexact scaling) — the
        caller then falls the whole cycle back to the host."""
        pairs = self._assignment_pairs(cls, assignment)
        if pairs is None:
            return False
        cls.host_assignments[wi] = assignment
        cls.host_pairs[wi] = pairs
        return True

    def _assignment_pairs(self, cls: ClassifiedCycle, assignment
                          ) -> Optional[list[tuple[int, int]]]:
        """assignment.usage → [(F-index, scaled amount)], or None."""
        st = cls.packed.structure
        scale_of = {r: int(st.resource_scale[i])
                    for i, r in enumerate(st.resource_names)}
        pairs = []
        for fr, v in assignment.usage.items():
            fi = st.fr_index.get(fr)
            if fi is None:
                return None
            s = scale_of.get(fr.resource)
            if s is None or v % s:
                return None
            q = v // s
            if q > 2**31 - 1:
                return None
            pairs.append((fi, int(q)))
        return pairs

    def _build_pair_tensors(self, cls: ClassifiedCycle,
                            rmask: np.ndarray, pmask: np.ndarray):
        """Merge vector and scalar classifications into the scan's
        decision-pair tensors.

        Returns (dec_fr, dec_amt, fit_mask, res_fr, res_amt, res_borrows,
        pre_fr, pre_amt, borrows) — all [W, K] / [W]."""
        packed = cls.packed
        st = packed.structure
        W = packed.wl_cq.shape[0]
        R = len(st.resource_names)

        # vector fit heads: pairs from the chosen slot (batched)
        dec_fr, dec_amt, fit_mask = decision_pairs_from_slots(
            st.slot_fr, packed.wl_cq, packed.wl_requests, cls.fit_slot0)
        # vector reserve/preempt entries: pairs from the preempt slot
        pre_on = rmask | pmask
        pslot = np.where(pre_on & (cls.preempt_slot0 >= 0),
                         cls.preempt_slot0, -1).astype(np.int32)
        res_fr, res_amt, _ = decision_pairs_from_slots(
            st.slot_fr, packed.wl_cq, packed.wl_requests, pslot)
        res_borrows = cls.preempt_borrows0 & pre_on
        borrows = cls.borrows0.copy()
        borrows |= res_borrows

        scalar_pairs = cls.host_pairs
        max_k = R
        for pairs in scalar_pairs.values():
            max_k = max(max_k, len(pairs))
        if max_k > R:
            K = _bucket(max_k, minimum=R if R >= 8 else 8)
            pad = np.full((W, K - R), -1, np.int32)
            zpad = np.zeros((W, K - R), np.int32)
            dec_fr = np.concatenate([dec_fr, pad], axis=1)
            dec_amt = np.concatenate([dec_amt, zpad], axis=1)
            res_fr = np.concatenate([res_fr, pad], axis=1)
            res_amt = np.concatenate([res_amt, zpad], axis=1)

        for wi, assignment in cls.host_assignments.items():
            pairs = scalar_pairs[wi]
            mode = assignment.representative_mode()
            is_fit = mode == Mode.FIT
            fit_mask[wi] = is_fit
            dec_fr[wi] = -1
            dec_amt[wi] = 0
            res_fr[wi] = -1
            res_amt[wi] = 0
            if is_fit:
                for k, (fi, q) in enumerate(pairs):
                    dec_fr[wi, k] = fi
                    dec_amt[wi, k] = q
            elif rmask[wi] or pmask[wi]:
                for k, (fi, q) in enumerate(pairs):
                    res_fr[wi, k] = fi
                    res_amt[wi, k] = q
                res_borrows[wi] = assignment.borrows()
            borrows[wi] = assignment.borrows()
        # preempt entries re-check fits on the same pairs they charge
        pre_fr, pre_amt = res_fr, res_amt
        return (dec_fr, dec_amt, fit_mask, res_fr, res_amt, res_borrows,
                pre_fr, pre_amt, borrows)

    # -- phase 2 -------------------------------------------------------

    def pack_targets(self, cls: ClassifiedCycle,
                     targets_by_wi: dict) -> Optional[PackedTargets]:
        """Pack per-head preemption-target lists into scan tensors.

        ``targets_by_wi``: {head index: [Target]} from the preemptor's
        nominate-time searches.  Returns None when a target's usage can't
        be represented exactly in the cached structure (host fallback)."""
        packed = cls.packed
        st = packed.structure
        W = packed.wl_cq.shape[0]
        F = packed.usage0.shape[1]
        universe: list = []
        uni_idx: dict[str, int] = {}
        scale_of = {r: int(st.resource_scale[i])
                    for i, r in enumerate(st.resource_names)}

        def to_f_vec(frq) -> Optional[np.ndarray]:
            vec = np.zeros(F, dtype=np.int64)
            for fr, v in frq.items():
                fi = st.fr_index.get(fr)
                if fi is None:
                    return None
                s = scale_of[fr.resource]
                if v % s:
                    return None
                vec[fi] += v // s
            if vec.max(initial=0) > 2**31 - 1:
                return None
            return vec.astype(np.int32)

        deltas: list[np.ndarray] = []
        cqs: list[int] = []
        per_wi: dict[int, list[int]] = {}
        for wi, targets in targets_by_wi.items():
            idxs = []
            for t in targets:
                key = t.info.key
                ti = uni_idx.get(key)
                if ti is None:
                    ci = st.cq_index.get(t.info.cluster_queue)
                    if ci is None:
                        return None
                    delta = to_f_vec(t.info.usage())
                    if delta is None:
                        return None
                    ti = len(universe)
                    uni_idx[key] = ti
                    universe.append(t.info)
                    deltas.append(delta)
                    cqs.append(ci)
                idxs.append(ti)
            per_wi[wi] = idxs

        n_universe = max(1, len(universe))
        n_per_head = max(1, max(len(v) for v in per_wi.values()))
        if n_universe > T_LADDER[-1] or n_per_head > MT_LADDER[-1]:
            return None   # beyond the shape ladders: host path
        T = coarse_bucket(n_universe, T_LADDER)
        MT = coarse_bucket(n_per_head, MT_LADDER)
        tu_cq = np.zeros(T, dtype=np.int32)
        tu_delta = np.zeros((T, F), dtype=np.int32)
        tu_cq[:len(cqs)] = cqs
        if deltas:
            tu_delta[:len(deltas)] = np.stack(deltas)
        tgt_mat = np.full((W, MT), -1, dtype=np.int32)
        preempt_mask = np.zeros(W, dtype=bool)
        for wi, idxs in per_wi.items():
            preempt_mask[wi] = True
            tgt_mat[wi, :len(idxs)] = idxs
        return PackedTargets(preempt_mask=preempt_mask, tgt_mat=tgt_mat,
                             tu_cq=tu_cq, tu_delta=tu_delta)

    def dispatch(self, cls: ClassifiedCycle, reserve_mask: np.ndarray,
                 targets: Optional[PackedTargets] = None) -> DispatchHandle:
        """Issue the admit scan (async) — or prove it unnecessary.

        ``reserve_mask`` (head order) marks preempt-classified entries the
        scheduler verified have zero preemption candidates — they reserve
        capacity in-scan (resourcesToReserve) and requeue.  ``targets``
        carries the packed preemption targets for preempt heads WITH
        candidates; those entries preempt in-scan (the reference admit
        loop's IssuePreemptions branch, scheduler.go:176-284).

        Decision-identical shortcuts (no dispatch issued):
        - no fit head and no preempt entry → nothing can be admitted,
          reserves requeue anyway;
        - ≤1 entry per cohort forest (and no preempt entry) → zero
          within-cycle contention, every fit head keeps its fit.
        Otherwise the scan is dispatched asynchronously to the calibrated
        backend; the host overlaps per-head work until ``fetch``."""
        import jax
        packed = cls.packed
        st = packed.structure
        W = packed.wl_cq.shape[0]
        n = cls.n
        rmask = np.zeros(W, dtype=bool)
        rmask[:len(reserve_mask)] = reserve_mask
        pmask = (targets.preempt_mask if targets is not None
                 else np.zeros(W, dtype=bool))
        (dec_fr, dec_amt, fit_mask, res_fr, res_amt, res_borrows,
         pre_fr, pre_amt, borrows) = self._build_pair_tensors(
            cls, rmask, pmask)
        order = cycle_order_np(borrows, packed.wl_priority,
                               packed.wl_timestamp)
        self.stats["reserve_entries"] += int(rmask[:n].sum())
        handle = DispatchHandle(order=order, rmask=rmask, n=n)
        handle.fit_mask = fit_mask
        zeros = np.zeros(W, dtype=bool)

        if not pmask.any():
            handle.preempting = zeros
            handle.overlap_skip = zeros
            if not fit_mask[:n].any():
                self.stats["skipped_dispatches"] += 1
                handle.admitted = zeros
                handle.route = "no_fit"
                return handle
            entry_mask = fit_mask | rmask
            entry_cqs = packed.wl_cq[entry_mask]
            if len(entry_cqs):
                forests = st.forest_of_node[np.maximum(entry_cqs, 0)]
                if np.bincount(forests, minlength=st.n_forests).max() <= 1:
                    # one entry per independent quota forest: the scan's
                    # only job (usage mutation between entries) is a no-op
                    self.stats["singleton_dispatches"] += 1
                    handle.admitted = fit_mask & (packed.wl_cq >= 0)
                    handle.route = "singleton"
                    return handle

        has_preempt = bool(pmask.any())
        mfw = self._forest_bucket(packed) if not has_preempt else None
        kernel = ("preempt" if has_preempt
                  else "flat" if mfw is None else "forest")
        args = (packed.usage0, st.subtree_quota, st.guaranteed,
                st.borrow_cap, st.has_borrow_limit, st.parent,
                st.nominal_cq, st.nominal_plus_blimit_cq, packed.wl_cq,
                dec_fr, dec_amt, fit_mask, res_fr, res_amt, rmask,
                res_borrows)
        from ..profiling import annotation
        if self.mesh is not None:
            # production mesh routing (takes precedence over backend
            # shortcuts): the scan runs as a sharded program over the
            # (wl, cq) mesh with XLA collectives
            fns = self._sharded_for(st.depth)
            self.stats["sharded_dispatches"] += 1
            handle.route = "sharded"
            with annotation(f"admit_scan_sharded:{kernel}"):
                if has_preempt:
                    (pargs, porder, ppmask, ppre_fr, ppre_amt, ptgt,
                     _) = self._mesh_pad(
                        args, order, st, pmask=pmask, pre_fr=pre_fr,
                        pre_amt=pre_amt, tgt_mat=targets.tgt_mat)
                    self.stats["sharded_preempt_dispatches"] += 1
                    handle.pending = fns["preempt"](
                        *pargs, ppmask, ppre_fr, ppre_amt,
                        ptgt, targets.tu_cq, targets.tu_delta,
                        porder)
                elif mfw is not None:
                    pargs, porder, _, _, _, _, pforest = self._mesh_pad(
                        args, order, st, forest_of_node=st.forest_of_node)
                    handle.pending = fns["forest"](
                        *pargs, porder, forest_of_node=pforest,
                        n_forests=st.n_forests, max_forest_wl=mfw)
                else:
                    pargs, porder, _, _, _, _, _ = self._mesh_pad(
                        args, order, st)
                    handle.pending = fns["flat"](*pargs, porder)
            return handle
        use_native = self.backend == "native"
        if (not use_native and not has_preempt and self.backend == "auto"):
            # calibrated three-way routing: the C++ admit loop competes
            # with the XLA backends on measured time per bucket.  The
            # native time is mfw-independent (one sequential loop), so a
            # forest bucket beyond the warmup ladder falls back to any
            # recorded forest entry at this W — same for the XLA twins,
            # whose ladder has the same cap.
            key_len = mfw if mfw is not None else W

            def _lookup(name):
                t = self.calibration.get((name, kernel, W, key_len))
                if t is None and kernel == "forest":
                    t = max((v for k, v in self.calibration.items()
                             if k[:3] == (name, "forest", W)),
                            default=None)
                return t

            t_nat = _lookup("native")
            if t_nat is not None:
                others = [t for t in (_lookup("cpu"), _lookup("accel"))
                          if t is not None]
                use_native = not others or t_nat < min(others)
        if use_native and not has_preempt:
            # the C++ core runs the admit loop synchronously (preempt
            # cycles keep the jitted scan — no native twin yet)
            from .. import native
            handle.admitted = native.admit_scan(
                packed, dec_fr, dec_amt, fit_mask, res_fr, res_amt,
                rmask, res_borrows, order)
            handle.preempting = zeros
            handle.overlap_skip = zeros
            handle.route = "native"
            self.stats["native_dispatches"] += 1
            return handle
        dev = self._route_device(kernel, W, mfw)
        if dev is self._accel_dev and self._accel_dev is not None:
            self.stats["accel_dispatches"] += 1
            handle.route = "accel"
        else:
            self.stats["cpu_dispatches"] += 1
            handle.route = "cpu"
        with annotation(f"admit_scan:{kernel}"), jax.default_device(dev):
            if pmask.any():
                handle.pending = admit_scan_preempt(
                    *args, pmask, pre_fr, pre_amt,
                    targets.tgt_mat, targets.tu_cq, targets.tu_delta,
                    order, depth=st.depth)
            elif mfw is not None:
                handle.pending = admit_scan_forests(
                    *args, order, st.forest_of_node, depth=st.depth,
                    n_forests=st.n_forests, max_forest_wl=mfw)
            else:
                handle.pending = admit_scan(*args, order, depth=st.depth)
        return handle

    def dispatch_fs(self, cls: ClassifiedCycle) -> Optional[DispatchHandle]:
        """Dispatch a fair-sharing cycle's tournament + admit loop as one
        jitted scan (ops/fs_scan.py) — FULL-mode FS (verdict r3 item 3).

        Returns None when the FS statics can't be built or the scaled
        DRS math could overflow (host tournament runs instead).  The
        caller guarantees: no scalar heads, no preempt-capable heads, no
        admission-block gate."""
        from .fs_scan import build_fs_statics, fs_admit_scan, fs_bounds_ok
        packed = cls.packed
        st = packed.structure
        statics = getattr(st, "_fs_statics", "unset")
        if isinstance(statics, str):
            statics = build_fs_statics(cls.snapshot, st)
            st._fs_statics = statics
        if statics is None:
            return None
        W = packed.wl_cq.shape[0]
        F = packed.usage0.shape[1]
        n = cls.n
        dec_fr, dec_amt, fit_mask = decision_pairs_from_slots(
            st.slot_fr, packed.wl_cq, packed.wl_requests, cls.fit_slot0)
        u_e = np.zeros((W, F), dtype=np.int32)
        rows, cols = np.nonzero(dec_fr >= 0)
        np.add.at(u_e, (rows, dec_fr[rows, cols]), dec_amt[rows, cols])
        if not fs_bounds_ok(statics, packed.usage0, u_e):
            return None
        valid = packed.wl_cq >= 0
        nofit = ~fit_mask
        # equality-preserving timestamp rank (ties must stay ties for
        # entryComparer.less parity)
        _, ts_rank = np.unique(packed.wl_timestamp, return_inverse=True)
        ts_rank = ts_rank.astype(np.int32)
        dev = self._route_device("fs", W, None)
        import jax
        handle = DispatchHandle(order=np.arange(W, dtype=np.int32),
                                rmask=np.zeros(W, dtype=bool), n=n)
        handle.fit_mask = fit_mask
        handle.route = ("accel" if dev is self._accel_dev
                        and self._accel_dev is not None else "cpu")
        if handle.route == "accel":
            self.stats["accel_dispatches"] += 1
        else:
            self.stats["cpu_dispatches"] += 1
        from ..profiling import annotation
        fs_args = (packed.usage0, st.subtree_quota, statics.sq_mask,
                   st.guaranteed, st.borrow_cap, st.has_borrow_limit,
                   st.parent, statics.node_level, st.fair_weight_milli,
                   statics.lendable_r, statics.onehot,
                   statics.child_order, packed.wl_cq, u_e, nofit,
                   packed.wl_priority, ts_rank, valid)
        if self.mesh is not None:
            # mesh-sharded FS tournament: the SAME jitted program,
            # partitioned by GSPMD over (wl, cq) — integer DRS math and
            # deterministic argmax tie-breaks make it bit-identical
            key = ("fs", st.depth, statics.n_levels)
            fn = self._sharded_fns.get(key)
            if fn is None:
                from ..parallel.sharded import fs_scan_fn
                fn = fs_scan_fn(self.mesh, st.depth, statics.n_levels)
                self._sharded_fns[key] = fn
            self.stats["sharded_fs_dispatches"] = (
                self.stats.get("sharded_fs_dispatches", 0) + 1)
            with annotation("fs_admit_scan"):
                handle.pending = ("fs", fn(*fs_args))
            return handle
        with annotation("fs_admit_scan"), jax.default_device(dev):
            handle.pending = ("fs", fs_admit_scan(
                *fs_args, depth=st.depth, n_levels=statics.n_levels))
        return handle

    def fetch(self, handle: DispatchHandle) -> DeviceCycleFinal:
        """Block for an in-flight scan's decisions (head order)."""
        if handle.admitted is None:
            import jax
            if (isinstance(handle.pending, tuple)
                    and len(handle.pending) == 2
                    and handle.pending[0] == "fs"):
                order, admitted, processed = jax.device_get(
                    handle.pending[1])
                handle.pending = None
                handle.admitted = np.asarray(admitted)
                W = len(handle.rmask)
                handle.preempting = np.zeros(W, dtype=bool)
                handle.overlap_skip = np.zeros(W, dtype=bool)
                handle.order = np.asarray(order)
                n = handle.n
                return DeviceCycleFinal(
                    order=handle.order[(handle.order >= 0)
                                       & (handle.order < n)],
                    admitted=handle.admitted[:n],
                    reserve_mask=handle.rmask[:n],
                    preempting=handle.preempting[:n],
                    overlap_skip=handle.overlap_skip[:n])
            out = jax.device_get(handle.pending)
            handle.pending = None
            if isinstance(out, tuple):
                handle.admitted = np.asarray(out[0])
                handle.preempting = np.asarray(out[1])
                handle.overlap_skip = np.asarray(out[2])
            else:
                W = len(handle.rmask)
                handle.admitted = np.asarray(out)
                handle.preempting = np.zeros(W, dtype=bool)
                handle.overlap_skip = np.zeros(W, dtype=bool)
        n = handle.n
        return DeviceCycleFinal(
            order=handle.order[handle.order < n],
            admitted=handle.admitted[:n], reserve_mask=handle.rmask[:n],
            preempting=handle.preempting[:n],
            overlap_skip=handle.overlap_skip[:n])

    def solve_full(self, cls: ClassifiedCycle,
                   reserve_mask: np.ndarray) -> DeviceCycleFinal:
        """dispatch + fetch in one call (tests/probes)."""
        return self.fetch(self.dispatch(cls, reserve_mask))

    @staticmethod
    def _forests_apply(W: int, n_forests: int) -> bool:
        """Single gate for forest-vs-flat scan dispatch (warmup must
        compile exactly what solve_full will run)."""
        return n_forests > 1 and W >= _FOREST_MIN_HEADS

    def _forest_bucket(self, packed: PackedCycle) -> Optional[int]:
        """Power-of-two scan length for the forest-parallel admit scan, or
        None when the flat scan is the better dispatch."""
        st = packed.structure
        if not self._forests_apply(packed.wl_cq.shape[0], st.n_forests):
            return None
        valid = packed.wl_cq >= 0
        if not valid.any():
            return None
        f_of = st.forest_of_node[np.maximum(packed.wl_cq, 0)]
        counts = np.bincount(f_of[valid], minlength=st.n_forests)
        return _bucket(int(counts.max()), minimum=4)

    # -- assignment reconstruction -------------------------------------

    def build_fit_assignment(self, cls: ClassifiedCycle,
                             wi) -> Assignment:
        """Host Assignment for a device-classified Fit head, including the
        fungibility resume state the host walk would record."""
        slot = int(cls.fit_slot0[wi])
        borrow = bool(cls.borrows0[wi])
        return self._build_assignment(cls, wi, slot, Mode.FIT, borrow)

    def _build_assignment(self, cls: ClassifiedCycle, wi: int, slot: int,
                          mode: Mode, borrow: bool,
                          res_modes: Optional[dict] = None) -> Assignment:
        h = cls.heads[wi]
        cq = cls.snapshot.cq(h.cluster_queue)
        return build_slot_assignment(h, cq, slot, mode, borrow,
                                     res_modes=res_modes)

    def build_preempt_assignment(self, cls: ClassifiedCycle,
                                 wi: int) -> Assignment:
        """Host Assignment for a preempt-classified head with per-resource
        modes (resources fitting on the preempt slot are FIT, the
        shortfall resources PREEMPT — flavorassigner.go:692), as the
        preemptor's target search expects (preemption.go:466)."""
        slot = int(cls.preempt_slot0[wi])
        borrow = bool(cls.preempt_borrows0[wi])
        st = cls.packed.structure
        res_modes = {res: (Mode.FIT if cls.preempt_res_fit[wi][ri]
                           else Mode.PREEMPT)
                     for res, ri in st.r_index.items()}
        return self._build_assignment(cls, wi, slot, Mode.PREEMPT, borrow,
                                      res_modes=res_modes)

    def reserve_details(self, cls: ClassifiedCycle, wi: int
                        ) -> tuple[Assignment, str]:
        """Assignment + inadmissible message for a preempt-classified head
        with no candidates (reachable whenever exactly one slot is
        preempt-capable, including multi-flavor CQs whose other slots are
        NoFit), replicating the host walk's reasons (flavorassigner.go:692
        messages)."""
        h = cls.heads[wi]
        assignment = self.build_preempt_assignment(cls, wi)
        cq = cls.snapshot.cq(h.cluster_queue)
        ps = assignment.pod_sets[0]
        reasons = []
        for res in sorted(ps.requests):
            val = ps.requests[res]
            fr = FlavorResource(ps.flavors[res].name, res)
            avail = cq.available(fr)
            if val > avail:
                reasons.append(
                    f"insufficient unused quota for {res} in flavor "
                    f"{fr.flavor}, {val - avail} more needed")
        ps.reasons = reasons
        return assignment, assignment.message()

    # -- back-compat one-shot API (tests/probes) -----------------------

    def try_solve(self, snapshot: Snapshot, heads: list[Info]
                  ) -> Optional[dict[str, Assignment]]:
        """Classify-only: {workload_key: Fit Assignment} for heads that fit
        at snapshot usage, or None when the host path must run (any
        preempt-capable head, or unsupported semantics)."""
        cls = self.classify(snapshot, heads)
        if cls is None:
            self.stats["host_cycles"] += 1
            return None
        if cls.preempt0[:cls.n].any() or cls.scalar_mask[:cls.n].any():
            self.stats["host_cycles"] += 1
            return None
        self.stats["classify_cycles"] += 1
        out: dict[str, Assignment] = {}
        for wi in range(cls.n):
            if cls.fit_slot0[wi] >= 0:
                out[cls.heads[wi].key] = self.build_fit_assignment(cls, wi)
        return out


def build_slot_assignment(info: Info, cq, slot: int, mode: Mode,
                          borrow: bool,
                          res_modes: Optional[dict] = None) -> Assignment:
    """Reconstruct the host Assignment a device-classified head would get
    from the flavor walk: single resource group, slot = flavor index,
    including the fungibility resume state (flavorassigner.go:499).
    ``cq`` is any CQState (snapshot or live cache) carrying .spec and
    .allocatable_generation."""
    slot = int(slot)
    rg = cq.spec.resource_groups[0]
    covers_pods = "pods" in rg.covered_resources
    flavor_name = rg.flavors[slot].name
    n_slots = len(rg.flavors)
    # the host records attempted_idx = the slot the walk STOPPED on, or
    # the last slot when it scanned to the end and kept the best
    # (flavorassigner.go:386-390 + shouldTryNextFlavor); tried = -1 when
    # the whole list was attempted
    ff = cq.spec.flavor_fungibility
    wcb = ff.when_can_borrow == FlavorFungibilityPolicy.BORROW
    wcp = ff.when_can_preempt == FlavorFungibilityPolicy.PREEMPT
    stopped = ((not borrow or wcb)
               and (mode == Mode.FIT
                    or (mode == Mode.PREEMPT and wcp)))
    attempted = slot if stopped else n_slots - 1
    tried = -1 if attempted == n_slots - 1 else attempted

    assignment = Assignment()
    assignment.borrowing = borrow
    assignment.last_state = AssignmentClusterQueueState(
        cluster_queue_generation=cq.allocatable_generation)
    for psr in info.total_requests:
        # mirror the host's implicit "pods" handling
        # (flavorassigner.go:226 / _assign_flavors)
        reqs = dict(psr.requests)
        if covers_pods:
            reqs["pods"] = psr.count
        else:
            reqs.pop("pods", None)
        ps_res = PodSetAssignmentResult(
            name=psr.name, requests=Requests(reqs), count=psr.count)
        flavor_idx: dict[str, int] = {}
        for res in reqs:
            res_mode = mode if res_modes is None else res_modes.get(
                res, mode)
            ps_res.flavors[res] = FlavorAssignmentDecision(
                name=flavor_name, mode=res_mode, borrow=borrow,
                tried_flavor_idx=tried)
            flavor_idx[res] = tried
            fr = FlavorResource(flavor_name, res)
            assignment.usage[fr] = (assignment.usage.get(fr, 0)
                                    + reqs[res])
        assignment.pod_sets.append(ps_res)
        assignment.last_state.last_tried_flavor_idx.append(flavor_idx)
    return assignment


def resume_start(info: Info, cq, covers_pods: bool) -> int:
    """Flavor-walk start slot for a head with fungibility resume state.

    Mirrors the host's entry into the walk (flavorassigner.go:359-366 via
    next_flavor_to_try of the first resource in sorted request order): 0
    when there is no usable resume state, last_tried + 1 otherwise.  The
    state is void when the CQ's quota changed since it was recorded
    (assign() clears it on allocatable_generation advance)."""
    last = info.last_assignment
    if last is None or cq is None:
        return 0
    if cq.allocatable_generation > last.cluster_queue_generation:
        return 0
    if not info.total_requests:
        return 0
    psr = info.total_requests[0]
    reqs = set(psr.requests)
    if covers_pods:
        reqs.add("pods")
    else:
        reqs.discard("pods")
    if not reqs:
        return 0
    return max(0, int(last.next_flavor_to_try(0, sorted(reqs)[0])))
