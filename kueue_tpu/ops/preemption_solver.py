"""Host wrapper for the device preemption search.

Packs the snapshot + candidate list and runs
ops.preemption_kernel.minimal_preemptions; returns the Target list in
host semantics, or None when the scenario needs the host path (inexact
scaling, unknown flavor-resources).  Decision parity with the host
greedy+fillback search is enforced by tests/test_preemption_kernel.py.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..api.types import (
    IN_CLUSTER_QUEUE_REASON,
    IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
    IN_COHORT_RECLAMATION_REASON,
)
from .packing import pack_cycle
from .preemption_kernel import minimal_preemptions

_cpu_dev = None


def _cpu_device():
    """Candidate lists are small; a tunneled accelerator's ~100ms round
    trip would dwarf the search, so the kernel always runs on the XLA CPU
    backend (identical decisions)."""
    global _cpu_dev
    if _cpu_dev is None:
        import jax
        try:
            _cpu_dev = jax.devices("cpu")[0]
        except RuntimeError:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            _cpu_dev = jax.devices("cpu")[0]
    return _cpu_dev


def _bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def device_minimal_preemptions_batch(specs, packed):
    """ALL of a cycle's preemption searches in one vmapped dispatch.

    ``specs``: [(ctx, candidates, allow_borrowing, threshold)] — the
    per-head search requests the preemptor planned (every search is
    against the same nominate-time snapshot, so they are independent).
    Returns a list of per-spec Target lists ([] = search failed), or
    None when any spec can't be packed (caller runs the host path)."""
    from ..scheduler.preemption import Target  # circular-safe import

    if packed is None or not packed.exact or not specs:
        return None
    cq_idx = {n: i for i, n in enumerate(packed.cq_names)}
    F = packed.usage0.shape[1]
    scale_of = {r: int(packed.resource_scale[i])
                for i, r in enumerate(packed.resource_names)}

    def to_f_vec(frq) -> Optional[np.ndarray]:
        vec = np.zeros(F, dtype=np.int64)
        for fr, v in frq.items():
            fi = packed.fr_index.get(fr)
            if fi is None:
                return None
            s = scale_of[fr.resource]
            if v % s:
                return None
            vec[fi] += v // s
        if vec.max(initial=0) > 2**31 - 1:
            return None
        return vec.astype(np.int32)

    # generous bucket floors: each distinct (S, K) combination is one
    # XLA compilation — keep the variety low across a run's cycles
    S = _bucket(len(specs), minimum=32)
    K = _bucket(max(1, max(len(c) for _, c, _, _ in specs)), minimum=16)
    pre_cq = np.full(S, -1, dtype=np.int32)
    wl_usage = np.zeros((S, F), dtype=np.int32)
    frs_mask = np.zeros((S, F), dtype=bool)
    cand_cq = np.full((S, K), -1, dtype=np.int32)
    cand_delta = np.zeros((S, K, F), dtype=np.int32)
    cand_other = np.zeros((S, K), dtype=bool)
    cand_above = np.zeros((S, K), dtype=bool)
    allow_b0 = np.zeros(S, dtype=bool)
    thr_en = np.zeros(S, dtype=bool)
    # target-usage vectors dedupe across specs (the same admitted
    # workload is a candidate for many preemptors)
    vec_cache: dict[str, Optional[np.ndarray]] = {}

    for si, (ctx, candidates, allow_borrowing, threshold) in enumerate(specs):
        ci = cq_idx.get(ctx.preemptor_cq.name)
        if ci is None:
            return None
        wu = to_f_vec(ctx.workload_usage)
        if wu is None:
            return None
        pre_cq[si] = ci
        wl_usage[si] = wu
        for fr in ctx.frs_need_preemption:
            fi = packed.fr_index.get(fr)
            if fi is None:
                return None
            frs_mask[si, fi] = True
        allow_b0[si] = allow_borrowing
        thr_en[si] = threshold is not None
        for k, cand in enumerate(candidates):
            cci = cq_idx.get(cand.cluster_queue)
            if cci is None:
                return None
            delta = vec_cache.get(cand.key)
            if delta is None and cand.key not in vec_cache:
                delta = to_f_vec(cand.usage())
                vec_cache[cand.key] = delta
            if delta is None:
                return None
            cand_cq[si, k] = cci
            cand_delta[si, k] = delta
            cand_other[si, k] = cand.cluster_queue != ctx.preemptor_cq.name
            cand_above[si, k] = (threshold is not None
                                 and cand.obj.priority >= threshold)

    import jax
    from .preemption_kernel import minimal_preemptions_batch
    with jax.default_device(_cpu_device()):
        fitted, mask = minimal_preemptions_batch(
            packed.usage0, packed.subtree_quota, packed.guaranteed,
            packed.borrow_cap, packed.has_borrow_limit, packed.parent,
            pre_cq, wl_usage, frs_mask, cand_cq, cand_delta, cand_other,
            cand_above, allow_b0, thr_en, depth=packed.depth)
    fitted = np.asarray(fitted)
    mask = np.asarray(mask)

    out = []
    for si, (ctx, candidates, _, threshold) in enumerate(specs):
        if not fitted[si]:
            out.append([])
            continue
        targets = []
        for k, cand in enumerate(candidates):
            if not mask[si, k]:
                continue
            if cand.cluster_queue == ctx.preemptor_cq.name:
                reason = IN_CLUSTER_QUEUE_REASON
            elif threshold is not None and cand.obj.priority < threshold:
                reason = IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
            else:
                reason = IN_COHORT_RECLAMATION_REASON
            targets.append(Target(info=cand, reason=reason))
        out.append(targets)
    return out


def device_minimal_preemptions(ctx, candidates, allow_borrowing: bool,
                               threshold: Optional[int], packed=None):
    """Device twin of Preemptor._minimal_preemptions.

    ``packed`` (a PackedCycle for the SAME snapshot at nominate time, e.g.
    the admission solver's cached-structure pack) avoids re-packing per
    search.  Returns a list of Targets, [] (search failed), or None
    (unsupported — run the host path)."""
    from ..scheduler.preemption import Target  # circular-safe import

    if not candidates:
        return []
    if packed is None:
        packed = pack_cycle(ctx.snapshot, [])
    if packed is None or not packed.exact:
        return None
    cq_idx = {n: i for i, n in enumerate(packed.cq_names)}
    pre_cq = cq_idx.get(ctx.preemptor_cq.name)
    if pre_cq is None:
        return None
    F = packed.usage0.shape[1]
    scale_of = {r: int(packed.resource_scale[i])
                for i, r in enumerate(packed.resource_names)}

    def to_f_vec(frq) -> Optional[np.ndarray]:
        vec = np.zeros(F, dtype=np.int64)
        for fr, v in frq.items():
            fi = packed.fr_index.get(fr)
            if fi is None:
                return None
            s = scale_of[fr.resource]
            if v % s:
                return None
            vec[fi] += v // s
        if vec.max(initial=0) > 2**31 - 1:
            return None
        return vec.astype(np.int32)

    wl_usage = to_f_vec(ctx.workload_usage)
    if wl_usage is None:
        return None
    frs_mask = np.zeros(F, dtype=bool)
    for fr in ctx.frs_need_preemption:
        fi = packed.fr_index.get(fr)
        if fi is None:
            return None
        frs_mask[fi] = True

    K = _bucket(len(candidates))
    cand_cq = np.full(K, -1, dtype=np.int32)
    cand_delta = np.zeros((K, F), dtype=np.int32)
    cand_other = np.zeros(K, dtype=bool)
    cand_above = np.zeros(K, dtype=bool)
    for i, cand in enumerate(candidates):
        ci = cq_idx.get(cand.cluster_queue)
        if ci is None:
            return None
        delta = to_f_vec(cand.usage())
        if delta is None:
            return None
        cand_cq[i] = ci
        cand_delta[i] = delta
        cand_other[i] = cand.cluster_queue != ctx.preemptor_cq.name
        cand_above[i] = (threshold is not None
                         and cand.obj.priority >= threshold)

    import jax
    with jax.default_device(_cpu_device()):
        fitted, target_mask = minimal_preemptions(
            packed.usage0, packed.subtree_quota, packed.guaranteed,
            packed.borrow_cap, packed.has_borrow_limit, packed.parent,
            pre_cq, wl_usage, frs_mask, cand_cq, cand_delta, cand_other,
            cand_above, allow_borrowing, threshold is not None,
            depth=packed.depth)
    if not bool(fitted):
        return []
    mask = np.asarray(target_mask)
    targets = []
    for i, cand in enumerate(candidates):
        if not mask[i]:
            continue
        if not cand_other[i]:
            reason = IN_CLUSTER_QUEUE_REASON
        elif threshold is not None and cand.obj.priority < threshold:
            reason = IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
        else:
            reason = IN_COHORT_RECLAMATION_REASON
        targets.append(Target(info=cand, reason=reason))
    return targets
