"""Host wrapper for the device preemption search.

Packs the snapshot + candidate list and runs
ops.preemption_kernel.minimal_preemptions; returns the Target list in
host semantics, or None when the scenario needs the host path (inexact
scaling, unknown flavor-resources).  Decision parity with the host
greedy+fillback search is enforced by tests/test_preemption_kernel.py.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..api.types import (
    IN_CLUSTER_QUEUE_REASON,
    IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
    IN_COHORT_RECLAMATION_REASON,
)
from .packing import pack_cycle
from .preemption_kernel import minimal_preemptions

_cpu_dev = None

# shape ladders for the batched search (see coarse_bucket)
S_LADDER = (32, 256, 1024, 4096)
K_LADDER = (16, 128, 1024)


def _cpu_device():
    """Candidate lists are small; a tunneled accelerator's ~100ms round
    trip would dwarf the search, so the kernel always runs on the XLA CPU
    backend (identical decisions)."""
    global _cpu_dev
    if _cpu_dev is None:
        import jax
        try:
            _cpu_dev = jax.devices("cpu")[0]
        except RuntimeError:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            _cpu_dev = jax.devices("cpu")[0]
    return _cpu_dev


def _bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class _ForestPlanes:
    """Per-forest compact quota planes, cached per PackedStructure.

    Each cohort forest's nodes are remapped to a dense local index space
    (bucketed to NL) so a preemption search carries [NL, F] instead of
    the whole [N, F] cluster."""

    def __init__(self, st):
        forest = np.asarray(st.forest_of_node)
        N, F = st.subtree_quota.shape
        per_forest: list[list[int]] = [[] for _ in range(st.n_forests)]
        for ni in range(N):
            per_forest[int(forest[ni])].append(ni)
        self.NL = _bucket(max(1, max(len(v) for v in per_forest)),
                          minimum=4)
        G = st.n_forests
        self.glob_idx = np.full((G, self.NL), -1, dtype=np.int32)
        self.parent = np.full((G, self.NL), -1, dtype=np.int32)
        self.subtree = np.zeros((G, self.NL, F), dtype=np.int32)
        self.guaranteed = np.zeros((G, self.NL, F), dtype=np.int32)
        self.borrow_cap = np.full((G, self.NL, F), 2**30, dtype=np.int32)
        self.has_blim = np.zeros((G, self.NL, F), dtype=bool)
        self.local: dict[int, tuple[int, int]] = {}   # global → (f, local)
        for f, nodes in enumerate(per_forest):
            if len(nodes) > self.NL:
                raise ValueError("forest exceeds bucket")
            loc = {g: i for i, g in enumerate(nodes)}
            for i, g in enumerate(nodes):
                self.glob_idx[f, i] = g
                p = int(st.parent[g])
                self.parent[f, i] = loc.get(p, -1) if p >= 0 else -1
                self.subtree[f, i] = st.subtree_quota[g]
                self.guaranteed[f, i] = st.guaranteed[g]
                self.borrow_cap[f, i] = st.borrow_cap[g]
                self.has_blim[f, i] = st.has_borrow_limit[g]
                self.local[g] = (f, i)

    def usage_planes(self, usage0: np.ndarray) -> np.ndarray:
        """[G, NL, F] usage slices from the cycle's [N, F] usage."""
        safe = np.maximum(self.glob_idx, 0)
        return usage0[safe] * (self.glob_idx >= 0)[:, :, None]


def _planes_for(packed) -> Optional[_ForestPlanes]:
    st = getattr(packed, "structure", None)
    if st is None:
        return None
    planes = getattr(st, "_preempt_planes", None)
    if planes is None:
        try:
            planes = _ForestPlanes(st)
        except ValueError:
            return None
        st._preempt_planes = planes
    return planes


def device_minimal_preemptions_batch(specs, packed):
    """ALL of a cycle's preemption searches in one vmapped dispatch,
    each over its preemptor's forest-local quota plane.

    ``specs``: [(ctx, candidates, allow_borrowing, threshold)] — the
    per-head search requests the preemptor planned (every search is
    against the same nominate-time snapshot, so they are independent).
    Returns a list of per-spec Target lists ([] = search failed), or
    None when any spec can't be packed (caller runs the host path)."""
    from ..scheduler.preemption import Target  # circular-safe import

    if packed is None or not packed.exact or not specs:
        return None
    planes = _planes_for(packed)
    if planes is None:
        return None
    cq_idx = {n: i for i, n in enumerate(packed.cq_names)}
    F = packed.usage0.shape[1]
    scale_of = {r: int(packed.resource_scale[i])
                for i, r in enumerate(packed.resource_names)}

    def to_f_vec(frq) -> Optional[np.ndarray]:
        vec = np.zeros(F, dtype=np.int64)
        for fr, v in frq.items():
            fi = packed.fr_index.get(fr)
            if fi is None:
                return None
            s = scale_of[fr.resource]
            if v % s:
                return None
            vec[fi] += v // s
        if vec.max(initial=0) > 2**31 - 1:
            return None
        return vec.astype(np.int32)

    # coarse shape ladders: each distinct (S, K) combination is one XLA
    # compilation — a handful of rungs covers every cycle, and warmup
    # pre-compiles them (CycleSolver.warmup).  Beyond the top rung the
    # host path runs (None), never an array overflow.
    from .packing import coarse_bucket
    max_cands = max(1, max(len(c) for _, c, _, _ in specs))
    if len(specs) > S_LADDER[-1] or max_cands > K_LADDER[-1]:
        return None
    S = coarse_bucket(len(specs), S_LADDER)
    K = coarse_bucket(max_cands, K_LADDER)
    NL = planes.NL
    usage_planes = planes.usage_planes(packed.usage0)     # [G, NL, F]
    forest_of = np.zeros(S, dtype=np.int32)
    pre_cq = np.full(S, -1, dtype=np.int32)
    wl_usage = np.zeros((S, F), dtype=np.int32)
    frs_mask = np.zeros((S, F), dtype=bool)
    cand_cq = np.full((S, K), -1, dtype=np.int32)
    cand_delta = np.zeros((S, K, F), dtype=np.int32)
    cand_other = np.zeros((S, K), dtype=bool)
    cand_above = np.zeros((S, K), dtype=bool)
    allow_b0 = np.zeros(S, dtype=bool)
    thr_en = np.zeros(S, dtype=bool)
    # target-usage vectors dedupe across specs (the same admitted
    # workload is a candidate for many preemptors)
    vec_cache: dict[str, Optional[np.ndarray]] = {}

    for si, (ctx, candidates, allow_borrowing, threshold) in enumerate(specs):
        ci = cq_idx.get(ctx.preemptor_cq.name)
        if ci is None or ci not in planes.local:
            return None
        f, ci_local = planes.local[ci]
        wu = to_f_vec(ctx.workload_usage)
        if wu is None:
            return None
        forest_of[si] = f
        pre_cq[si] = ci_local
        wl_usage[si] = wu
        for fr in ctx.frs_need_preemption:
            fi = packed.fr_index.get(fr)
            if fi is None:
                return None
            frs_mask[si, fi] = True
        allow_b0[si] = allow_borrowing
        thr_en[si] = threshold is not None
        for k, cand in enumerate(candidates):
            cci = cq_idx.get(cand.cluster_queue)
            if cci is None:
                return None
            cf_local = planes.local.get(cci)
            if cf_local is None or cf_local[0] != f:
                return None   # candidate outside the preemptor's forest
            delta = vec_cache.get(cand.key)
            if delta is None and cand.key not in vec_cache:
                delta = to_f_vec(cand.usage())
                vec_cache[cand.key] = delta
            if delta is None:
                return None
            cand_cq[si, k] = cf_local[1]
            cand_delta[si, k] = delta
            cand_other[si, k] = cand.cluster_queue != ctx.preemptor_cq.name
            cand_above[si, k] = (threshold is not None
                                 and cand.obj.priority >= threshold)

    import jax
    from .preemption_kernel import minimal_preemptions_batch
    with jax.default_device(_cpu_device()):
        fitted, mask = minimal_preemptions_batch(
            usage_planes[forest_of], planes.subtree[forest_of],
            planes.guaranteed[forest_of], planes.borrow_cap[forest_of],
            planes.has_blim[forest_of], planes.parent[forest_of],
            pre_cq, wl_usage, frs_mask, cand_cq, cand_delta, cand_other,
            cand_above, allow_b0, thr_en, depth=packed.depth)
    fitted = np.asarray(fitted)
    mask = np.asarray(mask)

    out = []
    for si, (ctx, candidates, _, threshold) in enumerate(specs):
        if not fitted[si]:
            out.append([])
            continue
        targets = []
        for k, cand in enumerate(candidates):
            if not mask[si, k]:
                continue
            if cand.cluster_queue == ctx.preemptor_cq.name:
                reason = IN_CLUSTER_QUEUE_REASON
            elif threshold is not None and cand.obj.priority < threshold:
                reason = IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
            else:
                reason = IN_COHORT_RECLAMATION_REASON
            targets.append(Target(info=cand, reason=reason))
        out.append(targets)
    return out


def device_minimal_preemptions(ctx, candidates, allow_borrowing: bool,
                               threshold: Optional[int], packed=None):
    """Device twin of Preemptor._minimal_preemptions.

    ``packed`` (a PackedCycle for the SAME snapshot at nominate time, e.g.
    the admission solver's cached-structure pack) avoids re-packing per
    search.  Returns a list of Targets, [] (search failed), or None
    (unsupported — run the host path)."""
    from ..scheduler.preemption import Target  # circular-safe import

    if not candidates:
        return []
    if packed is None:
        packed = pack_cycle(ctx.snapshot, [])
    if packed is None or not packed.exact:
        return None
    cq_idx = {n: i for i, n in enumerate(packed.cq_names)}
    pre_cq = cq_idx.get(ctx.preemptor_cq.name)
    if pre_cq is None:
        return None
    F = packed.usage0.shape[1]
    scale_of = {r: int(packed.resource_scale[i])
                for i, r in enumerate(packed.resource_names)}

    def to_f_vec(frq) -> Optional[np.ndarray]:
        vec = np.zeros(F, dtype=np.int64)
        for fr, v in frq.items():
            fi = packed.fr_index.get(fr)
            if fi is None:
                return None
            s = scale_of[fr.resource]
            if v % s:
                return None
            vec[fi] += v // s
        if vec.max(initial=0) > 2**31 - 1:
            return None
        return vec.astype(np.int32)

    wl_usage = to_f_vec(ctx.workload_usage)
    if wl_usage is None:
        return None
    frs_mask = np.zeros(F, dtype=bool)
    for fr in ctx.frs_need_preemption:
        fi = packed.fr_index.get(fr)
        if fi is None:
            return None
        frs_mask[fi] = True

    K = _bucket(len(candidates))
    cand_cq = np.full(K, -1, dtype=np.int32)
    cand_delta = np.zeros((K, F), dtype=np.int32)
    cand_other = np.zeros(K, dtype=bool)
    cand_above = np.zeros(K, dtype=bool)
    for i, cand in enumerate(candidates):
        ci = cq_idx.get(cand.cluster_queue)
        if ci is None:
            return None
        delta = to_f_vec(cand.usage())
        if delta is None:
            return None
        cand_cq[i] = ci
        cand_delta[i] = delta
        cand_other[i] = cand.cluster_queue != ctx.preemptor_cq.name
        cand_above[i] = (threshold is not None
                         and cand.obj.priority >= threshold)

    import jax
    with jax.default_device(_cpu_device()):
        fitted, target_mask = minimal_preemptions(
            packed.usage0, packed.subtree_quota, packed.guaranteed,
            packed.borrow_cap, packed.has_borrow_limit, packed.parent,
            pre_cq, wl_usage, frs_mask, cand_cq, cand_delta, cand_other,
            cand_above, allow_borrowing, threshold is not None,
            depth=packed.depth)
    if not bool(fitted):
        return []
    mask = np.asarray(target_mask)
    targets = []
    for i, cand in enumerate(candidates):
        if not mask[i]:
            continue
        if not cand_other[i]:
            reason = IN_CLUSTER_QUEUE_REASON
        elif threshold is not None and cand.obj.priority < threshold:
            reason = IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
        else:
            reason = IN_COHORT_RECLAMATION_REASON
        targets.append(Target(info=cand, reason=reason))
    return targets
