"""Device kernels: the batched admission solver.

The reference's per-cycle admission loop (pkg/scheduler) is rebuilt here as
JAX array programs over packed (Workload × ClusterQueue × FlavorResource)
tensors: hierarchical quota as D-step parent-pointer recurrences, flavor
assignment as masked argmax over the flavor axis, and the sequential admit
loop as a lax.scan with the usage tensor as carry.  Semantics bit-match the
scalar oracle in kueue_tpu.scheduler (verified in tests/test_solver_parity).
"""

from .packing import PackedCycle, pack_cycle  # noqa: F401
from .solver import CycleSolver  # noqa: F401
