"""The batched admission cycle as one jitted program.

Phase 1 (vectorized nominate): classify every head against every flavor
slot at once — Fit / Preempt-capable / NoFit — mirroring
findFlavorForPodSetResource (flavorassigner.go:499) under the default
FlavorFungibility policy.

Phase 2 (lax.scan admit loop): entries ordered by (borrows, priority desc,
timestamp) as in entryOrdering.Less (scheduler.go:567); the usage tensor
[N, F] is the scan carry so later entries see earlier admissions — the
within-cycle sequential semantics of the reference admit loop.

Preemption-capable entries are flagged; when any exist the host falls back
to the scalar path for the whole cycle (bit-matching; device-side
preemption search lands in a later round).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .quota_kernel import available_all, add_usage_chain


@partial(jax.jit, static_argnames=("depth", "run_scan"))
def solve_cycle(usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
                nominal_cq, slot_fr, slot_valid, cq_can_preempt_borrow,
                wl_cq, wl_requests, wl_priority, wl_timestamp,
                *, depth: int, run_scan: bool = True):
    """Returns (admitted[W] bool, slot[W] int32, borrows[W] bool,
    preempt_possible[W] bool, fit_slot0[W] int32, borrows0[W] bool).

    With ``run_scan=False`` only the vectorized phase-1 classification runs
    (the caller consumes fit_slot0/borrows0 and drives the sequential admit
    loop host-side); the first three outputs are then zeros."""
    C = slot_fr.shape[0]
    W = wl_cq.shape[0]
    S = slot_fr.shape[1]

    avail0 = available_all(usage0, subtree, guaranteed, borrow_cap, has_blim,
                           parent, depth)
    potential0 = available_all(jnp.zeros_like(usage0), subtree, guaranteed,
                               borrow_cap, has_blim, parent, depth)

    def classify(avail, usage, wl_cq_i, req):
        """Per-workload slot classification given avail/usage tensors.

        Returns (fit_slot int32 or -1, borrows bool, preempt_possible bool).
        """
        cq = jnp.maximum(wl_cq_i, 0)
        frs = slot_fr[cq]                       # [S, R]
        frs_safe = jnp.maximum(frs, 0)
        covered = frs >= 0                      # [S, R]
        needed = req[None, :] > 0               # [1, R] broadcast
        # resource requested but not covered by this slot → slot invalid
        missing = jnp.any(needed & ~covered, axis=1)        # [S]
        av = avail[cq][frs_safe]                # [S, R] gather over F
        pot = potential0[cq][frs_safe]
        nom = nominal_cq[cq][frs_safe]
        use = usage[cq][frs_safe]               # CQ-local usage (for borrow calc)
        sq = subtree[cq][frs_safe]

        # Per-resource mode lattice (flavorassigner.go:692 fitsResourceQuota,
        # evaluated per resource; the slot's representative mode is the min):
        #   fit:     req <= available
        #   nofit:   req > potentialAvailable, or neither fit nor
        #            preempt-capable
        #   preempt: otherwise, if req <= nominal or the CQ may preempt
        #            while borrowing
        relevant = covered & needed
        fit_r = req[None, :] <= av              # [S, R]
        nofit_r = req[None, :] > pot
        preempt_capable_r = (req[None, :] <= nom) | cq_can_preempt_borrow[cq]
        res_nofit = relevant & (nofit_r | (~fit_r & ~preempt_capable_r))

        fit = (jnp.all(jnp.where(relevant, fit_r, True), axis=1)
               & ~missing & slot_valid[cq])     # [S]
        nofit = jnp.any(res_nofit, axis=1) | missing | ~slot_valid[cq]
        preempt = ~fit & ~nofit
        # borrowing: usage + req would exceed the CQ's own subtree quota,
        # and the CQ is in a cohort (clusterqueue_snapshot.go BorrowingWith)
        has_parent = parent[cq] >= 0
        borrow_r = jnp.where(relevant, use + req[None, :] > sq, False)
        borrows_s = jnp.any(borrow_r, axis=1) & has_parent   # [S]

        # default fungibility: first Fit slot wins (whenCanBorrow=Borrow)
        fit_idx = jnp.argmax(fit)
        has_fit = jnp.any(fit)
        fit_slot = jnp.where(has_fit, fit_idx, -1)
        borrows = jnp.where(has_fit, borrows_s[fit_idx], False)
        preempt_possible = ~has_fit & jnp.any(preempt)
        valid = wl_cq_i >= 0
        return (jnp.where(valid, fit_slot, -1),
                borrows & valid,
                preempt_possible & valid)

    fit_slot0, borrows0, preempt0 = jax.vmap(
        lambda c, r: classify(avail0, usage0, c, r))(wl_cq, wl_requests)

    if not run_scan:
        zeros_b = jnp.zeros(W, dtype=bool)
        zeros_i = jnp.full(W, -1, dtype=jnp.int32)
        return zeros_b, zeros_i, zeros_b, preempt0, fit_slot0, borrows0

    # --- ordering: borrows asc, priority desc, timestamp asc, index asc ---
    order = jnp.lexsort((jnp.arange(W), wl_timestamp, -wl_priority,
                         borrows0.astype(jnp.int32)))

    # --- sequential admit scan ---
    def step(usage, wi):
        wl_cq_i = wl_cq[wi]
        req = wl_requests[wi]
        avail = available_all(usage, subtree, guaranteed, borrow_cap,
                              has_blim, parent, depth)
        fit_slot, borrows, _ = classify(avail, usage, wl_cq_i, req)
        admit = fit_slot >= 0
        # scatter request into F space for the chosen slot
        cq = jnp.maximum(wl_cq_i, 0)
        frs = slot_fr[cq][jnp.maximum(fit_slot, 0)]      # [R]
        delta_f = jnp.zeros(usage.shape[1], dtype=usage.dtype)
        delta_f = delta_f.at[jnp.maximum(frs, 0)].add(
            jnp.where((frs >= 0) & admit, req, 0))
        new_usage = add_usage_chain(usage, cq, delta_f, guaranteed, parent,
                                    depth)
        usage = jnp.where(admit, new_usage, usage)
        return usage, (wi, admit, fit_slot, borrows)

    _, (order_out, admit_o, slot_o, borrows_o) = jax.lax.scan(
        step, usage0, order)

    # scatter back to original W order
    admitted = jnp.zeros(W, dtype=bool).at[order_out].set(admit_o)
    slots = jnp.full(W, -1, dtype=jnp.int32).at[order_out].set(slot_o)
    borrows = jnp.zeros(W, dtype=bool).at[order_out].set(borrows_o)

    return admitted, slots, borrows, preempt0, fit_slot0, borrows0


def add_usage_chain_batched(usage, nodes, deltas, guaranteed, parent,
                            depth: int):
    """add_usage_chain for G disjoint ancestor chains at once.

    nodes: [G] int32 (-1 = no-op); deltas: [G, F] int32.  Chains in
    different cohort forests never share nodes, so the per-level
    scatter-adds commute."""
    def body(i, state):
        usage, cur, carry = state                     # [G], [G, F]
        valid = cur >= 0
        cur_safe = jnp.maximum(cur, 0)
        local_avail = jnp.maximum(0, guaranteed[cur_safe] - usage[cur_safe])
        add = jnp.where(valid[:, None], carry, 0)
        usage = usage.at[cur_safe].add(add)
        next_carry = jnp.maximum(0, carry - local_avail)
        next_cur = jnp.where(valid, parent[cur_safe], -1)
        return usage, next_cur, jnp.where(valid[:, None], next_carry, carry)

    usage, _, _ = jax.lax.fori_loop(
        0, depth, body, (usage, nodes.astype(jnp.int32), deltas))
    return usage


@partial(jax.jit, static_argnames=("depth", "n_forests", "max_forest_wl"))
def solve_cycle_forests(usage0, subtree, guaranteed, borrow_cap, has_blim,
                        parent, nominal_cq, slot_fr, slot_valid,
                        cq_can_preempt_borrow, wl_cq, wl_requests,
                        wl_priority, wl_timestamp, forest_of_node,
                        *, depth: int, n_forests: int, max_forest_wl: int):
    """The admit scan parallelized over independent cohort forests.

    Quota never flows between forests, so the sequential within-cycle
    semantics only constrain workloads of the SAME forest; each scan step
    admits one workload per forest simultaneously (scatter-adds on
    disjoint chains).  Scan length drops from W to max_forest_wl — the
    lever that takes the north-star 1k-head cycle from O(heads) to
    O(heads / forests) (SURVEY §7 hard part (a), exploited structurally).

    Decision-identical to solve_cycle(run_scan=True); enforced by
    tests/test_forest_scan.py."""
    W = wl_cq.shape[0]
    G = n_forests + 1                       # + padding bucket

    # phase 1 + global ordering (identical to solve_cycle)
    _, _, _, preempt0, fit_slot0, borrows0 = solve_cycle(
        usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
        nominal_cq, slot_fr, slot_valid, cq_can_preempt_borrow,
        wl_cq, wl_requests, wl_priority, wl_timestamp,
        depth=depth, run_scan=False)
    order = jnp.lexsort((jnp.arange(W), wl_timestamp, -wl_priority,
                         borrows0.astype(jnp.int32)))
    inv_order = jnp.zeros(W, dtype=jnp.int32).at[order].set(
        jnp.arange(W, dtype=jnp.int32))

    f_w = jnp.where(wl_cq >= 0,
                    forest_of_node[jnp.maximum(wl_cq, 0)], n_forests)
    # group by forest, cycle order within each group
    p = jnp.lexsort((inv_order, f_w))                    # [W]
    f_sorted = f_w[p]
    first = jnp.concatenate([jnp.array([True]),
                             f_sorted[1:] != f_sorted[:-1]])
    pos = jnp.arange(W)
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, pos, 0))
    rank = (pos - seg_start).astype(jnp.int32)           # in-forest rank
    mat = jnp.full((G, max_forest_wl), -1, dtype=jnp.int32)
    # ranks beyond max_forest_wl are dropped (host sizes the bucket)
    mat = mat.at[f_sorted, rank].set(p.astype(jnp.int32), mode="drop")

    def classify_g(avail, usage, wi):
        """Per-forest step: classify workload wi (or -1)."""
        wl_cq_i = jnp.where(wi >= 0, wl_cq[jnp.maximum(wi, 0)], -1)
        valid = wl_cq_i >= 0
        req = wl_requests[jnp.maximum(wi, 0)]
        # reuse the classification from solve_cycle via a fresh pass
        cq = jnp.maximum(wl_cq_i, 0)
        frs = slot_fr[cq]
        frs_safe = jnp.maximum(frs, 0)
        covered = frs >= 0
        needed = req[None, :] > 0
        missing = jnp.any(needed & ~covered, axis=1)
        av = avail[cq][frs_safe]
        nom = nominal_cq[cq][frs_safe]
        use = usage[cq][frs_safe]
        sq = subtree[cq][frs_safe]
        relevant = covered & needed
        fit_r = req[None, :] <= av
        fit = (jnp.all(jnp.where(relevant, fit_r, True), axis=1)
               & ~missing & slot_valid[cq])
        has_parent = parent[cq] >= 0
        borrow_r = jnp.where(relevant, use + req[None, :] > sq, False)
        borrows_s = jnp.any(borrow_r, axis=1) & has_parent
        fit_idx = jnp.argmax(fit)
        has_fit = jnp.any(fit) & valid
        fit_slot = jnp.where(has_fit, fit_idx, -1)
        borrows = jnp.where(has_fit, borrows_s[fit_idx], False)
        return fit_slot, borrows

    def step(usage, col):
        wis = mat[:, col]                                # [G]
        avail = available_all(usage, subtree, guaranteed, borrow_cap,
                              has_blim, parent, depth)
        fit_slot, borrows = jax.vmap(
            lambda wi: classify_g(avail, usage, wi))(wis)
        admit = fit_slot >= 0
        cqs = jnp.where(admit, wl_cq[jnp.maximum(wis, 0)], -1)
        frs = slot_fr[jnp.maximum(cqs, 0),
                      jnp.maximum(fit_slot, 0)]          # [G, R]
        reqs = wl_requests[jnp.maximum(wis, 0)]          # [G, R]
        deltas = jnp.zeros((G, usage.shape[1]), dtype=usage.dtype)
        deltas = deltas.at[jnp.arange(G)[:, None],
                           jnp.maximum(frs, 0)].add(
            jnp.where((frs >= 0) & admit[:, None], reqs, 0))
        usage = add_usage_chain_batched(usage, cqs, deltas, guaranteed,
                                        parent, depth)
        return usage, (wis, admit, fit_slot, borrows)

    _, (wis_o, admit_o, slot_o, borrows_o) = jax.lax.scan(
        step, usage0, jnp.arange(max_forest_wl))

    wis_flat = wis_o.reshape(-1)
    safe = jnp.maximum(wis_flat, 0)
    mask = wis_flat >= 0
    admitted = jnp.zeros(W, dtype=bool).at[safe].max(
        admit_o.reshape(-1) & mask)
    slots = jnp.full(W, -1, dtype=jnp.int32).at[safe].max(
        jnp.where(mask, slot_o.reshape(-1), -1))
    borrows = jnp.zeros(W, dtype=bool).at[safe].max(
        borrows_o.reshape(-1) & mask)
    return admitted, slots, borrows, preempt0, fit_slot0, borrows0
