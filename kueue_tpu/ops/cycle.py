"""The batched admission cycle: vectorized nominate + sequential admit scan.

Phase 1 (vectorized nominate): classify every head against every flavor
slot at once — Fit / Preempt-capable / NoFit — mirroring
findFlavorForPodSetResource (flavorassigner.go:499) under the default
FlavorFungibility policy.  Production runs this phase in numpy on the host
(``classify_np``): it is O(W·S·R) array math, and keeping it host-side
avoids a device round-trip before the admit scan is dispatched.

Phase 2 (``admit_scan``): the sequential admit loop as one jitted
``lax.scan`` over the cycle order.  Assignments are FIXED at nominate time
(phase 1) — each step only re-checks that the chosen slot still fits under
the usage mutated by earlier steps, exactly like the reference admit loop
(scheduler.go:245 fits re-check; it never re-runs flavor assignment).
Preempt-classified entries with no preemption candidates reserve capacity
(resourcesToReserve, scheduler.go:383-408) so later entries can't jump
ahead.

``solve_cycle`` / ``solve_cycle_forests`` keep the one-call probe/test
surface (phase 1 + scan in a single jitted program).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .quota_kernel import available_all, available_at, add_usage_chain


# ----------------------------------------------------------------------
# Host-side (numpy) phase 1
# ----------------------------------------------------------------------

def available_all_np(usage, subtree, guaranteed, borrow_cap, has_blim,
                     parent, depth: int) -> np.ndarray:
    """numpy twin of quota_kernel.available_all (resource_node.go:89)."""
    is_root = (parent < 0)[:, None]
    parent_safe = np.maximum(parent, 0)
    root_avail = subtree.astype(np.int64) - usage
    local = np.maximum(0, guaranteed.astype(np.int64) - usage)
    used_in_parent = np.maximum(0, usage.astype(np.int64) - guaranteed)
    blim_cap = borrow_cap.astype(np.int64) - used_in_parent
    avail = root_avail.copy()
    for _ in range(depth):
        parent_avail = avail[parent_safe]
        parent_avail = np.where(has_blim, np.minimum(blim_cap, parent_avail),
                                parent_avail)
        avail = np.where(is_root, root_avail, local + parent_avail)
    return avail


def classify_np(packed, avail0=None, potential0=None, start_slot=None):
    """Vectorized nominate on the host: per-head slot classification.

    The per-head flavor walk (flavorassigner.go:499) is evaluated dense
    over all slots and then resolved under the CQ's FlavorFungibility
    policy: a slot STOPS the walk when it fits without borrowing, fits
    borrowing under whenCanBorrow=Borrow, or is preempt-capable under
    whenCanPreempt=Preempt (shouldTryNextFlavor, :620); otherwise the
    walk keeps the best-mode slot seen (Fit > Preempt > NoFit, first
    occurrence wins), with a stop slot overriding any earlier best.
    ``start_slot`` [W] carries the fungibility resume index
    (last_tried_flavor_idx + 1); slots below it are never attempted.

    Returns a dict of [W]-shaped arrays:
      fit_slot0     the walk's chosen Fit slot or -1
      borrows0      the fit assignment borrows
      preempt0      no fit chosen, the walk chose a preempt-capable slot
      preempt_slot0 that slot
      preempt_borrows0  that preempt assignment borrows
      preempt_res_fit   [W, R] per-resource Fit flag on the preempt slot
                    (False ⇒ the resource is the one needing preemption)
      preempt_stopped0  the walk STOPPED at the preempt slot (the choice
                    is policy-forced, independent of the reclaim oracle)
    """
    st = packed.structure
    usage0 = packed.usage0
    if avail0 is None:
        avail0 = available_all_np(
            usage0, st.subtree_quota, st.guaranteed, st.borrow_cap,
            st.has_borrow_limit, st.parent, st.depth)
    if potential0 is None:
        potential0 = available_all_np(
            np.zeros_like(usage0), st.subtree_quota, st.guaranteed,
            st.borrow_cap, st.has_borrow_limit, st.parent, st.depth)

    wl_cq = packed.wl_cq
    req = packed.wl_requests.astype(np.int64)[:, None, :]   # [W,1,R]
    cqs = np.maximum(wl_cq, 0)
    frs = st.slot_fr[cqs]                                   # [W,S,R]
    frs_safe = np.maximum(frs, 0)
    covered = frs >= 0
    needed = req > 0
    missing = np.any(needed & ~covered, axis=2)             # [W,S]
    av = avail0[cqs[:, None, None], frs_safe]               # [W,S,R]
    pot = potential0[cqs[:, None, None], frs_safe]
    nom = st.nominal_cq[cqs[:, None, None], frs_safe]
    use = usage0[cqs[:, None, None], frs_safe]
    sq = st.subtree_quota[cqs[:, None, None], frs_safe]

    relevant = covered & needed
    fit_r = req <= av
    nofit_r = req > pot
    preempt_capable_r = (req <= nom) | st.cq_can_preempt_borrow[cqs][:, None, None]
    res_nofit = relevant & (nofit_r | (~fit_r & ~preempt_capable_r))

    slot_ok = st.slot_valid[cqs]
    fit_s = (np.all(np.where(relevant, fit_r, True), axis=2)
             & ~missing & slot_ok)                          # [W,S]
    nofit_s = np.any(res_nofit, axis=2) | missing | ~slot_ok
    preempt_s = ~fit_s & ~nofit_s
    has_parent = st.parent[cqs] >= 0
    borrow_r = np.where(relevant, use + req > sq, False)
    borrows_s = np.any(borrow_r, axis=2) & has_parent[:, None]

    valid = wl_cq >= 0
    W = len(cqs)
    S = fit_s.shape[1]
    w = np.arange(W)
    wcb = st.cq_wcb_borrow[cqs]
    wcp = st.cq_wcp_preempt[cqs]
    if start_slot is None:
        start = np.zeros(W, dtype=np.int32)
    else:
        start = np.asarray(start_slot, dtype=np.int32)
    active_s = np.arange(S)[None, :] >= start[:, None]      # [W, S]
    stop_s = (active_s & (fit_s | (preempt_s & wcp[:, None]))
              & (~borrows_s | wcb[:, None]))
    has_stop = np.any(stop_s, axis=1)
    stop_idx = np.argmax(stop_s, axis=1)
    act_mode = np.where(active_s,
                        np.where(fit_s, 2, np.where(preempt_s, 1, 0)), 0)
    best_mode = act_mode.max(axis=1)
    best_idx = np.argmax((act_mode == best_mode[:, None]) & active_s,
                         axis=1)
    chosen = np.where(has_stop, stop_idx, best_idx)
    chosen_mode = act_mode[w, chosen]

    has_fit = (chosen_mode == 2) & valid
    fit_slot0 = np.where(has_fit, chosen, -1).astype(np.int32)
    borrows0 = borrows_s[w, chosen] & has_fit

    has_preempt = (chosen_mode == 1) & valid
    preempt_slot0 = np.where(has_preempt, chosen, -1).astype(np.int32)
    preempt_borrows0 = borrows_s[w, chosen] & has_preempt
    # per-resource fit on the preempt slot (for frs_need_preemption)
    preempt_res_fit = fit_r[w, chosen] | ~relevant[w, chosen]
    # how many attempted slots are preempt-capable: with exactly one, the
    # host walk picks it regardless of the reclaim oracle (the oracle only
    # reorders among preempt-capable flavors — flavorassigner.go:692
    # RECLAIM vs PREEMPT), so the device may fix the slot without running
    # the oracle; a policy STOP at the slot forces it the same way
    preempt_slot_count = (preempt_s & active_s).sum(axis=1).astype(np.int32)
    preempt_stopped0 = has_preempt & has_stop

    return {
        "fit_slot0": fit_slot0,
        "borrows0": borrows0,
        "preempt0": has_preempt,
        "preempt_slot0": preempt_slot0,
        "preempt_borrows0": preempt_borrows0,
        "preempt_res_fit": preempt_res_fit,
        "preempt_slot_count": preempt_slot_count,
        "preempt_stopped0": preempt_stopped0,
        "avail0": avail0,
        "potential0": potential0,
    }


def cycle_order_np(borrows, priority, timestamp) -> np.ndarray:
    """entryOrdering.Less (scheduler.go:567): borrows asc, priority desc,
    timestamp asc, stable."""
    W = len(priority)
    return np.lexsort((np.arange(W), timestamp, -priority,
                       borrows.astype(np.int32))).astype(np.int32)


# ----------------------------------------------------------------------
# Device admit scan (fixed assignments; the production phase 2)
# ----------------------------------------------------------------------

def _entry_decision(avail_row, usage, wi, valid, *, nominal_cq, npb_cq,
                    wl_cq, dec_fr, dec_amt, fit_mask, res_fr, res_amt,
                    res_mask, res_borrows):
    """The per-entry decision shared by admit_scan and admit_scan_forests:
    fixed-assignment fit re-check (scheduler.go:372, Fits over
    assignment.Usage) or capacity reserve (resourcesToReserve,
    scheduler.go:383-408).

    Decisions are (flavor-resource, amount) pairs [K] per head — exactly
    the assignment.Usage map the reference re-checks — so multi-resource-
    group and multi-PodSet assignments need no special casing here.  The
    packer guarantees each head's pairs have distinct flavor-resources.

    Returns (admit, node, delta_f): node is the CQ to charge (-1 = no-op)."""
    wis = jnp.maximum(wi, 0)
    cq = jnp.maximum(wl_cq[wis], 0)
    F = usage.shape[1]

    frs = dec_fr[wis]                                       # [K]
    amt = dec_amt[wis]
    frs_safe = jnp.maximum(frs, 0)
    relevant = frs >= 0
    ok = jnp.all(jnp.where(relevant, amt <= avail_row[frs_safe], True))
    admit = fit_mask[wis] & valid & ok
    delta_f = jnp.zeros(F, dtype=usage.dtype).at[frs_safe].add(
        jnp.where(relevant & admit, amt, 0))

    is_res = res_mask[wis] & valid
    rfrs = res_fr[wis]
    ramt = res_amt[wis]
    rfrs_safe = jnp.maximum(rfrs, 0)
    rrel = rfrs >= 0
    cur = usage[cq][rfrs_safe]
    res_borrow = jnp.minimum(ramt, npb_cq[cq][rfrs_safe] - cur)
    res_nob = jnp.maximum(0, jnp.minimum(ramt, nominal_cq[cq][rfrs_safe] - cur))
    rdelta = jnp.where(res_borrows[wis], res_borrow, res_nob)
    delta_f = delta_f.at[rfrs_safe].add(
        jnp.where(rrel & is_res, rdelta, 0))

    node = jnp.where(admit | is_res, wl_cq[wis], -1)
    return admit, node, delta_f


def _admit_step(usage, wi, *, subtree, guaranteed, borrow_cap, has_blim,
                parent, nominal_cq, npb_cq, wl_cq, dec_fr, dec_amt,
                fit_mask, res_fr, res_amt, res_mask, res_borrows, depth):
    """One cycle-order step: fit re-check + admit, or capacity reserve.

    Availability is computed chain-locally for the entry's CQ only
    (O(depth·F) per step, not O(N·F)) — the fits re-check never looks at
    another CQ's row."""
    cq = jnp.maximum(wl_cq[jnp.maximum(wi, 0)], 0)
    avail_row = available_at(usage, subtree, guaranteed, borrow_cap,
                             has_blim, parent, cq, depth)
    admit, node, delta_f = _entry_decision(
        avail_row, usage, wi, wl_cq[wi] >= 0,
        nominal_cq=nominal_cq, npb_cq=npb_cq, wl_cq=wl_cq,
        dec_fr=dec_fr, dec_amt=dec_amt, fit_mask=fit_mask,
        res_fr=res_fr, res_amt=res_amt, res_mask=res_mask,
        res_borrows=res_borrows)
    usage = add_usage_chain(usage, node, delta_f, guaranteed, parent, depth)
    return usage, admit


@partial(jax.jit, static_argnames=("depth",))
def admit_scan(usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
               nominal_cq, npb_cq, wl_cq, dec_fr, dec_amt, fit_mask,
               res_fr, res_amt, res_mask, res_borrows,
               order, *, depth: int):
    """The sequential admit loop over ``order`` as one lax.scan.

    Returns admitted[W] (original head order).  Decision-identical to the
    host admit loop for cycles whose preempt entries all have zero
    preemption candidates (the solver checks that before dispatching)."""
    W = wl_cq.shape[0]
    step = partial(_admit_step, subtree=subtree, guaranteed=guaranteed,
                   borrow_cap=borrow_cap, has_blim=has_blim, parent=parent,
                   nominal_cq=nominal_cq, npb_cq=npb_cq, wl_cq=wl_cq,
                   dec_fr=dec_fr, dec_amt=dec_amt, fit_mask=fit_mask,
                   res_fr=res_fr, res_amt=res_amt, res_mask=res_mask,
                   res_borrows=res_borrows, depth=depth)
    _, admit_o = jax.lax.scan(step, usage0, order)
    return jnp.zeros(W, dtype=bool).at[order].set(admit_o)


# ----------------------------------------------------------------------
# Preemption-aware admit scan (cycles whose preempt heads have targets)
# ----------------------------------------------------------------------

def _remove_usage_chain(usage, node, delta, guaranteed, parent, depth):
    """remove_usage bubbling up one ancestor chain (resource_node.go:135)."""
    def body(i, state):
        usage, cur, carry = state
        valid = cur >= 0
        cur_safe = jnp.maximum(cur, 0)
        stored_in_parent = usage[cur_safe] - guaranteed[cur_safe]
        sub = jnp.where(valid, carry, 0)
        usage = usage.at[cur_safe].add(-sub)
        next_carry = jnp.where(stored_in_parent > 0,
                               jnp.minimum(carry, stored_in_parent), 0)
        next_cur = jnp.where(valid, parent[cur_safe], -1)
        return usage, next_cur, jnp.where(valid, next_carry, carry)

    usage, _, _ = jax.lax.fori_loop(
        0, depth, body, (usage, node.astype(jnp.int32), delta))
    return usage


def _preempt_entry_decision(usage, usage_check, used, wi, valid,
                            *, nominal_cq, npb_cq, wl_cq, dec_fr, dec_amt,
                            fit_mask, res_fr, res_amt, res_mask,
                            res_borrows, preempt_mask, pre_fr, pre_amt,
                            tgt_mat, tu_cq, tu_delta, guaranteed, parent,
                            subtree, borrow_cap, has_blim, depth):
    """One entry of the preemption-aware admit loop.

    Mirrors the reference admit loop (scheduler.go:211-284) with
    preemptions: every fits check runs against usage minus the
    already-preempted targets (scheduler.go:372 fits under
    PreemptedWorkloads), preempt entries remove their own targets first
    (_fits_with_removal), overlapping targets skip the entry, and both
    admitted and preempting entries charge their usage forward.

    Returns (admit, preempting, overlap_skip, node, delta_f, u_try,
    used_next): ``node`` is the CQ to charge (-1 no-op); ``u_try`` is the
    check-usage after this entry's target removals (committed by the
    caller only when the entry preempts)."""
    wis = jnp.maximum(wi, 0)
    cq = jnp.maximum(wl_cq[wis], 0)
    F = usage.shape[1]
    MT = tgt_mat.shape[1]

    # --- fit entry: re-check the fixed pairs against the check state
    # (chain-local availability at the entry's CQ only) ---
    avail_check = available_at(usage_check, subtree, guaranteed, borrow_cap,
                               has_blim, parent, cq, depth)
    frs = dec_fr[wis]
    amt = dec_amt[wis]
    frs_safe = jnp.maximum(frs, 0)
    relevant = frs >= 0
    fit_ok = jnp.all(jnp.where(relevant, amt <= avail_check[frs_safe],
                               True))
    admit = fit_mask[wis] & valid & fit_ok
    delta_f = jnp.zeros(F, dtype=usage.dtype).at[frs_safe].add(
        jnp.where(relevant & admit, amt, 0))

    # --- preempt entry: overlap check + remove targets + fits ---
    is_pre = preempt_mask[wis] & valid
    tgts = tgt_mat[wis]                                    # [MT]
    t_valid = tgts >= 0
    t_safe = jnp.maximum(tgts, 0)
    overlap = jnp.any(used[t_safe] & t_valid)
    overlap_skip = is_pre & overlap
    act_pre = is_pre & ~overlap

    def rm(j, u):
        do = t_valid[j] & act_pre
        u2 = _remove_usage_chain(u, tu_cq[t_safe[j]], tu_delta[t_safe[j]],
                                 guaranteed, parent, depth)
        return jnp.where(do, u2, u)

    u_try = jax.lax.fori_loop(0, MT, rm, usage_check)
    avail_try = available_at(u_try, subtree, guaranteed, borrow_cap,
                             has_blim, parent, cq, depth)
    pfrs = pre_fr[wis]
    pamt = pre_amt[wis]
    pfrs_safe = jnp.maximum(pfrs, 0)
    p_rel = pfrs >= 0
    pre_ok = jnp.all(jnp.where(p_rel, pamt <= avail_try[pfrs_safe], True))
    preempting = act_pre & pre_ok
    pre_delta = jnp.zeros(F, dtype=usage.dtype).at[pfrs_safe].add(
        jnp.where(p_rel & preempting, pamt, 0))
    delta_f = delta_f + pre_delta
    # max-scatter: pads share index 0 with real targets; a duplicate
    # .set's winner is undefined, while max(used, mark) is order-free
    used_next = used.at[t_safe].max(t_valid & preempting)

    # --- reserve entry (unchanged semantics) ---
    is_res = res_mask[wis] & valid
    rfrs = res_fr[wis]
    ramt = res_amt[wis]
    rfrs_safe = jnp.maximum(rfrs, 0)
    rrel = rfrs >= 0
    cur = usage[cq][rfrs_safe]
    res_borrow = jnp.minimum(ramt, npb_cq[cq][rfrs_safe] - cur)
    res_nob = jnp.maximum(0, jnp.minimum(ramt, nominal_cq[cq][rfrs_safe] - cur))
    rdelta = jnp.where(res_borrows[wis], res_borrow, res_nob)
    delta_f = delta_f.at[rfrs_safe].add(jnp.where(rrel & is_res, rdelta, 0))

    node = jnp.where(admit | preempting | is_res, wl_cq[wis], -1)
    return admit, preempting, overlap_skip, node, delta_f, u_try, used_next


@partial(jax.jit, static_argnames=("depth",))
def admit_scan_preempt(usage0, subtree, guaranteed, borrow_cap, has_blim,
                       parent, nominal_cq, npb_cq, wl_cq, dec_fr, dec_amt,
                       fit_mask, res_fr, res_amt, res_mask, res_borrows,
                       preempt_mask, pre_fr, pre_amt, tgt_mat, tu_cq,
                       tu_delta, order, *, depth: int):
    """``admit_scan`` extended with preempting entries.

    Carries (usage, usage_check, used): ``usage`` follows the reference's
    live snapshot (admits + reserves + preemptor additions, targets NOT
    removed — scheduler.go:272 simulate), ``usage_check`` additionally has
    every preempted target removed (the state `fits` checks against,
    scheduler.go:372-381), ``used`` is the PreemptedWorkloads set.

    Returns (admitted[W], preempting[W], overlap_skip[W]) in head order."""
    W = wl_cq.shape[0]
    T = tu_cq.shape[0]

    def step(carry, wi):
        usage, usage_check, used = carry
        admit, preempting, overlap_skip, node, delta_f, u_try, used = (
            _preempt_entry_decision(
                usage, usage_check, used, wi, wl_cq[wi] >= 0,
                nominal_cq=nominal_cq, npb_cq=npb_cq, wl_cq=wl_cq,
                dec_fr=dec_fr, dec_amt=dec_amt, fit_mask=fit_mask,
                res_fr=res_fr, res_amt=res_amt, res_mask=res_mask,
                res_borrows=res_borrows, preempt_mask=preempt_mask,
                pre_fr=pre_fr, pre_amt=pre_amt,
                tgt_mat=tgt_mat, tu_cq=tu_cq, tu_delta=tu_delta,
                guaranteed=guaranteed, parent=parent, subtree=subtree,
                borrow_cap=borrow_cap, has_blim=has_blim, depth=depth))
        usage = add_usage_chain(usage, node, delta_f, guaranteed, parent,
                                depth)
        base_check = jnp.where(preempting, u_try, usage_check)
        usage_check = add_usage_chain(base_check, node, delta_f, guaranteed,
                                      parent, depth)
        return (usage, usage_check, used), (admit, preempting, overlap_skip)

    used0 = jnp.zeros(T, dtype=bool)
    _, (admit_o, pre_o, skip_o) = jax.lax.scan(
        step, (usage0, usage0, used0), order)
    z = jnp.zeros(W, dtype=bool)
    return (z.at[order].set(admit_o), z.at[order].set(pre_o),
            z.at[order].set(skip_o))


# ----------------------------------------------------------------------
# One-call solvers (probe / parity-test surface)
# ----------------------------------------------------------------------

@partial(jax.jit, static_argnames=("depth", "run_scan"))
def solve_cycle(usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
                nominal_cq, slot_fr, slot_valid, cq_can_preempt_borrow,
                wl_cq, wl_requests, wl_priority, wl_timestamp,
                cq_wcb_borrow=None, cq_wcp_preempt=None, start_slot=None,
                *, depth: int, run_scan: bool = True):
    """Returns (admitted[W] bool, slot[W] int32, borrows[W] bool,
    preempt_possible[W] bool, fit_slot0[W] int32, borrows0[W] bool).

    Phase 1 classifies each head once against the snapshot usage; the scan
    then admits in cycle order with a fits re-check on the FIXED slot —
    the reference admit-loop semantics (assignments are never recomputed
    within a cycle).  With ``run_scan=False`` only phase 1 runs.

    ``cq_wcb_borrow``/``cq_wcp_preempt`` [C] carry the FlavorFungibility
    policy per CQ and ``start_slot`` [W] the fungibility resume index;
    omitted, the default policy (whenCanBorrow=Borrow,
    whenCanPreempt=TryNextFlavor) walks every slot from 0 — the legacy
    classify surface."""
    C = slot_fr.shape[0]
    W = wl_cq.shape[0]
    S = slot_fr.shape[1]
    if cq_wcb_borrow is None:
        cq_wcb_borrow = jnp.ones(C, dtype=bool)
    if cq_wcp_preempt is None:
        cq_wcp_preempt = jnp.zeros(C, dtype=bool)
    if start_slot is None:
        start_slot = jnp.zeros(W, dtype=jnp.int32)

    avail0 = available_all(usage0, subtree, guaranteed, borrow_cap, has_blim,
                           parent, depth)
    potential0 = available_all(jnp.zeros_like(usage0), subtree, guaranteed,
                               borrow_cap, has_blim, parent, depth)

    def classify(wl_cq_i, req, start_i):
        cq = jnp.maximum(wl_cq_i, 0)
        frs = slot_fr[cq]                       # [S, R]
        frs_safe = jnp.maximum(frs, 0)
        covered = frs >= 0
        needed = req[None, :] > 0
        missing = jnp.any(needed & ~covered, axis=1)        # [S]
        av = avail0[cq][frs_safe]               # [S, R]
        pot = potential0[cq][frs_safe]
        nom = nominal_cq[cq][frs_safe]
        use = usage0[cq][frs_safe]
        sq = subtree[cq][frs_safe]

        relevant = covered & needed
        fit_r = req[None, :] <= av
        nofit_r = req[None, :] > pot
        preempt_capable_r = (req[None, :] <= nom) | cq_can_preempt_borrow[cq]
        res_nofit = relevant & (nofit_r | (~fit_r & ~preempt_capable_r))

        fit = (jnp.all(jnp.where(relevant, fit_r, True), axis=1)
               & ~missing & slot_valid[cq])     # [S]
        nofit = jnp.any(res_nofit, axis=1) | missing | ~slot_valid[cq]
        preempt = ~fit & ~nofit
        has_parent = parent[cq] >= 0
        borrow_r = jnp.where(relevant, use + req[None, :] > sq, False)
        borrows_s = jnp.any(borrow_r, axis=1) & has_parent   # [S]

        # fungibility walk (classify_np twin): stop slots override the
        # best-mode slot; slots below the resume index are not attempted
        wcb = cq_wcb_borrow[cq]
        wcp = cq_wcp_preempt[cq]
        active = jnp.arange(S) >= start_i                    # [S]
        stop = active & (fit | (preempt & wcp)) & (~borrows_s | wcb)
        has_stop = jnp.any(stop)
        act_mode = jnp.where(active,
                             jnp.where(fit, 2, jnp.where(preempt, 1, 0)),
                             0)
        best_idx = jnp.argmax(act_mode == act_mode.max())
        chosen = jnp.where(has_stop, jnp.argmax(stop), best_idx)
        chosen_mode = act_mode[chosen]

        has_fit = chosen_mode == 2
        fit_slot = jnp.where(has_fit, chosen, -1)
        borrows = jnp.where(has_fit, borrows_s[chosen], False)
        preempt_possible = chosen_mode == 1
        valid = wl_cq_i >= 0
        return (jnp.where(valid, fit_slot, -1),
                borrows & valid,
                preempt_possible & valid)

    fit_slot0, borrows0, preempt0 = jax.vmap(classify)(
        wl_cq, wl_requests, start_slot)

    if not run_scan:
        zeros_b = jnp.zeros(W, dtype=bool)
        zeros_i = jnp.full(W, -1, dtype=jnp.int32)
        return zeros_b, zeros_i, zeros_b, preempt0, fit_slot0, borrows0

    order = jnp.lexsort((jnp.arange(W), wl_timestamp, -wl_priority,
                         borrows0.astype(jnp.int32)))
    no_reserve = jnp.zeros(W, dtype=bool)
    dec_fr, dec_amt, fit_mask = decision_pairs_from_slots(
        slot_fr, wl_cq, wl_requests, fit_slot0)
    zero_pairs = jnp.full_like(dec_fr, -1)
    admitted = admit_scan(
        usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
        nominal_cq, jnp.zeros_like(nominal_cq), wl_cq, dec_fr, dec_amt,
        fit_mask, zero_pairs, jnp.zeros_like(dec_amt), no_reserve,
        no_reserve, order, depth=depth)
    slots = jnp.where(admitted, fit_slot0, -1).astype(jnp.int32)
    borrows = borrows0 & admitted
    return admitted, slots, borrows, preempt0, fit_slot0, borrows0


def decision_pairs_from_slots(slot_fr, wl_cq, wl_requests, fit_slot0):
    """Single-slot classifications → decision pairs (jax or numpy).

    dec_fr/dec_amt [W, R]: the chosen slot's flavor-resource per requested
    resource (-1 where not requested or not fit); fit_mask [W]."""
    xp = jnp if isinstance(wl_cq, jnp.ndarray) else np
    cqs = xp.maximum(wl_cq, 0)
    slots = xp.maximum(fit_slot0, 0)
    frs = slot_fr[cqs, slots]                               # [W, R]
    fit_mask = (fit_slot0 >= 0) & (wl_cq >= 0)
    relevant = (frs >= 0) & (wl_requests > 0) & fit_mask[:, None]
    dec_fr = xp.where(relevant, frs, -1).astype(xp.int32)
    dec_amt = xp.where(relevant, wl_requests, 0).astype(xp.int32)
    return dec_fr, dec_amt, fit_mask


def add_usage_chain_batched(usage, nodes, deltas, guaranteed, parent,
                            depth: int):
    """add_usage_chain for G disjoint ancestor chains at once.

    nodes: [G] int32 (-1 = no-op); deltas: [G, F] int32.  Chains in
    different cohort forests never share nodes, so the per-level
    scatter-adds commute."""
    def body(i, state):
        usage, cur, carry = state                     # [G], [G, F]
        valid = cur >= 0
        cur_safe = jnp.maximum(cur, 0)
        local_avail = jnp.maximum(0, guaranteed[cur_safe] - usage[cur_safe])
        add = jnp.where(valid[:, None], carry, 0)
        usage = usage.at[cur_safe].add(add)
        next_carry = jnp.maximum(0, carry - local_avail)
        next_cur = jnp.where(valid, parent[cur_safe], -1)
        return usage, next_cur, jnp.where(valid[:, None], next_carry, carry)

    usage, _, _ = jax.lax.fori_loop(
        0, depth, body, (usage, nodes.astype(jnp.int32), deltas))
    return usage


def _forest_schedule(order, f_w, W, G, max_forest_wl):
    """Group entries by forest, cycle order within each group → [G, L]."""
    inv_order = jnp.zeros(W, dtype=jnp.int32).at[order].set(
        jnp.arange(W, dtype=jnp.int32))
    p = jnp.lexsort((inv_order, f_w))                    # [W]
    f_sorted = f_w[p]
    pos = jnp.arange(W)
    # each segment's start = index of the first element with its forest
    # id; searchsorted on the sorted ids gives it directly.  NOT a
    # prefix max over flagged starts: lax.associative_scan miscomputes
    # under GSPMD sharding (observed on the (wl, cq) production mesh —
    # positions read partial maxima from other shards' blocks), and
    # sort-family ops gather correctly where the scan lowering does not
    seg_start = jnp.searchsorted(f_sorted, f_sorted, side="left")
    rank = (pos - seg_start).astype(jnp.int32)           # in-forest rank
    mat = jnp.full((G, max_forest_wl), -1, dtype=jnp.int32)
    # ranks beyond max_forest_wl are dropped (host sizes the bucket)
    return mat.at[f_sorted, rank].set(p.astype(jnp.int32), mode="drop")


@partial(jax.jit, static_argnames=("depth", "n_forests", "max_forest_wl"))
def admit_scan_forests(usage0, subtree, guaranteed, borrow_cap, has_blim,
                       parent, nominal_cq, npb_cq, wl_cq, dec_fr, dec_amt,
                       fit_mask, res_fr, res_amt, res_mask, res_borrows,
                       order, forest_of_node,
                       *, depth: int, n_forests: int, max_forest_wl: int):
    """``admit_scan`` parallelized over independent cohort forests.

    Quota never flows between forests, so the sequential within-cycle
    semantics only constrain workloads of the SAME forest; each scan step
    processes one workload per forest simultaneously (scatter-adds on
    disjoint chains).  Scan length drops from W to max_forest_wl — the
    lever that takes a 1k-head cycle from O(heads) to O(heads / forests).
    Decision-identical to admit_scan (tests/test_forest_scan.py)."""
    W = wl_cq.shape[0]
    G = n_forests + 1                       # + padding bucket

    f_w = jnp.where(wl_cq >= 0,
                    forest_of_node[jnp.maximum(wl_cq, 0)], n_forests)
    mat = _forest_schedule(order, f_w, W, G, max_forest_wl)

    def step(usage, col):
        wis = mat[:, col]                                # [G]

        def entry(wi):
            cq = jnp.maximum(wl_cq[jnp.maximum(wi, 0)], 0)
            avail_row = available_at(usage, subtree, guaranteed,
                                     borrow_cap, has_blim, parent, cq,
                                     depth)
            return _entry_decision(
                avail_row, usage, wi,
                (wi >= 0) & (wl_cq[jnp.maximum(wi, 0)] >= 0),
                nominal_cq=nominal_cq, npb_cq=npb_cq,
                wl_cq=wl_cq, dec_fr=dec_fr, dec_amt=dec_amt,
                fit_mask=fit_mask, res_fr=res_fr, res_amt=res_amt,
                res_mask=res_mask, res_borrows=res_borrows)

        admit, nodes, deltas = jax.vmap(entry)(wis)
        usage = add_usage_chain_batched(usage, nodes, deltas, guaranteed,
                                        parent, depth)
        return usage, (wis, admit)

    _, (wis_o, admit_o) = jax.lax.scan(step, usage0,
                                       jnp.arange(max_forest_wl))

    wis_flat = wis_o.reshape(-1)
    safe = jnp.maximum(wis_flat, 0)
    mask = wis_flat >= 0
    admitted = jnp.zeros(W, dtype=bool).at[safe].max(
        admit_o.reshape(-1) & mask)
    return admitted


@partial(jax.jit, static_argnames=("depth", "n_forests", "max_forest_wl"))
def solve_cycle_forests(usage0, subtree, guaranteed, borrow_cap, has_blim,
                        parent, nominal_cq, slot_fr, slot_valid,
                        cq_can_preempt_borrow, wl_cq, wl_requests,
                        wl_priority, wl_timestamp, forest_of_node,
                        *, depth: int, n_forests: int, max_forest_wl: int):
    """One-call phase 1 + forest-parallel admit scan (probe surface)."""
    W = wl_cq.shape[0]
    _, _, _, preempt0, fit_slot0, borrows0 = solve_cycle(
        usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
        nominal_cq, slot_fr, slot_valid, cq_can_preempt_borrow,
        wl_cq, wl_requests, wl_priority, wl_timestamp,
        depth=depth, run_scan=False)
    order = jnp.lexsort((jnp.arange(W), wl_timestamp, -wl_priority,
                         borrows0.astype(jnp.int32))).astype(jnp.int32)
    no_reserve = jnp.zeros(W, dtype=bool)
    dec_fr, dec_amt, fit_mask = decision_pairs_from_slots(
        slot_fr, wl_cq, wl_requests, fit_slot0)
    admitted = admit_scan_forests(
        usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
        nominal_cq, jnp.zeros_like(nominal_cq), wl_cq, dec_fr, dec_amt,
        fit_mask, jnp.full_like(dec_fr, -1), jnp.zeros_like(dec_amt),
        no_reserve, no_reserve, order, forest_of_node, depth=depth,
        n_forests=n_forests, max_forest_wl=max_forest_wl)
    slots = jnp.where(admitted, fit_slot0, -1).astype(jnp.int32)
    borrows = borrows0 & admitted
    return admitted, slots, borrows, preempt0, fit_slot0, borrows0
