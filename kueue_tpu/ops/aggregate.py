"""Cohort-forest aggregate planes (the hierarchical compression layer).

The fused burst kernel's per-row work is bounded by the packed row
count, and before this layer every admitted workload owned a packed
row — so kernel cost scaled with *live workloads* and the composite
candidate-key encoding capped the pack at 2^19 rows.  But an admitted
row is only ever *read* by the kernel as a preemption candidate, and
candidates are drawn strictly from the head's own cohort forest
(``cand_rows[forest_of_cq[c]]``; ops/burst.py eligibility).  A forest
in which no member CQ can preempt (no ``withinClusterQueue:
LowerPriority``, no ``reclaimWithinCohort``) therefore never reads its
admitted rows at all: their only kernel effects are (a) the CQ usage
they hold — already aggregated in the ``u_cq0`` plane — and (b) the
release pulse when one finishes mid-burst — already routed through the
driver's ``ext_release`` fallback for unpacked keys.

``compressible_cqs`` identifies exactly those forests; the pack then
keeps their admitted workloads *out of the row planes* and tracks them
in per-CQ aggregates instead (count + max reservation time, maintained
incrementally by the streaming delta-pack).  Packed-row count — and
with it kernel cycle time and the 2^19 ceiling — scales with active
CQs + queue heads, not live workloads.  ``KUEUE_TPU_AGG_PLANES=0``
opts out; the uncompressed arm is the parity oracle (decisions are
bit-identical by the argument above, test-enforced).
"""

from __future__ import annotations

import numpy as np

from ..features import env_value

# aggregate plane layout: name -> (pad value, dtype); all [C]-shaped,
# arena-resident, maintained by the streaming pack alongside the row
# planes (registered in analysis/dtypes.PLANE_SCHEMA)
AGG_PLANES = {
    "agg_heads": (0, np.int32),        # pending (head-eligible) rows
    "agg_rows": (0, np.int32),         # rows actually packed
    "agg_comp": (0, np.int32),         # admitted rows compressed out
    "agg_comp_ts": (-1.0, np.float64),  # max reservation ts compressed
    "agg_best_prio": (0, np.int32),    # best head's priority per lane
    "agg_best_ts": (-1.0, np.float64),  # best head's queue-order ts
}


def agg_planes_enabled() -> bool:
    return env_value("KUEUE_TPU_AGG_PLANES") != "0"


def head_pack_enabled() -> bool:
    """Head-only packing (``KUEUE_TPU_HEAD_PACK``, default on).

    The same forest census that makes admitted rows compressible makes
    *pending* rows budget-exempt: a pending row of a never-preempting
    forest can win its own CQ's head slot (a per-CQ lexsort, no
    composite key involved) but can never be gathered as a preemption
    candidate — candidate eligibility requires the head CQ's
    ``wcq_lower``/``rwc_enabled``, which no member of such a forest
    has, and ineligible candidates sort behind every eligible one via
    key_hi bit 30.  So the kernel's 19-bit uid rank and the 2^19/2^20
    poison gates only need to cover rows of *preempting* forests
    ("budget rows"); everything else rides along as rank context.
    Kernel row *budget* then scales with preempting-forest rows, not
    active CQs — the r19 ceiling lift.  The scoped uid rank is the
    subset rank (order-preserving), so candidate ordering — hence every
    decision — is bit-identical to the row-backed arm (test-enforced
    in tests/test_head_packing.py)."""
    return env_value("KUEUE_TPU_HEAD_PACK") != "0"


def compressible_cqs(statics) -> np.ndarray:
    """[C] bool: CQ sits in a forest no member of which can preempt.

    Pure function of the pack statics' preemption-policy flags
    (``wcq_lower`` | ``rwc_enabled``), i.e. of the structure
    generation; admitted rows of such forests are never candidate-
    gathered by the kernel and may be aggregate-compressed."""
    forest_of_cq = statics.forest_of_cq
    G = len(statics.deep)
    preempting = np.zeros(G, dtype=bool)
    np.logical_or.at(preempting, forest_of_cq,
                     statics.wcq_lower | statics.rwc_enabled)
    return ~preempting[forest_of_cq]


def agg_clear_cq(views: dict, ci: int) -> None:
    for name, (pad, _) in AGG_PLANES.items():
        views[name][ci] = pad


def agg_write_cq(views: dict, ci: int, rec) -> None:
    """Refresh one CQ's aggregate lane from a freshly walked record."""
    views["agg_heads"][ci] = rec.n_pend
    views["agg_rows"][ci] = rec.n_rows
    views["agg_comp"][ci] = rec.n_comp
    views["agg_comp_ts"][ci] = (rec.comp_max_ts
                                if np.isfinite(rec.comp_max_ts) else -1.0)
    if rec.n_pend:
        np_ = rec.n_pend
        best = np.lexsort((rec.keys[:np_], rec.ts[:np_],
                           -rec.prio[:np_]))[0]
        views["agg_best_prio"][ci] = np.clip(
            rec.prio[best], -(2 ** 31 - 1), 2 ** 31 - 1)
        views["agg_best_ts"][ci] = rec.ts[best]
    else:
        views["agg_best_prio"][ci] = 0
        views["agg_best_ts"][ci] = -1.0


def agg_fill(views: dict, records) -> None:
    for ci, rec in enumerate(records):
        agg_write_cq(views, ci, rec)


def agg_summary(state, comp_cq) -> dict:
    """Counters for the driver stats block / kueue_agg_* metrics."""
    return {
        "agg_rows_compressed": int(state.n_comp_cq.sum()),
        "agg_rows_packed": int(state.n_rows_cq.sum()),
        "agg_heads": int(state.n_pend_cq.sum()),
        "agg_cqs_compressible": int(np.count_nonzero(comp_cq)),
    }
