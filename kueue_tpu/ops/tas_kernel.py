"""Topology-aware scheduling on device (SURVEY §7 stage 7).

The domain tree (block → rack → host) becomes level-indexed CSR arrays;
the reference's two-phase algorithm (tas_flavor_snapshot.go:406-613)
becomes:

- phase 1 ``fill_counts``: leaf fits = min over resources of
  free // per_pod, then a segment-sum up the levels (one scatter-add per
  level — XLA turns these into efficient one-pass reductions);
- phase 2 ``best_fit_descend``: pick the best domain at the requested
  level (least spare capacity, BestFit), then descend level by level
  allocating children in (-state, id) order via in-segment prefix sums —
  no data-dependent Python control flow, one jit for the whole query.

Domains at every level are packed sorted by id tuple, so children of one
parent are contiguous and segment ops are contiguous-range ops, and index
order is id order (the reference's tie-break).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.tas_snapshot import TASFlavorSnapshot

I32_MAX = 2**31 - 1


@dataclass
class PackedTAS:
    levels: list[str]
    level_sizes: list[int]            # domains per level, top→bottom
    parents: list[np.ndarray]         # parents[l]: [N_{l+1}] → level-l idx
    leaf_free: np.ndarray             # [N_leaf, R] int32
    resource_names: list[str]
    leaf_ids: list[tuple]             # leaf idx → id tuple
    domain_ids: list[list[tuple]]     # per level, idx → id tuple


def pack_tas(snap: TASFlavorSnapshot) -> PackedTAS:
    L = len(snap.levels)
    domain_ids = [sorted(d.id for d in snap.domains_per_level[lvl])
                  for lvl in range(L)]
    idx = [{did: i for i, did in enumerate(domain_ids[lvl])}
           for lvl in range(L)]
    parents = []
    for lvl in range(1, L):
        par = np.array([idx[lvl - 1][did[:lvl]] for did in domain_ids[lvl]],
                       dtype=np.int32)
        parents.append(par)
    resources = sorted({r for leaf in snap.leaves.values()
                        for r in leaf.free})
    r_index = {r: i for i, r in enumerate(resources)}
    leaf_ids = domain_ids[L - 1]
    leaf_free = np.zeros((max(1, len(leaf_ids)), max(1, len(resources))),
                         dtype=np.int64)
    for i, did in enumerate(leaf_ids):
        for r, v in snap.leaves[did].free.items():
            leaf_free[i, r_index[r]] = min(max(v, 0), I32_MAX)
    return PackedTAS(levels=list(snap.levels),
                     level_sizes=[len(d) for d in domain_ids],
                     parents=parents,
                     leaf_free=leaf_free.astype(np.int32),
                     resource_names=resources,
                     leaf_ids=list(leaf_ids), domain_ids=domain_ids)


# ---------------------------------------------------------------------------
# Phase 1: counts up the tree
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("level_sizes",))
def fill_counts(leaf_free, per_pod, parents, *, level_sizes: tuple[int, ...]):
    """Returns states per level (tuple of arrays, top→bottom).

    leaf_free: [N_leaf, R]; per_pod: [R] (0 = not requested);
    parents: tuple of [N_{l+1}] arrays.
    """
    needed = per_pod > 0
    fits_r = jnp.where(needed[None, :],
                       leaf_free // jnp.maximum(per_pod, 1)[None, :],
                       I32_MAX)
    leaf_state = jnp.min(fits_r, axis=1)          # [N_leaf]
    leaf_state = jnp.where(jnp.any(needed), leaf_state, 0)
    states = [leaf_state]
    for lvl in range(len(level_sizes) - 2, -1, -1):
        child_state = states[0]
        par = parents[lvl]
        state = jnp.zeros(level_sizes[lvl],
                          dtype=child_state.dtype).at[par].add(child_state)
        states.insert(0, state)
    return tuple(states)


# ---------------------------------------------------------------------------
# Phase 2: best-fit selection + descent
# ---------------------------------------------------------------------------

def _best_at_level(state, count):
    """Least spare capacity among domains fitting `count`; ties by index
    (= id order).  Returns -1 when none fits."""
    fits = state >= count
    key = jnp.where(fits, state, I32_MAX)
    best = jnp.argmin(key)                        # ties → lowest index
    return jnp.where(jnp.any(fits), best, -1)


def _allocate_level(parent_counts, par, state):
    """Distribute parent counts over children in (-state, idx) order.

    parent_counts: [N_l]; par: [N_{l+1}] parent idx; state: [N_{l+1}].
    Returns child_counts [N_{l+1}].
    """
    n = state.shape[0]
    order = jnp.lexsort((jnp.arange(n), -state, par))   # group, then -state
    par_o = par[order]
    state_o = state[order]
    # in-segment exclusive prefix sum of state (segments = equal par_o runs)
    csum = jnp.cumsum(state_o)
    first_of_seg = jnp.concatenate(
        [jnp.array([True]), par_o[1:] != par_o[:-1]])
    # running max of the segment-start cumsum works because csum is
    # nondecreasing, so each segment's base dominates all earlier ones
    seg_base = jax.lax.associative_scan(jnp.maximum,
                                        jnp.where(first_of_seg,
                                                  csum - state_o, 0))
    prev = (csum - state_o) - seg_base
    cnt_o = parent_counts[par_o]
    take_o = jnp.clip(cnt_o - prev, 0, state_o)
    out = jnp.zeros(n, dtype=parent_counts.dtype).at[order].set(take_o)
    return out


@partial(jax.jit, static_argnames=("level_sizes", "level"))
def best_fit_descend(leaf_free, per_pod, parents, count,
                     *, level_sizes: tuple[int, ...], level: int):
    """Single-domain BestFit at `level` + descent to leaf counts.

    Returns (ok bool, leaf_counts [N_leaf] int32); ok=False when no
    single domain at `level` fits `count`."""
    states = fill_counts(leaf_free, per_pod, parents,
                         level_sizes=level_sizes)
    best = _best_at_level(states[level], count)
    ok = best >= 0
    counts = jnp.zeros(level_sizes[level], dtype=jnp.int32)
    counts = counts.at[jnp.maximum(best, 0)].set(
        jnp.where(ok, count, 0).astype(jnp.int32))
    for lvl in range(level, len(level_sizes) - 1):
        counts = _allocate_level(counts, parents[lvl], states[lvl + 1])
    return ok, counts


@partial(jax.jit, static_argnames=("level_sizes",))
def split_across_roots(leaf_free, per_pod, parents, count,
                       *, level_sizes: tuple[int, ...]):
    """The unconstrained / final-fallback path: split over root domains,
    largest first (reference `unconstrained` + root split), then descend.

    Returns (ok, leaf_counts)."""
    states = fill_counts(leaf_free, per_pod, parents,
                         level_sizes=level_sizes)
    root_state = states[0]
    total = jnp.sum(root_state)
    ok = total >= count
    # take from largest roots first (fewest domains)
    n = root_state.shape[0]
    order = jnp.lexsort((jnp.arange(n), -root_state))
    state_o = root_state[order]
    prev = jnp.cumsum(state_o) - state_o
    take_o = jnp.clip(count - prev, 0, state_o)
    counts = jnp.zeros(n, dtype=jnp.int32).at[order].set(
        take_o.astype(jnp.int32))
    counts = jnp.where(ok, counts, 0)
    for lvl in range(0, len(level_sizes) - 1):
        counts = _allocate_level(counts, parents[lvl], states[lvl + 1])
    return ok, counts
