"""Topology-aware scheduling on device (SURVEY §7 stage 7).

The domain tree (block → rack → host) becomes level-indexed CSR arrays;
the reference's two-phase algorithm (tas_flavor_snapshot.go:406-613)
becomes:

- phase 1 ``fill_counts``: leaf fits = min over resources of
  free // per_pod, then a segment-sum up the levels (one scatter-add per
  level — XLA turns these into efficient one-pass reductions);
- phase 2 ``best_fit_descend``: pick the best domain at the requested
  level (least spare capacity, BestFit), then descend level by level
  allocating children in (-state, id) order via in-segment prefix sums —
  no data-dependent Python control flow, one jit for the whole query.

Domains at every level are packed sorted by id tuple, so children of one
parent are contiguous and segment ops are contiguous-range ops, and index
order is id order (the reference's tie-break).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.tas_snapshot import TASFlavorSnapshot

I32_MAX = 2**31 - 1


@dataclass
class PackedTAS:
    levels: list[str]
    level_sizes: list[int]            # domains per level, top→bottom
    parents: list[np.ndarray]         # parents[l]: [N_{l+1}] → level-l idx
    leaf_free: np.ndarray             # [N_leaf, R] int32
    resource_names: list[str]
    leaf_ids: list[tuple]             # leaf idx → id tuple
    domain_ids: list[list[tuple]]     # per level, idx → id tuple


def pack_tas(snap: TASFlavorSnapshot) -> PackedTAS:
    L = len(snap.levels)
    domain_ids = [sorted(d.id for d in snap.domains_per_level[lvl])
                  for lvl in range(L)]
    idx = [{did: i for i, did in enumerate(domain_ids[lvl])}
           for lvl in range(L)]
    parents = []
    for lvl in range(1, L):
        par = np.array([idx[lvl - 1][did[:lvl]] for did in domain_ids[lvl]],
                       dtype=np.int32)
        parents.append(par)
    resources = sorted({r for leaf in snap.leaves.values()
                        for r in leaf.free})
    r_index = {r: i for i, r in enumerate(resources)}
    leaf_ids = domain_ids[L - 1]
    leaf_free = np.zeros((max(1, len(leaf_ids)), max(1, len(resources))),
                         dtype=np.int64)
    for i, did in enumerate(leaf_ids):
        for r, v in snap.leaves[did].free.items():
            leaf_free[i, r_index[r]] = min(max(v, 0), I32_MAX)
    return PackedTAS(levels=list(snap.levels),
                     level_sizes=[len(d) for d in domain_ids],
                     parents=parents,
                     leaf_free=leaf_free.astype(np.int32),
                     resource_names=resources,
                     leaf_ids=list(leaf_ids), domain_ids=domain_ids)


# ---------------------------------------------------------------------------
# Phase 1: counts up the tree
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("level_sizes",))
def fill_counts(leaf_free, per_pod, parents, *, level_sizes: tuple[int, ...]):
    """Returns states per level (tuple of arrays, top→bottom).

    leaf_free: [N_leaf, R]; per_pod: [R] (0 = not requested);
    parents: tuple of [N_{l+1}] arrays.
    """
    needed = per_pod > 0
    fits_r = jnp.where(needed[None, :],
                       leaf_free // jnp.maximum(per_pod, 1)[None, :],
                       I32_MAX)
    leaf_state = jnp.min(fits_r, axis=1)          # [N_leaf]
    leaf_state = jnp.where(jnp.any(needed), leaf_state, 0)
    states = [leaf_state]
    for lvl in range(len(level_sizes) - 2, -1, -1):
        child_state = states[0]
        par = parents[lvl]
        state = jnp.zeros(level_sizes[lvl],
                          dtype=child_state.dtype).at[par].add(child_state)
        states.insert(0, state)
    return tuple(states)


# ---------------------------------------------------------------------------
# Phase 2: best-fit selection + descent
# ---------------------------------------------------------------------------

def _best_at_level(state, count, profile: str):
    """Single fitting domain per profile (_find_fit_at): BestFit and
    LeastFree pick the least spare capacity, MostFree the most free —
    ties by index (= id order).  Returns -1 when none fits."""
    fits = state >= count
    if profile == "mostfree":
        key = jnp.where(fits, -state, I32_MAX)
    else:
        key = jnp.where(fits, state, I32_MAX)
    best = jnp.argmin(key)                        # ties → lowest index
    return jnp.where(jnp.any(fits), best, -1)


def _seg_scan_sum(values, first_of_seg):
    """Inclusive in-segment prefix sum (segments = runs where
    first_of_seg marks the start)."""
    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av + bv), af | bf
    out, _ = jax.lax.associative_scan(combine, (values, first_of_seg))
    return out


def _seg_broadcast_max(values, first_of_seg):
    """Inclusive in-segment running max."""
    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, jnp.maximum(av, bv)), af | bf
    out, _ = jax.lax.associative_scan(combine, (values, first_of_seg))
    return out


def _allocate_level(parent_counts, par, state, profile: str = "bestfit"):
    """Distribute parent counts over children in sortedDomains order
    (updateCountsToMinimum, tas_flavor_snapshot.go:571): take whole
    domains in profile order; under BestFit, once the remainder fits a
    single domain, give it to the tightest domain that still fits it
    (MostFree/LeastFree hand the remainder to the first in-order fit —
    the plain greedy walk).  LeastFree reverses the (-state, id) order,
    so equal-state ties run in id-DESCENDING order there.

    parent_counts: [N_l]; par: [N_{l+1}] parent idx; state: [N_{l+1}].
    Returns child_counts [N_{l+1}].
    """
    n = state.shape[0]
    if profile == "leastfree":
        order = jnp.lexsort((-jnp.arange(n), state, par))
    else:
        order = jnp.lexsort((jnp.arange(n), -state, par))  # group, -state
    par_o = par[order]
    state_o = state[order]
    first = jnp.concatenate([jnp.array([True]), par_o[1:] != par_o[:-1]])
    # in-segment exclusive prefix sum of state
    exc = _seg_scan_sum(state_o, first) - state_o
    cnt_o = parent_counts[par_o]
    remaining = cnt_o - exc                              # before child k
    absorb = (state_o >= remaining) & (remaining > 0)
    # j = first absorbing child per segment; positions k < j have
    # state < remaining (so greedy = full state); positions k >= j give
    # the remainder to the tightest fitting child
    ab_count = _seg_scan_sum(absorb.astype(jnp.int32), first)
    is_j = absorb & (ab_count == 1)
    has_j = ab_count >= 1                                # running: k >= j
    rem_j = _seg_broadcast_max(jnp.where(is_j, remaining, 0), first)
    # best-fit last domain: the tightest child (min state, ties by id =
    # position order) with state >= rem_j — always at index >= j
    cand = (has_j & (rem_j > 0) & (state_o >= rem_j)) | is_j
    first_rev = jnp.concatenate(
        [jnp.array([True]), par_o[::-1][1:] != par_o[::-1][:-1]])
    # min candidate state per segment = state at the last candidate
    # (desc order); broadcast it backward over the segment
    cand_rev_count = _seg_scan_sum(cand[::-1].astype(jnp.int32), first_rev)
    is_last_cand = (cand[::-1] & (cand_rev_count == 1))[::-1]
    min_state = _seg_broadcast_max(
        jnp.where(is_last_cand[::-1], state_o[::-1], 0), first_rev)[::-1]
    # the pick: FIRST candidate holding the minimal state (id tie-break)
    tight = cand & (state_o == min_state)
    tight_count = _seg_scan_sum(tight.astype(jnp.int32), first)
    is_pick = tight & (tight_count == 1)

    greedy = jnp.clip(remaining, 0, state_o)             # also covers k < j
    if profile == "bestfit":
        take_o = jnp.where(has_j, jnp.where(is_pick, rem_j, 0), greedy)
    else:
        take_o = greedy      # _select_from without the last-domain pick
    out = jnp.zeros(n, dtype=parent_counts.dtype).at[order].set(take_o)
    return out


@partial(jax.jit, static_argnames=("level_sizes", "level", "profile"))
def best_fit_descend(leaf_free, per_pod, parents, count,
                     *, level_sizes: tuple[int, ...], level: int,
                     profile: str = "bestfit"):
    """Single-domain selection at `level` + descent to leaf counts,
    under the requested TAS profile (tas_flavor_snapshot.go:551-568).

    Returns (ok bool, leaf_counts [N_leaf] int32); ok=False when no
    single domain at `level` fits `count`."""
    states = fill_counts(leaf_free, per_pod, parents,
                         level_sizes=level_sizes)
    best = _best_at_level(states[level], count, profile)
    ok = best >= 0
    counts = jnp.zeros(level_sizes[level], dtype=jnp.int32)
    counts = counts.at[jnp.maximum(best, 0)].set(
        jnp.where(ok, count, 0).astype(jnp.int32))
    for lvl in range(level, len(level_sizes) - 1):
        counts = _allocate_level(counts, parents[lvl], states[lvl + 1],
                                 profile)
    return ok, counts


@partial(jax.jit,
         static_argnames=("level_sizes", "profile", "descend_profile"))
def split_across_roots(leaf_free, per_pod, parents, count,
                       *, level_sizes: tuple[int, ...],
                       profile: str = "bestfit",
                       descend_profile: str | None = None):
    """The unconstrained / final-fallback path: split over root domains
    in ``profile`` order (reference `unconstrained` + root split), then
    descend in ``descend_profile`` order.  They differ only under the
    Mixed gate: its unconstrained variant selects roots least-free but
    the per-level descent (_descend -> _sorted_domains without the
    unconstrained flag) stays on the non-unconstrained profile.

    Returns (ok, leaf_counts)."""
    if descend_profile is None:
        descend_profile = profile
    states = fill_counts(leaf_free, per_pod, parents,
                         level_sizes=level_sizes)
    root_state = states[0]
    total = jnp.sum(root_state)
    ok = total >= count
    n = root_state.shape[0]
    counts = _allocate_level(jnp.array([count], dtype=jnp.int32),
                             jnp.zeros(n, dtype=jnp.int32), root_state,
                             profile)
    counts = jnp.where(ok, counts, 0)
    for lvl in range(0, len(level_sizes) - 1):
        counts = _allocate_level(counts, parents[lvl], states[lvl + 1],
                                 descend_profile)
    return ok, counts
