"""Device preemption search (SURVEY §7 stage 5).

The reference's ``minimalPreemptions`` (preemption.go:275-342) — greedily
remove ordered candidates until the preemptor fits, then fill back in
reverse — becomes two ``lax.scan``s over the candidate axis:

- forward scan: per candidate, replicate the dynamic skip test (an
  other-CQ candidate is skipped unless its CQ is *currently* borrowing),
  the borrowWithinCohort threshold flag flip, the ``remove_usage`` chain
  walk, and the ``workloadFits`` check; stops removing once fitted;
- reverse scan (fillBackWorkloads, preemption.go:329): re-add each
  removed candidate except the fit-achieving one, keep it re-added if
  the preemptor still fits.

Bit-parity with the host search is enforced by
tests/test_preemption_kernel.py over random scenarios.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .quota_kernel import available_at, add_usage_chain


def remove_usage_chain(usage, node, delta, guaranteed, parent, depth):
    """remove_usage bubbling up one ancestor chain
    (reference resource_node.go:135; host cache/resource_node.remove_usage).

    node: scalar int32; delta: [F] int32 (>=0).  Returns new usage."""
    def body(i, state):
        usage, cur, carry = state
        valid = cur >= 0
        cur_safe = jnp.maximum(cur, 0)
        stored_in_parent = usage[cur_safe] - guaranteed[cur_safe]   # [F]
        sub = jnp.where(valid, carry, 0)
        usage = usage.at[cur_safe].add(-sub)
        next_carry = jnp.where(stored_in_parent > 0,
                               jnp.minimum(carry, stored_in_parent), 0)
        next_cur = jnp.where(valid, parent[cur_safe], -1)
        return usage, next_cur, jnp.where(valid, next_carry, carry)

    usage, _, _ = jax.lax.fori_loop(
        0, depth, body, (usage, node.astype(jnp.int32), delta))
    return usage


def _minimal_preemptions_core(usage0, subtree, guaranteed, borrow_cap,
                              has_blim, parent, preemptor_cq, wl_usage,
                              frs_mask, cand_cq, cand_delta, cand_other_cq,
                              cand_above_threshold, allow_borrowing0,
                              threshold_enabled, depth: int):
    """Returns (fitted bool, target_mask [K] bool).

    wl_usage/cand_delta are in packed-F space (scaled ints); frs_mask
    marks the flavor-resources needing preemption (for the dynamic
    is-borrowing skip test, preemption.go _cq_is_borrowing)."""
    K = cand_cq.shape[0]

    def fits(usage, allow_borrowing):
        """workloadFits (preemption.go:552) — availability chain-local
        to the preemptor's CQ (O(depth·F) per candidate step)."""
        avail = available_at(usage, subtree, guaranteed, borrow_cap,
                             has_blim, parent, preemptor_cq, depth)
        relevant = wl_usage > 0
        ok = jnp.all(jnp.where(relevant, wl_usage <= avail, True))
        borrowing = jnp.any(jnp.where(
            relevant, usage[preemptor_cq] + wl_usage > subtree[preemptor_cq],
            False))
        return ok & (allow_borrowing | ~borrowing)

    def fwd(carry, k):
        usage, allow_b, fitted = carry
        cq = cand_cq[k]
        # dynamic skip: other-CQ candidates only count while their CQ is
        # borrowing in a resource needing preemption
        cand_borrowing = jnp.any((usage[cq] > subtree[cq]) & frs_mask)
        skip = cand_other_cq[k] & ~cand_borrowing
        act = ~fitted & ~skip & (cand_cq[k] >= 0)
        # threshold: an above-threshold other-CQ target disables borrowing
        allow_b = jnp.where(
            act & cand_other_cq[k] & threshold_enabled
            & cand_above_threshold[k],
            False, allow_b)
        new_usage = remove_usage_chain(usage, cq, cand_delta[k],
                                       guaranteed, parent, depth)
        usage = jnp.where(act, new_usage, usage)
        now_fits = fits(usage, allow_b)
        fitted_next = fitted | (act & now_fits)
        return (usage, allow_b, fitted_next), (act, fitted_next)

    (usage_end, allow_b_end, fitted), (removed, fitted_after) = jax.lax.scan(
        fwd, (usage0, allow_borrowing0, jnp.asarray(False)), jnp.arange(K))

    # index of the fit-achieving removal (the last removed candidate)
    removed_idx = jnp.where(removed, jnp.arange(K), -1)
    last_removed = jnp.max(removed_idx)

    def back(carry, k):
        usage = carry
        consider = removed[k] & (k != last_removed) & fitted
        usage_try = add_usage_chain(usage, cand_cq[k], cand_delta[k],
                                    guaranteed, parent, depth)
        still_fits = fits(usage_try, allow_b_end)
        fill_back = consider & still_fits
        usage = jnp.where(fill_back, usage_try, usage)
        return usage, fill_back

    _, filled_back_rev = jax.lax.scan(back, usage_end,
                                      jnp.arange(K - 1, -1, -1))
    filled_back = filled_back_rev[::-1]

    target_mask = removed & ~filled_back & fitted
    return fitted, target_mask


@partial(jax.jit, static_argnames=("depth",))
def minimal_preemptions(usage0, subtree, guaranteed, borrow_cap, has_blim,
                        parent, preemptor_cq, wl_usage, frs_mask,
                        cand_cq, cand_delta, cand_other_cq,
                        cand_above_threshold, allow_borrowing0,
                        threshold_enabled, *, depth: int):
    """One search (see _minimal_preemptions_core)."""
    return _minimal_preemptions_core(
        usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
        preemptor_cq, wl_usage, frs_mask, cand_cq, cand_delta,
        cand_other_cq, cand_above_threshold, allow_borrowing0,
        threshold_enabled, depth)


@partial(jax.jit, static_argnames=("depth",))
def minimal_preemptions_batch(usage0, subtree, guaranteed, borrow_cap,
                              has_blim, parent, pre_cq, wl_usage, frs_mask,
                              cand_cq, cand_delta, cand_other_cq,
                              cand_above_threshold, allow_borrowing0,
                              threshold_enabled, *, depth: int):
    """ALL of a cycle's preemption searches in ONE dispatch, each over
    its own FOREST-LOCAL node plane.

    A search only ever touches its preemptor's cohort forest (candidates
    are same-CQ or cohort CQs), so the host packs each search's quota
    plane into compact [NL, F] slices (NL = forest-size bucket, ~8)
    instead of the full [N, F] cluster — the scan carry per search drops
    ~N/NL-fold.  All node-plane args carry a leading S axis:
    usage0/subtree/guaranteed/borrow_cap [S, NL, F], has_blim [S, NL, F],
    parent [S, NL]; per-search work: pre_cq [S] (local index),
    wl_usage/frs_mask [S, F], cand_* [S, K] (local cq indices), flags
    [S].  Returns (fitted [S], target_mask [S, K]); padded rows
    (pre_cq = -1) come back unfitted."""
    def one(u0, sub, gua, bc, hb, par, pcq, wu, fm, cc, cd, co, ca, ab, te):
        return _minimal_preemptions_core(
            u0, sub, gua, bc, hb, par,
            jnp.maximum(pcq, 0), wu, fm, cc, cd, co, ca, ab, te, depth)

    fitted, mask = jax.vmap(one)(usage0, subtree, guaranteed, borrow_cap,
                                 has_blim, parent, pre_cq, wl_usage,
                                 frs_mask, cand_cq, cand_delta,
                                 cand_other_cq, cand_above_threshold,
                                 allow_borrowing0, threshold_enabled)
    valid = pre_cq >= 0
    return fitted & valid, mask & valid[:, None]
