"""Streaming delta-pack: patch a persistent packed universe in place.

The classic delta pack (ops/burst.py pack_burst_cached) re-walks only
journal-dirty CQs but still *reassembles* the whole dense ``[C, M]``
plan every window: a full concatenate of the per-CQ row records, three
global lexsorts over every row, a fresh grid allocation + scatter, a
``tolist`` of every key and a rebuilt ``row_of_key`` dict.  All of
that is O(total rows) per window — the host residue that caps the
10k-CQ artifacts.

This module keeps the packed universe *resident on the host* between
windows (cache/arena.py PlaneArena slabs, slab-doubling growth) and
patches it from the PackJournal:

- **dirty CQs** are re-walked (same stage-A ``_pack_cq_rows``) and only
  their grid rows are cleared + rescattered;
- **row-grade touches** (``PackJournal.touch_row``, deduped
  last-writer-wins by ``drain_into``) patch single cells — the dynamic
  bits a check-state flip can move (``vec_ok``, parked, resume) — with
  verify-and-escalate when anything structural moved;
- **global ranks** (``wl_cycle_rank``, ``wl_uidrank``, ``adm_seq0``)
  are maintained as order-statistic updates over sorted key arrays:
  the dirty CQs' entries are deleted and merge-inserted (vectorized
  ``searchsorted`` + ``insert``), and the dense rank planes are
  rewritten only from the first shifted position onward — the
  ``kueue_pack_rank_patches`` gauge counts exactly those rewrites.

The reference sort orders are reproduced bit for bit by encoding each
lexsort key into a fixed-width big-endian byte string (order-preserving
integer/float maps + the ASCII workload key), so one memcmp order
equals the reference ``np.lexsort`` order; non-ASCII or oversized keys
poison the structure back to the classic path (``_StreamBail``).

The produced plan is bit-identical to ``pack_burst`` of the same live
state (enforced by tests/test_streaming_pack.py); plans carry snapshot
*copies* of the live planes, so consumers (pipeline speculation, the
shard-resident scatter, parity tests) never observe later patches.
``KUEUE_TPU_STREAM_PACK=0`` opts out back to the classic delta pack.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..cache.arena import PlaneArena
from . import aggregate as _agg
from . import burst as _b
from .packing import _bucket

_KEY_BYTES = 48          # workload key width in the encoded sort keys
_UID_BYTES = 64
_SKEY_DT = np.dtype([("p", ">u8"), ("t", ">u8"), ("o", ">u4"),
                     ("k", f"S{_KEY_BYTES}")])
_SKEY_S = f"S{_SKEY_DT.itemsize}"


class _StreamBail(Exception):
    """This structure can't be streamed (non-ASCII / oversized keys):
    poison it back to the classic pack path."""


def _enc_i64(x: np.ndarray) -> np.ndarray:
    """Order-preserving int64 → uint64 (offset binary)."""
    return x.astype(np.int64).astype(np.uint64) ^ np.uint64(1 << 63)


def _enc_f64(x: np.ndarray) -> np.ndarray:
    """Order-preserving float64 → uint64 (sign-magnitude flip).
    Zeros are canonicalized first: the reference lexsort compares
    -0.0 == 0.0 (tie broken by the next key) and the byte encoding
    must not order them."""
    x = np.asarray(x, dtype=np.float64)
    x = np.where(x == 0.0, 0.0, x)
    b = np.ascontiguousarray(x).view(np.uint64).copy()
    neg = (b >> np.uint64(63)).astype(bool)
    b[neg] = ~b[neg]
    b[~neg] |= np.uint64(1 << 63)
    return b


def _enc_str(arr: np.ndarray, width: int) -> np.ndarray:
    """ASCII-encode a unicode array into fixed-width bytes whose memcmp
    order equals the unicode code-point order; bail when a value can't
    be represented."""
    a = np.asarray(arr, dtype=np.str_)
    if a.size and int(np.char.str_len(a).max(initial=0)) > width:
        raise _StreamBail(f"key longer than {width} bytes")
    try:
        out = np.char.encode(a.astype(f"U{width}"), "ascii")
    except UnicodeEncodeError as e:
        raise _StreamBail("non-ascii key") from e
    return out.astype(f"S{width}")


def _crank_skey(prio, ts, pos, kbytes) -> np.ndarray:
    """Encoded key for the global cycle-order rank: memcmp order ==
    ``np.lexsort((key, pos, ts, -prio))`` order."""
    n = len(kbytes)
    out = np.empty(n, dtype=_SKEY_DT)
    out["p"] = _enc_i64(-np.asarray(prio, dtype=np.int64))
    out["t"] = _enc_f64(ts)
    out["o"] = np.asarray(pos, dtype=np.uint32)
    out["k"] = kbytes
    return out.view(_SKEY_S).reshape(n)


class _Order:
    """A maintained sorted total order: encoded sort keys plus the
    parallel (ci, mi) grid locators of each entry."""
    __slots__ = ("skey", "ci", "mi")

    def __init__(self, dtype):
        self.skey = np.empty(0, dtype=dtype)
        self.ci = np.empty(0, dtype=np.int32)
        self.mi = np.empty(0, dtype=np.int32)

    def set(self, skey, ci, mi):
        srt = np.argsort(skey, kind="stable")
        self.skey = skey[srt]
        self.ci = np.asarray(ci, np.int32)[srt]
        self.mi = np.asarray(mi, np.int32)[srt]

    def update(self, drop_cis, nskey, nci, nmi) -> Optional[int]:
        """Delete every entry of the ``drop_cis`` CQs, merge-insert the
        new entries; returns the first final position whose dense rank
        may have changed (None = order untouched)."""
        first = None
        if len(self.skey) and len(drop_cis):
            dm = np.isin(self.ci, drop_cis)
            if dm.any():
                first = int(np.argmax(dm))
                keep = ~dm
                self.skey = self.skey[keep]
                self.ci = self.ci[keep]
                self.mi = self.mi[keep]
        if len(nskey):
            srt = np.argsort(nskey, kind="stable")
            nskey = nskey[srt]
            nci = np.asarray(nci, np.int32)[srt]
            nmi = np.asarray(nmi, np.int32)[srt]
            pos = np.searchsorted(self.skey, nskey)
            fi = int(pos[0])
            first = fi if first is None else min(first, fi)
            self.skey = np.insert(self.skey, pos, nskey)
            self.ci = np.insert(self.ci, pos, nci)
            self.mi = np.insert(self.mi, pos, nmi)
        return first


# row-plane layout: name -> (pad value, dtype, extra axis: None | "R" | "F")
_ROW_PLANES = {
    "wl_req": (0, np.int32, "R"),
    "wl_rank": (_b.INF_I32, np.int32, None),
    "wl_cycle_rank": (0, np.int32, None),
    "wl_prio": (0, np.int32, None),
    "wl_uidrank": (0, np.int32, None),
    "vec_ok": (False, bool, None),
    "elig0": (False, bool, None),
    "parked0": (False, bool, None),
    "resume0": (0, np.int32, None),
    "adm0": (False, bool, None),
    "adm_seq0": (0, np.int32, None),
    "adm_usage0": (0, np.int32, "F"),
    "adm_uses0": (False, bool, "F"),
    "death0": (_b.I32_MAX, np.int32, None),   # constant plane
}


class StreamState:
    """Persistent streaming pack state, duck-compatible with
    ``DeltaPackState`` (key/records/fields/token) so the classic path
    can consume it after an opt-out or poison."""
    __slots__ = ("key", "records", "fields", "token", "arena",
                 "crank", "uord",
                 "adm_ts", "adm_ci", "adm_mi", "adm_seq_cache",
                 "mi_of", "kb_of",
                 "n_rows_cq", "n_pend_cq", "maxabs_prio_cq", "bad_cq",
                 "strict_cq", "pos_cq", "cq_names_list",
                 "n_comp_cq", "comp_max_cq",
                 "row_of_key", "keys_grid", "M")

    def __init__(self, key, arena):
        self.key = key
        self.fields = None        # classic-path compatibility
        self.arena = arena
        self.token = next(_b.DeltaPackState._next_token)


def _views(arena: PlaneArena, C: int, M: int, R: int, F: int) -> dict:
    out = {}
    for name, (pad, dt, extra) in _ROW_PLANES.items():
        shape = (C, M) if extra is None else \
            (C, M, R) if extra == "R" else (C, M, F)
        out[name] = arena.ensure(name, shape, dt, pad)
    out["u_cq0"] = arena.ensure("u_cq0", (C, F), np.int32, 0, grow_axes=1)
    out["keys_grid"] = arena.ensure("keys_grid", (C, M), object, None)
    out["agg_heads"] = arena.ensure("agg_heads", (C,), np.int32, 0)
    out["agg_rows"] = arena.ensure("agg_rows", (C,), np.int32, 0)
    out["agg_comp"] = arena.ensure("agg_comp", (C,), np.int32, 0)
    out["agg_comp_ts"] = arena.ensure("agg_comp_ts", (C,),
                                      np.float64, -1.0)
    out["agg_best_prio"] = arena.ensure("agg_best_prio", (C,),
                                        np.int32, 0)
    out["agg_best_ts"] = arena.ensure("agg_best_ts", (C,),
                                      np.float64, -1.0)
    return out


def _reset_views(views: dict) -> None:
    for name, v in views.items():
        if name == "keys_grid":
            pad = None
        elif name == "u_cq0":
            pad = 0
        elif name in _agg.AGG_PLANES:
            pad = _agg.AGG_PLANES[name][0]
        else:
            pad = _ROW_PLANES[name][0]
        base = v
        while base.base is not None:
            base = base.base
        base[...] = pad


def _clear_cq(state: "StreamState", views: dict, ci: int) -> None:
    """Reset one CQ's grid rows to pad across the FULL slab width, so
    later M growth exposes pads, and unindex its keys."""
    for name, (pad, _, _) in _ROW_PLANES.items():
        if name == "death0":
            continue
        slab = views[name]
        base = slab
        while base.base is not None:
            base = base.base
        base[ci] = pad
    views["u_cq0"][ci] = 0
    _agg.agg_clear_cq(views, ci)
    kg = views["keys_grid"]
    base = kg
    while base.base is not None:
        base = base.base
    base[ci] = None
    old = state.records[ci]
    if old is not None:
        for k in old.index_of_key:
            state.row_of_key.pop(k, None)


def _write_cq(state: "StreamState", views: dict, ci: int, rec,
              mi: np.ndarray) -> None:
    """Scatter one CQ's freshly walked record into the grid planes
    (the per-row half; global rank planes are patched separately)."""
    if rec.n_rows:
        views["wl_req"][ci, mi] = rec.req
        views["wl_rank"][ci, mi] = mi
        views["wl_prio"][ci, mi] = np.clip(
            rec.prio, -_b.I32_MAX, _b.I32_MAX)
        views["vec_ok"][ci, mi] = rec.ok
        views["parked0"][ci, mi] = rec.parked
        views["elig0"][ci, mi] = ~rec.parked & ~rec.adm
        views["resume0"][ci, mi] = rec.resume
        views["adm0"][ci, mi] = rec.adm
        views["adm_usage0"][ci, mi] = rec.usage
        views["adm_uses0"][ci, mi] = rec.uses
        keys = rec.keys.tolist()
        views["keys_grid"][ci, mi] = np.array(keys, dtype=object)
        row_of = state.row_of_key
        for k, m in zip(keys, mi.tolist()):
            row_of[k] = (ci, int(m))
    views["u_cq0"][ci] = rec.u_row
    _agg.agg_write_cq(views, ci, rec)


def _cq_mi(rec) -> np.ndarray:
    """Per-CQ heap rank — the ci-segment of the reference global
    ``lexsort((key, ts, -prio, ci))`` (total order via the unique key
    tiebreak, so the segmented and per-CQ sorts agree exactly)."""
    mi = np.empty(rec.n_rows, dtype=np.int32)
    mi[np.lexsort((rec.keys, rec.ts, -rec.prio))] = \
        np.arange(rec.n_rows, dtype=np.int32)
    return mi


_ESCALATE = object()


def _row_patch_job(state, st, queues, cache, scheduler, ci, key):
    """Re-derive one row's dynamic bits (parked / resume / vec_ok) from
    the live queue + cache state.  Returns None (nothing moved), a
    ``(ci, idx, parked, resume, ok)`` patch, or ``_ESCALATE`` when the
    change is beyond row grade (membership, identity, admission)."""
    from ..api.types import AdmissionCheckState
    from .solver import resume_start
    rec = state.records[ci]
    idx = rec.index_of_key.get(key)
    if idx is None:
        # benign absences: below a window-truncation cutoff, or an
        # aggregate-compressed admitted row (its only row-grade bit,
        # vec_ok, never reaches the kernel — no candidates are drawn
        # from a compressible forest).  Membership changes always come
        # through hard journal touches, which dirty the CQ before row
        # jobs run, so an unknown key here can't be a new workload.
        return None if (rec.truncated or rec.n_comp) else _ESCALATE
    cq_name = st.cq_names[ci]
    q = queues.queue_for(cq_name)
    cq_live = cache.cluster_queue(cq_name)
    if cq_live is None:
        return _ESCALATE
    covers_pods = cq_name in st.cq_covers_pods
    cq_ok = st.cq_vector_ok
    cq_vec = bool(cq_ok[ci]) if cq_ok is not None else False
    if cq_vec and cq_live.spec.namespace_selector:
        cq_vec = False
    if idx >= rec.n_pend:
        # admitted row: only the vec_ok gate can move at row grade
        info = rec.infos[idx]
        if cq_live.workloads.get(key) is not info:
            return _ESCALATE
        obj = info.obj
        from ..api.types import WL_EVICTED, WL_QUOTA_RESERVED
        if (obj.condition_true(WL_EVICTED)
                or obj.conditions.get(WL_QUOTA_RESERVED) is None):
            return _ESCALATE
        row = getattr(info, "_burst_row", None)
        if row is None or row[0] != st.generation:
            return _ESCALATE
        ok = cq_vec and row[3]
        if ok:
            lr = scheduler.limit_range_summaries
            if lr and lr.get(obj.namespace):
                ok = False
            elif obj.admission_check_states and any(
                    s.state in (AdmissionCheckState.RETRY,
                                AdmissionCheckState.REJECTED)
                    for s in obj.admission_check_states.values()):
                ok = False
        if ok == bool(rec.ok[idx]):
            return None
        return (ci, idx, bool(rec.parked[idx]), int(rec.resume[idx]), ok)
    if q is None or not q.active:
        return _ESCALATE
    parked_now = False
    info = q.heap.get(key)
    if info is None:
        info = q.inadmissible.get(key)
        if info is None:
            return _ESCALATE
        rs = info.obj.requeue_state
        if rs is not None and rs.requeue_at is not None:
            return _ESCALATE   # backoff-parked: membership changed
        parked_now = True
    if rec.infos[idx] is not info:
        return _ESCALATE
    row = getattr(info, "_burst_row", None)
    if row is None or row[0] != st.generation:
        return _ESCALATE
    obj = info.obj
    ok = cq_vec and row[3]
    if ok:
        lr = scheduler.limit_range_summaries
        if lr and lr.get(obj.namespace):
            ok = False
        elif key in cache.assumed_workloads or obj.admission is not None:
            ok = False
        elif obj.admission_check_states and any(
                s.state in (AdmissionCheckState.RETRY,
                            AdmissionCheckState.REJECTED)
                for s in obj.admission_check_states.values()):
            ok = False
    resume_now = resume_start(info, cq_live, covers_pods)
    if (parked_now == bool(rec.parked[idx])
            and resume_now == int(rec.resume[idx])
            and ok == bool(rec.ok[idx])):
        return None
    return (ci, idx, parked_now, resume_now, ok)


def _bump(stats, key, n=1):
    if stats is not None:
        stats[key] = stats.get(key, 0) + n


def _materialize(st, state, s, views, scheduler, dirty_cis, prev_token,
                 rank_patches, stats):
    """Build the BurstPlan snapshot from the patched arena state."""
    C = len(st.cq_names)
    M = state.M
    n = int(state.n_rows_cq.sum())
    L, G = s.L, st.n_forests
    KC = min(_b.KC_CAP, ((L * M + 31) // 32) * 32)
    # seq_base / max_res_ts from the maintained admitted-ts multiset;
    # max_res_ts (the driver's admission clock) must also cover
    # aggregate-compressed admitted rows, whose reservation times live
    # only in the per-CQ comp_max_cq aggregate
    if len(state.adm_ts):
        uniq = np.unique(state.adm_ts)
        seq_base = int(len(uniq)) + 2
        max_res_ts = float(state.adm_ts[-1])
    else:
        seq_base = 2
        max_res_ts = None
    comp_max = float(state.comp_max_cq.max(initial=-np.inf))
    if np.isfinite(comp_max):
        max_res_ts = (comp_max if max_res_ts is None
                      else max(max_res_ts, comp_max))
    forest_bad = s.deep.copy()
    bad_idx = np.nonzero(state.bad_cq)[0]
    if len(bad_idx):
        forest_bad[s.forest_of_cq[bad_idx]] = True
    if L * M > KC:
        forest_bad[:] = True
    if not scheduler.ordering.priority_sorting_within_cohort:
        forest_bad[:] = True
    # budget scoping mirrors _assemble_plan: with head-pack on, only
    # rows of preempting forests can ever be candidate-encoded, so only
    # they are charged against the 19/20-bit composite-key fields
    if _agg.head_pack_enabled():
        bm = ~s.comp_cq
        n_budget = int(state.n_rows_cq[bm].sum())
        prio_budget = int(state.maxabs_prio_cq[bm].max(initial=0))
    else:
        n_budget = n
        prio_budget = int(state.maxabs_prio_cq.max(initial=0))
    if (prio_budget >= (1 << 20)
            or seq_base + max(_b.K_BURST_LADDER) >= (1 << 20)
            or n_budget >= (1 << 19)):
        forest_bad[:] = True
    preempt_ok = s.modelable_base & ~forest_bad[s.forest_of_cq]
    tables = s.cand_tables.get((M, KC))
    if tables is None:
        tables = _b.build_candidate_tables(s.forest_of_cq, s.members,
                                           M, KC)
        s.cand_tables[(M, KC)] = tables
    cand_rows, cand_lmem, self_lmem = tables
    arrays = {name: views[name].copy()
              for name in _ROW_PLANES}
    arrays["u_cq0"] = views["u_cq0"].copy()
    arrays.update(
        potential0=s.potential0, subtree=st.subtree_quota,
        guaranteed=st.guaranteed, borrow_cap=st.borrow_cap,
        has_blim=st.has_borrow_limit, parent=st.parent,
        node_level=s.node_level, nominal_cq=st.nominal_cq,
        npb_cq=st.nominal_plus_blimit_cq, slot_fr=st.slot_fr,
        slot_valid=st.slot_valid,
        cq_can_preempt_borrow=st.cq_can_preempt_borrow,
        cq_wcb_borrow=st.cq_wcb_borrow,
        cq_wcp_preempt=st.cq_wcp_preempt,
        forest_of_cq=s.forest_of_cq,
        strict_cq=state.strict_cq.copy(),
        wcq_lower=s.wcq_lower, rwc_enabled=s.rwc_enabled,
        rwc_only_lower=s.rwc_only_lower, preempt_ok=preempt_ok,
        members=s.members, cand_rows=cand_rows, cand_lmem=cand_lmem,
        self_lmem=self_lmem)
    plan = _b.BurstPlan(
        structure=st, arrays=arrays,
        keys=_KeysView(views["keys_grid"].copy()),
        C=C, M=M, L=L, G=G, n_levels=s.n_levels, KC=KC,
        seq_base=seq_base, row_of_key=state.row_of_key,
        max_res_ts=max_res_ts,
        budget_rows=n_budget, grid_rows=n)
    plan.pack_token = state.token
    plan.prev_token = prev_token
    if dirty_cis is not None:
        plan.dirty_cqs = np.asarray(sorted(dirty_cis), dtype=np.int64)
        from ..utils.journal import PackJournal
        plan.dirty_ranges = PackJournal.coalesce(sorted(dirty_cis))
    if stats is not None:
        stats["pack_rank_patches"] = (
            stats.get("pack_rank_patches", 0) + int(rank_patches))
        shapes = {name: a.shape for name, a in arrays.items()
                  if name in _ROW_PLANES or name == "u_cq0"}
        state.arena.refresh_stats(shapes)
        stats.update({("pack_" + k): v
                      for k, v in state.arena.stats.items()})
        stats.update(_agg.agg_summary(state, s.comp_cq))
        stats["head_pack_budget_rows"] = n_budget
        stats["head_pack_exempt_rows"] = n - n_budget
    return plan


class _KeysView:
    """Lazy ``plan.keys``: an object grid supporting the consumers'
    ``plan.keys[ci][mi]`` indexing without materializing C×M Python
    lists every window.  Equality compares against list-of-lists (the
    classic plan shape) for the parity tests."""
    __slots__ = ("_g",)

    def __init__(self, grid):
        self._g = grid

    def __getitem__(self, ci):
        return self._g[ci]

    def __len__(self):
        return len(self._g)

    def __iter__(self):
        return iter(self._g)

    def tolist(self):
        return self._g.tolist()

    def __eq__(self, other):
        if isinstance(other, _KeysView):
            return self._g.tolist() == other._g.tolist()
        if isinstance(other, list):
            return self._g.tolist() == other
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq


def _init_full(st, queues, cache, scheduler, key, min_m, window, arena,
               stats, t0):
    """Full streaming (re)build: walk every CQ, reset + refill the
    arena, rebuild the maintained orders.  The assembly math mirrors
    ops/burst._assemble_plan line for line — same sorts, same pads —
    so the first streaming plan equals the reference plan bit for bit."""
    if _b._unknown_active_cq(st, queues):
        return None, None, False
    records = _b._walk_records(st, queues, cache, scheduler, window)
    if records is None:
        return None, None, False
    C = len(st.cq_names)
    R = len(st.resource_names)
    F = max(1, len(st.fr_index))
    s = _b._pack_statics(st, cache)

    state = StreamState(key, arena)
    state.records = records
    state.cq_names_list = list(queues.cluster_queue_names())
    pos_of = {name: i for i, name in enumerate(state.cq_names_list)}
    state.pos_cq = np.fromiter(
        (pos_of.get(nm, C) for nm in st.cq_names), np.int32, C)
    for rec in records:
        rec.pos = int(state.pos_cq[rec.ci])
    state.n_rows_cq = np.fromiter((r.n_rows for r in records),
                                  np.int64, C)
    state.n_pend_cq = np.fromiter((r.n_pend for r in records),
                                  np.int64, C)
    state.bad_cq = np.fromiter((r.bad for r in records), bool, C)
    state.strict_cq = np.fromiter((r.strict for r in records), bool, C)
    state.n_comp_cq = np.fromiter((r.n_comp for r in records),
                                  np.int64, C)
    state.comp_max_cq = np.fromiter((r.comp_max_ts for r in records),
                                    np.float64, C)
    bounds = np.concatenate(([0], np.cumsum(state.n_rows_cq)))
    n = int(bounds[-1])

    nz = [r for r in records if r.n_rows]
    def cat(attr, empty_dtype):
        if nz:
            return np.concatenate([getattr(r, attr) for r in nz])
        return np.empty(0, dtype=empty_dtype)
    keys_a = cat("keys", "U1")
    uids_a = cat("uids", "U1")
    prio_a = cat("prio", np.int64)
    ts_a = cat("ts", np.float64)
    res_ts_a = cat("res_ts", np.float64)
    adm_a = cat("adm", bool)
    kb_all = _enc_str(keys_a, _KEY_BYTES)      # may bail -> caller
    ub_all = _enc_str(uids_a, _UID_BYTES)
    ci_a = np.repeat(np.arange(C, dtype=np.int32), state.n_rows_cq)
    pos_a = np.repeat(state.pos_cq, state.n_rows_cq)

    # per-CQ |prio| maxima (reduceat; empty segments masked out)
    state.maxabs_prio_cq = np.zeros(C, np.int64)
    if n:
        red = np.maximum.reduceat(
            np.abs(prio_a), np.minimum(bounds[:-1], n - 1))
        state.maxabs_prio_cq = np.where(state.n_rows_cq > 0, red, 0)

    rows_per_cq = int(state.n_rows_cq.max(initial=0))
    state.M = M = max(_bucket(rows_per_cq, minimum=4), min_m)
    views = _views(arena, C, M, R, F)
    _reset_views(views)

    # per-CQ heap rank: the reference ci-segmented lexsort
    order = np.lexsort((keys_a, ts_a, -prio_a, ci_a))
    ci_sorted = ci_a[order]
    first = np.ones(n, dtype=bool)
    first[1:] = ci_sorted[1:] != ci_sorted[:-1]
    idx = np.arange(n, dtype=np.int32)
    seg_start = np.maximum.accumulate(np.where(first, idx, np.int32(0)))
    mi_sorted = idx - seg_start
    mi_a = np.empty(n, dtype=np.int32)
    mi_a[order] = mi_sorted

    state.mi_of = {}
    state.kb_of = {}
    for ci in range(C):
        lo, hi = int(bounds[ci]), int(bounds[ci + 1])
        state.mi_of[ci] = mi_a[lo:hi]
        state.kb_of[ci] = kb_all[lo:hi]

    if n:
        views["wl_req"][ci_a, mi_a] = cat("req", np.int32)
        views["wl_rank"][ci_a, mi_a] = mi_a
        views["wl_prio"][ci_a, mi_a] = np.clip(
            prio_a, -_b.I32_MAX, _b.I32_MAX)
        parked_a = cat("parked", bool)
        views["parked0"][ci_a, mi_a] = parked_a
        views["elig0"][ci_a, mi_a] = ~parked_a & ~adm_a
        views["vec_ok"][ci_a, mi_a] = cat("ok", bool)
        views["resume0"][ci_a, mi_a] = cat("resume", np.int32)
        views["adm0"][ci_a, mi_a] = adm_a
        views["adm_usage0"][ci_a, mi_a] = cat("usage", np.int32)
        views["adm_uses0"][ci_a, mi_a] = cat("uses", bool)
        key_list = keys_a.tolist()
        views["keys_grid"][ci_a, mi_a] = np.array(key_list, dtype=object)
        state.row_of_key = dict(zip(
            key_list, zip(ci_a.tolist(), mi_a.tolist())))
    else:
        state.row_of_key = {}
    for ci, rec in enumerate(records):
        views["u_cq0"][ci] = rec.u_row
    _agg.agg_fill(views, records)

    # maintained global orders + their dense rank planes
    state.crank = _Order(_SKEY_S)
    state.crank.set(_crank_skey(prio_a, ts_a, pos_a, kb_all),
                    ci_a, mi_a)
    if n:
        views["wl_cycle_rank"][state.crank.ci, state.crank.mi] = \
            np.arange(n, dtype=np.int32)
    # head-pack: the uid order (and so the 19-bit uidrank field) only
    # tracks budget rows — rows of preempting forests; exempt rows keep
    # the pad rank 0, which the kernel never reads for them (candidate
    # eligibility needs the head's wcq_lower/rwc_enabled census bits)
    state.uord = _Order(f"S{_UID_BYTES}")
    if _agg.head_pack_enabled() and n:
        bsel = np.nonzero(~s.comp_cq[ci_a])[0]
        state.uord.set(ub_all[bsel], ci_a[bsel], mi_a[bsel])
    else:
        state.uord.set(ub_all, ci_a, mi_a)
    n_uord = len(state.uord.ci)
    if n_uord:
        views["wl_uidrank"][state.uord.ci, state.uord.mi] = \
            np.arange(n_uord, dtype=np.int32)
    am = np.nonzero(adm_a)[0]
    ats = res_ts_a[am]
    aord = np.argsort(ats, kind="stable")
    state.adm_ts = ats[aord]
    state.adm_ci = ci_a[am][aord]
    state.adm_mi = mi_a[am][aord]
    if len(state.adm_ts):
        uniq = np.unique(state.adm_ts)
        state.adm_seq_cache = (np.searchsorted(uniq, state.adm_ts)
                               + 1).astype(np.int32)
        views["adm_seq0"][state.adm_ci, state.adm_mi] = \
            state.adm_seq_cache
    else:
        state.adm_seq_cache = np.empty(0, np.int32)

    _bump(stats, "burst_full_packs")
    _bump(stats, "stream_full_packs")
    _bump(stats, "rows_repacked", n)
    if int(state.n_pend_cq.sum()) == 0:
        _note_ms(stats, t0)
        return None, state, False
    plan = _materialize(st, state, s, views, scheduler, None,
                        None, 0, stats)
    _note_ms(stats, t0)
    return plan, state, False


def _note_ms(stats, t0, delta=False):
    if stats is not None:
        dt = time.perf_counter() - t0
        stats["stream_pack_s"] = stats.get("stream_pack_s", 0.0) + dt
        stats["pack_last_ms"] = dt * 1e3
        if delta:
            # classic-path compat: tooling reads delta_pack_s as "time
            # spent on incremental (non-full) packs"
            stats["delta_pack_s"] = stats.get("delta_pack_s", 0.0) + dt


def pack_burst_streaming(structure, queues, cache, scheduler, clock,
                         state=None, min_m: int = 0, window: int = 0,
                         stats=None):
    """Streaming counterpart of ``pack_burst_cached``; same return
    contract ``(plan, state, was_delta)``, bit-identical plans."""
    st = structure
    t0 = time.perf_counter()
    key = (st.generation, st.resource_scale.tobytes(),
           tuple(st.cq_names), window, _agg.agg_planes_enabled())
    dirty: set = set()
    soft: dict = {}
    rows: dict = {}
    jranges: list = []
    force_full = False
    for j in (getattr(queues, "pack_journal", None),
              getattr(cache, "pack_journal", None)):
        if j is None:
            force_full = True
        else:
            force_full |= j.drain_into(dirty, soft, row_of=st.cq_index,
                                       ranges_out=jranges, rows_out=rows)
    arena = getattr(cache, "_pack_arena", None)
    if arena is None:
        arena = cache._pack_arena = PlaneArena()

    try:
        if (not isinstance(state, StreamState) or state.key != key
                or force_full):
            return _init_full(st, queues, cache, scheduler, key, min_m,
                              window, arena, stats, t0)

        index_of = st.cq_index
        C = len(st.cq_names)
        for name in set(dirty) | set(soft) | set(rows.values()):
            if name not in index_of:
                q = queues.queue_for(name)
                if q is not None and q.active and q.pending_active():
                    return None, None, False
        for name, skeys in soft.items():
            ci = index_of.get(name)
            if ci is None or name in dirty:
                continue
            if not _b._roundtrips_clean(
                    state.records[ci], queues.queue_for(name),
                    cache.cluster_queue(name), skeys,
                    name in st.cq_covers_pods):
                dirty.add(name)
        row_jobs = []
        rows_verified = 0
        for wkey, name in rows.items():
            ci = index_of.get(name)
            if ci is None or name in dirty:
                continue
            job = _row_patch_job(state, st, queues, cache, scheduler,
                                 ci, wkey)
            if job is _ESCALATE:
                dirty.add(name)
            elif job is not None:
                row_jobs.append(job)
            else:
                rows_verified += 1
        if rows_verified:
            _bump(stats, "pack_rows_verified", rows_verified)

        if len(dirty) > max(_b._DELTA_MIN_DIRTY_CQS,
                            _b._DELTA_MAX_DIRTY_FRAC * C):
            return _init_full(st, queues, cache, scheduler, key, min_m,
                              window, arena, stats, t0)

        # heads-enumeration position drift (CQs joined/left the queue
        # manager without a structure change): the crank sort keys of
        # every row of a moved CQ change, nothing else does
        pos_dirty_cis: list = []
        names_now = queues.cluster_queue_names()
        if state.cq_names_list != names_now:
            pos_of = {nm: i for i, nm in enumerate(names_now)}
            newpos = np.fromiter(
                (pos_of.get(nm, C) for nm in st.cq_names), np.int32, C)
            for ci in np.nonzero(newpos != state.pos_cq)[0]:
                ci = int(ci)
                pos_dirty_cis.append(ci)
                state.records[ci].pos = int(newpos[ci])
            state.pos_cq = newpos
            state.cq_names_list = list(names_now)

        # stage A over the dirty CQs only; encode before mutating so a
        # bail leaves the state coherent
        assumed = cache.assumed_workloads
        scale_of = {r: int(st.resource_scale[i])
                    for i, r in enumerate(st.resource_names)}
        statics = _b._pack_statics(st, cache)
        comp_cq = (statics.comp_cq if _agg.agg_planes_enabled()
                   else None)
        def _walk_one(ci):
            rec = _b._pack_cq_rows(st, ci, int(state.pos_cq[ci]),
                                   queues, cache, scheduler, assumed,
                                   scale_of, window,
                                   compress=(comp_cq is not None
                                             and bool(comp_cq[ci])))
            if rec is _b._PACK_FAIL:
                return None
            kb = _enc_str(rec.keys, _KEY_BYTES)
            ub = _enc_str(rec.uids, _UID_BYTES)
            return (ci, rec, kb, ub, _cq_mi(rec))

        cis = sorted(ci for name in dirty
                     if (ci := index_of.get(name)) is not None)
        # stage A is per-CQ pure (each walk reads shared structure and
        # writes only its own CQ's rows/memos), so the host pool fans
        # the dirty walk out by cohort forest; the gather is in
        # ascending (forest, ci) order, and every downstream merge is
        # order-insensitive (sorted-order updates, disjoint row writes),
        # so pooled and serial walks build identical states
        pool = getattr(cache, "host_pool", None)
        if pool is not None and pool.active and len(cis) >= 2:
            fcq = statics.forest_of_cq
            parts = pool.map_partitions(
                cis, lambda ci: int(fcq[ci]),
                lambda g, part: [_walk_one(ci) for ci in part])
            walked = [w for part in parts for w in part]
        else:
            walked = [_walk_one(ci) for ci in cis]
        if any(w is None for w in walked):
            return None, None, False

        for ci, rec, kb, ub, mi in walked:
            state.n_rows_cq[ci] = rec.n_rows
            state.n_pend_cq[ci] = rec.n_pend
            state.bad_cq[ci] = rec.bad
            state.strict_cq[ci] = rec.strict
            state.n_comp_cq[ci] = rec.n_comp
            state.comp_max_cq[ci] = rec.comp_max_ts
            state.maxabs_prio_cq[ci] = int(
                np.abs(rec.prio).max(initial=0))
        rows_per_cq = int(state.n_rows_cq.max(initial=0))
        state.M = M = max(_bucket(rows_per_cq, minimum=4), min_m)
        R = len(st.resource_names)
        F = max(1, len(st.fr_index))
        views = _views(arena, C, M, R, F)

        for ci, rec, kb, ub, mi in walked:
            _clear_cq(state, views, ci)
            _write_cq(state, views, ci, rec, mi)
            state.records[ci] = rec
            state.mi_of[ci] = mi
            state.kb_of[ci] = kb

        rank_patches = 0
        # cycle-order rank: drop dirty + pos-moved CQ entries, merge the
        # fresh ones back in, rewrite the dense rank suffix
        walked_cis = [w[0] for w in walked]
        crank_drop = np.asarray(walked_cis + pos_dirty_cis, np.int32)
        ins_sk, ins_ci, ins_mi = [], [], []
        for ci, rec, kb, ub, mi in walked:
            if rec.n_rows:
                ins_sk.append(_crank_skey(
                    rec.prio, rec.ts,
                    np.full(rec.n_rows, state.pos_cq[ci], np.int64), kb))
                ins_ci.append(np.full(rec.n_rows, ci, np.int32))
                ins_mi.append(mi)
        for ci in pos_dirty_cis:
            rec = state.records[ci]
            if rec.n_rows:
                ins_sk.append(_crank_skey(
                    rec.prio, rec.ts,
                    np.full(rec.n_rows, state.pos_cq[ci], np.int64),
                    state.kb_of[ci]))
                ins_ci.append(np.full(rec.n_rows, ci, np.int32))
                ins_mi.append(state.mi_of[ci])
        sfrom = state.crank.update(
            crank_drop,
            np.concatenate(ins_sk) if ins_sk
            else np.empty(0, _SKEY_S),
            np.concatenate(ins_ci) if ins_ci else (),
            np.concatenate(ins_mi) if ins_mi else ())
        if sfrom is not None:
            ntot = len(state.crank.skey)
            views["wl_cycle_rank"][
                state.crank.ci[sfrom:], state.crank.mi[sfrom:]] = \
                np.arange(sfrom, ntot, dtype=np.int32)
            rank_patches += ntot - sfrom

        # uid rank: same mechanism, dirty CQs only; head-pack keeps
        # exempt (never-candidate) CQs out of the maintained uid order,
        # mirroring the _init_full budget filter
        head_pack = _agg.head_pack_enabled()
        ins_sk, ins_ci, ins_mi = [], [], []
        for ci, rec, kb, ub, mi in walked:
            if rec.n_rows and not (head_pack and statics.comp_cq[ci]):
                ins_sk.append(ub)
                ins_ci.append(np.full(rec.n_rows, ci, np.int32))
                ins_mi.append(mi)
        sfrom = state.uord.update(
            np.asarray(walked_cis, np.int32),
            np.concatenate(ins_sk) if ins_sk
            else np.empty(0, f"S{_UID_BYTES}"),
            np.concatenate(ins_ci) if ins_ci else (),
            np.concatenate(ins_mi) if ins_mi else ())
        if sfrom is not None:
            ntot = len(state.uord.skey)
            views["wl_uidrank"][
                state.uord.ci[sfrom:], state.uord.mi[sfrom:]] = \
                np.arange(sfrom, ntot, dtype=np.int32)
            rank_patches += ntot - sfrom

        # admitted reservation-seq: maintain the sorted ts multiset,
        # recompute dense seqs vectorized, scatter only changed cells
        if walked:
            wset = np.asarray(walked_cis, np.int32)
            keep = ~np.isin(state.adm_ci, wset) \
                if len(state.adm_ci) else np.empty(0, bool)
            a_ts = state.adm_ts[keep]
            a_ci = state.adm_ci[keep]
            a_mi = state.adm_mi[keep]
            a_sq = state.adm_seq_cache[keep]
            nts, nci, nmi = [], [], []
            for ci, rec, kb, ub, mi in walked:
                if rec.n_adm:
                    am = rec.adm
                    nts.append(rec.res_ts[am])
                    nci.append(np.full(int(am.sum()), ci, np.int32))
                    nmi.append(mi[am])
            if nts:
                nts = np.concatenate(nts)
                srt = np.argsort(nts, kind="stable")
                nts = nts[srt]
                nci = np.concatenate(nci)[srt]
                nmi = np.concatenate(nmi)[srt]
                pos = np.searchsorted(a_ts, nts)
                a_ts = np.insert(a_ts, pos, nts)
                a_ci = np.insert(a_ci, pos, nci)
                a_mi = np.insert(a_mi, pos, nmi)
                a_sq = np.insert(a_sq, pos,
                                 np.full(len(nts), -1, np.int32))
            state.adm_ts, state.adm_ci, state.adm_mi = a_ts, a_ci, a_mi
            if len(a_ts):
                uniq = np.unique(a_ts)
                seq_all = (np.searchsorted(uniq, a_ts)
                           + 1).astype(np.int32)
                chg = seq_all != a_sq
                if chg.any():
                    views["adm_seq0"][a_ci[chg], a_mi[chg]] = \
                        seq_all[chg]
                    rank_patches += int(chg.sum())
                state.adm_seq_cache = seq_all
            else:
                state.adm_seq_cache = np.empty(0, np.int32)

        # row-grade patches (deduped by the journal): single cells.
        # A job queued before a later row escalated its CQ to dirty is
        # stale — the re-walk rebuilt the record (and row order), so its
        # idx no longer addresses the row it was derived from.
        wset_cis = set(walked_cis)
        row_jobs = [j for j in row_jobs if j[0] not in wset_cis]
        for ci, idx, parked_now, resume_now, ok_now in row_jobs:
            rec = state.records[ci]
            mi = int(state.mi_of[ci][idx])
            rec.parked[idx] = parked_now
            rec.resume[idx] = resume_now
            rec.ok[idx] = ok_now
            views["parked0"][ci, mi] = parked_now
            views["elig0"][ci, mi] = (not parked_now
                                      and not bool(rec.adm[idx]))
            views["resume0"][ci, mi] = resume_now
            views["vec_ok"][ci, mi] = ok_now
        _bump(stats, "pack_row_patches", len(row_jobs))

        prev_token = state.token
        state.token = next(_b.DeltaPackState._next_token)
        repacked = sum(r.n_rows for _, r, _, _, _ in walked)
        _bump(stats, "burst_delta_packs")
        _bump(stats, "stream_packs")
        _bump(stats, "rows_repacked", repacked)
        _bump(stats, "rows_reused",
              int(state.n_rows_cq.sum()) - repacked)
        _bump(stats, "burst_journal_dirty_ranges", len(jranges))

        if int(state.n_pend_cq.sum()) == 0:
            _note_ms(stats, t0)
            return None, state, False
        s = _b._pack_statics(st, cache)
        dirty_cis = set(walked_cis) | {j[0] for j in row_jobs}
        plan = _materialize(st, state, s, views, scheduler, dirty_cis,
                            prev_token, rank_patches, stats)
        _note_ms(stats, t0, delta=True)
        return plan, state, True
    except _StreamBail:
        st._stream_poison = True
        _bump(stats, "stream_pack_bails")
        return _b._pack_burst_cached_classic(
            structure, queues, cache, scheduler, clock, state=None,
            min_m=min_m, window=window, stats=stats)
