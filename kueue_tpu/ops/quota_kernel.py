"""Hierarchical quota as array ops over the parent-pointer forest.

The reference's recursive ``available``/``addUsage`` walks
(pkg/cache/resource_node.go:89-144) become D-step vectorized recurrences
over [N, F] tensors (D = forest depth, static).  XLA unrolls the D loop and
fuses the gathers; no data-dependent control flow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def available_all(usage: jnp.ndarray, subtree: jnp.ndarray,
                  guaranteed: jnp.ndarray, borrow_cap: jnp.ndarray,
                  has_blim: jnp.ndarray, parent: jnp.ndarray,
                  depth: int) -> jnp.ndarray:
    """available() for every node at once: [N, F] → [N, F].

    Top-down recurrence (resource_node.go:89): roots are exact immediately;
    each iteration finalizes one more level below.
    """
    is_root = (parent < 0)[:, None]
    parent_safe = jnp.maximum(parent, 0)

    root_avail = subtree - usage
    local = jnp.maximum(0, guaranteed - usage)
    used_in_parent = jnp.maximum(0, usage - guaranteed)
    blim_cap = borrow_cap - used_in_parent

    avail = root_avail  # exact for roots; refined for deeper nodes below

    def body(_, avail):
        parent_avail = avail[parent_safe]
        parent_avail = jnp.where(has_blim,
                                 jnp.minimum(blim_cap, parent_avail),
                                 parent_avail)
        return jnp.where(is_root, root_avail, local + parent_avail)

    return jax.lax.fori_loop(0, depth, body, avail)


def available_at(usage: jnp.ndarray, subtree: jnp.ndarray,
                 guaranteed: jnp.ndarray, borrow_cap: jnp.ndarray,
                 has_blim: jnp.ndarray, parent: jnp.ndarray,
                 node, depth: int) -> jnp.ndarray:
    """available() for ONE node: gathers only the node's ancestor chain.

    O(depth·F) instead of available_all's O(N·F·depth) — the hot-loop
    form for scan steps that check a single CQ's availability (the admit
    scans' fits re-check, the preemption search's workloadFits).  Equals
    ``available_all(...)[node]``; node = -1 returns zeros (callers mask
    validity).  Parity: tests/test_solver_parity.py."""
    node = jnp.asarray(node, dtype=jnp.int32)
    if usage.shape[0] <= 64:
        # small forests: the dense recurrence beats per-level gathers
        # (shape is static — this branch resolves at trace time)
        full = available_all(usage, subtree, guaranteed, borrow_cap,
                             has_blim, parent, depth)
        return full[jnp.maximum(node, 0)] * (node >= 0)
    chain = [node]
    for _ in range(depth - 1):
        prev = chain[-1]
        chain.append(jnp.where(prev >= 0,
                               parent[jnp.maximum(prev, 0)], -1))
    avail = jnp.zeros(usage.shape[1], dtype=usage.dtype)
    for i in chain[::-1]:                  # root (topmost valid) → node
        safe = jnp.maximum(i, 0)
        valid = i >= 0
        is_root = parent[safe] < 0
        u = usage[safe]
        root_avail = subtree[safe] - u
        local = jnp.maximum(0, guaranteed[safe] - u)
        used_in_parent = jnp.maximum(0, u - guaranteed[safe])
        blim_cap = borrow_cap[safe] - used_in_parent
        pa = jnp.where(has_blim[safe], jnp.minimum(blim_cap, avail), avail)
        a = jnp.where(is_root, root_avail, local + pa)
        avail = jnp.where(valid, a, avail)
    return avail


def add_usage_chain(usage: jnp.ndarray, node: jnp.ndarray, delta: jnp.ndarray,
                    guaranteed: jnp.ndarray, parent: jnp.ndarray,
                    depth: int) -> jnp.ndarray:
    """addUsage() bubbling up one ancestor chain (resource_node.go:123).

    node: scalar int32 index; delta: [F] int32 (>=0).  Returns new usage.
    """
    def body(i, state):
        usage, cur, carry = state
        valid = cur >= 0
        cur_safe = jnp.maximum(cur, 0)
        local_avail = jnp.maximum(0, guaranteed[cur_safe] - usage[cur_safe])
        add = jnp.where(valid, carry, 0)
        usage = usage.at[cur_safe].add(add)
        next_carry = jnp.maximum(0, carry - local_avail)
        next_cur = jnp.where(valid, parent[cur_safe], -1)
        return usage, next_cur, jnp.where(valid, next_carry, carry)

    usage, _, _ = jax.lax.fori_loop(
        0, depth, body, (usage, node.astype(jnp.int32), delta))
    return usage
