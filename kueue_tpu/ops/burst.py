"""Fused multi-cycle admission bursts: K scheduling cycles in ONE dispatch.

Round 3 measured why the accelerator never ran a production cycle: one
dispatch through this environment's tunnel costs ~112 ms flat, more than
an entire XLA-CPU cycle at the north-star shape, so the calibrated
per-cycle router correctly starved the chip.  The fix is architectural,
not a tuning knob: keep the WHOLE pending set on the device (not just the
cycle heads) and fuse K successive cycles — head selection + classify +
admit scan + usage release + re-heads — into one jitted program, so the
dispatch cost is paid once per K cycles (verdict r3 item 1; reference hot
loop scheduler.go:176-302).

Semantics reproduced per fused cycle, bit-matching the host scheduler:

1. **Heads** (queue/manager.go:586 Heads): the top of every CQ's heap —
   here an argmin over a dense per-CQ rank matrix.  Ranks are
   host-precomputed with the exact heap comparator (priority desc,
   queue-order timestamp asc, key asc — cluster_queue.go:408); they are
   static within a burst because priorities/timestamps never change
   without an external event, and external events end the burst.
2. **Classify** (flavorassigner.go:499): the vectorized nominate of
   ops.cycle.classify_np, evaluated dense over [C, S, R].
3. **Cycle order** (scheduler.go:567 entryOrdering): borrows asc, then a
   host-precomputed (priority desc, timestamp asc, heads-position) rank.
4. **Admit scan** (scheduler.go:211-284): forest-parallel — one head per
   cohort forest per step, fits re-checked chain-locally, usage charged
   up the ancestor chain (the ops.cycle.admit_scan_forests discipline).
5. **Requeue semantics** (cluster_queue.go:225): a NoFit head parks in
   the inadmissible lot (BestEffortFIFO) or stays eligible (StrictFIFO);
   a fit head that lost capacity in-scan requeues immediately (stays
   eligible) — FAILED_AFTER_NOMINATION is immediate on both strategies.
6. **Finish + unpark** (driver.finish_workload → manager.go:490
   QueueInadmissibleWorkloads): quota released at end-of-cycle unparks
   every CQ in the affected cohort forest.  Releases come from two
   sources: workloads admitted IN the burst finishing ``runtime`` cycles
   later (the perf harness's fake execution — reference
   runner/controller/controller.go:113), and an external release
   schedule for workloads admitted before the burst.

Anything the fused math can't decide bit-identically makes the cycle
**dirty**: a preempt-capable head (needs the host preemption search), a
head outside the vectorized classify's coverage (multi-RG / multi-PodSet
/ taints / TAS / partial admission — ``vec_ok`` False), or a head with
fungibility resume state.  The kernel reports the first dirty cycle and
the host applies only the clean prefix, running the normal per-cycle
path from there.  Decisions are additionally validated on application:
the driver compares each cycle's modeled heads against the live queues
and truncates on any divergence, so burst mode can never corrupt state
even under unmodeled events.

Usage invariant that makes device-resident state exact: for every cohort
node, ``usage[node] == Σ_children max(0, usage[child] - guaranteed
[child])`` (resource_node.go:123-144 add/remove bubbling preserves it, by
induction).  The kernel therefore keeps only CQ-level usage as ground
truth and rebuilds cohort rows level-by-level each cycle — releases need
no sequential remove-chain walks.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .quota_kernel import available_all, available_at
from .cycle import add_usage_chain_batched

INF_I32 = np.int32(2**31 - 1)
I32_MAX = 2**31 - 1
# composite in-forest ordering key: borrows (entryOrdering's primary) in
# bit 30, the host-precomputed (priority, timestamp, position) rank below
_BORROW_BIT = np.int32(1 << 30)


# ----------------------------------------------------------------------
# The fused kernel
# ----------------------------------------------------------------------

@partial(
    jax.jit,
    static_argnames=("K", "depth", "L", "S", "RTP", "n_levels", "G",
                     "runtime"))
def burst_cycles(
    # dense workload state [C, M, ...]
    wl_req,          # [C, M, R] int32 scaled requests
    wl_rank,         # [C, M] int32 heap rank (INF_I32 = empty slot)
    wl_cycle_rank,   # [C, M] int32 global (priority, ts, pos) rank
    vec_ok,          # [C, M] bool  vectorized-classify coverage
    elig0,           # [C, M] bool  in the heap at burst start
    parked0,         # [C, M] bool  in the inadmissible lot at burst start
    resume0,         # [C, M] bool  fungibility resume state pending
    # quota plane
    u_cq0,           # [C, F] int32 CQ-level usage at burst start
    potential0,      # [N, F] int32 available() at zero usage (static)
    # structure (PackedStructure tensors)
    subtree, guaranteed, borrow_cap, has_blim,   # [N, F]
    parent,          # [N] int32
    node_level,      # [N] int32 (roots = 0)
    nominal_cq,      # [C, F]
    slot_fr,         # [C, S, R] int32 F-index or -1
    slot_valid,      # [C, S] bool
    cq_can_preempt_borrow,                       # [C] bool
    forest_of_cq,    # [C] int32
    strict_cq,       # [C] bool StrictFIFO
    members,         # [G, L] int32 CQ indices per forest (-1 pad, static)
    # event schedule
    ext_release,     # [K, C, F] int32 usage released at END of cycle k
    ext_unpark,      # [K, G] bool forest unpark events at END of cycle k
    *, K: int, depth: int, L: int, S: int, RTP: int, n_levels: int,
    G: int, runtime: int,
):
    """Run K fused admission cycles.  Returns per-cycle (head_row[K,C],
    admitted[K,C], fit_slot[K,C], borrows[K,C], parked_new[K,C],
    dirty[K]) plus the final u_cq."""
    C, M, R = wl_req.shape
    N, F = subtree.shape
    cidx = jnp.arange(C, dtype=jnp.int32)
    has_parent_cq = parent[:C] >= 0

    def rebuild_usage(u_cq):
        """CQ usage → full node usage via the subtree invariant."""
        usage = jnp.zeros((N, F), dtype=jnp.int32).at[:C].set(u_cq)
        parent_safe = jnp.maximum(parent, 0)
        for lvl in range(n_levels - 1, 0, -1):
            is_l = (node_level == lvl) & (parent >= 0)
            contrib = jnp.where(is_l[:, None],
                                jnp.maximum(0, usage - guaranteed), 0)
            usage = usage.at[parent_safe].add(contrib)
        return usage

    def cycle(carry, k):
        elig, parked, resume, u_cq, rel = carry
        usage = rebuild_usage(u_cq)
        avail = available_all(usage, subtree, guaranteed, borrow_cap,
                              has_blim, parent, depth)

        # -- heads: argmin heap rank per CQ ---------------------------
        key = jnp.where(elig, wl_rank, INF_I32)
        row = jnp.argmin(key, axis=1).astype(jnp.int32)        # [C]
        has_head = key[cidx, row] < INF_I32
        req = wl_req[cidx, row]                                # [C, R]

        # -- classify (classify_np dense twin) ------------------------
        frs = slot_fr                                          # [C,S,R]
        frs_safe = jnp.maximum(frs, 0)
        covered = frs >= 0
        needed = req[:, None, :] > 0
        missing = jnp.any(needed & ~covered, axis=2)           # [C,S]
        av = avail[:C][cidx[:, None, None], frs_safe]          # [C,S,R]
        pot = potential0[:C][cidx[:, None, None], frs_safe]
        nom = nominal_cq[cidx[:, None, None], frs_safe]
        use = usage[:C][cidx[:, None, None], frs_safe]
        sq = subtree[:C][cidx[:, None, None], frs_safe]

        relevant = covered & needed
        fit_r = req[:, None, :] <= av
        nofit_r = req[:, None, :] > pot
        preempt_capable_r = ((req[:, None, :] <= nom)
                             | cq_can_preempt_borrow[:, None, None])
        res_nofit = relevant & (nofit_r | (~fit_r & ~preempt_capable_r))
        fit_s = (jnp.all(jnp.where(relevant, fit_r, True), axis=2)
                 & ~missing & slot_valid)                      # [C,S]
        nofit_s = jnp.any(res_nofit, axis=2) | missing | ~slot_valid
        preempt_s = ~fit_s & ~nofit_s
        has_fit = jnp.any(fit_s, axis=1) & has_head
        fit_idx = jnp.argmax(fit_s, axis=1).astype(jnp.int32)
        fit_slot = jnp.where(has_fit, fit_idx, -1)
        borrow_r = jnp.where(relevant, use + req[:, None, :] > sq, False)
        borrows_s = jnp.any(borrow_r, axis=2) & has_parent_cq[:, None]
        borrows = borrows_s[cidx, fit_idx] & has_fit
        has_preempt = ~has_fit & jnp.any(preempt_s, axis=1) & has_head

        dirty_c = has_head & (has_preempt | ~vec_ok[cidx, row]
                              | resume[cidx, row])
        dirty = jnp.any(dirty_c)

        # -- cycle order + forest schedule ----------------------------
        # entryOrdering (scheduler.go:567) within each forest: borrows
        # asc then the static (priority desc, ts asc, position) rank.
        # Forest membership is static, so the schedule is a tiny per-row
        # argsort over the members matrix — no global sort per cycle.
        head_crank = wl_cycle_rank[cidx, row]
        fit_key = jnp.where(
            has_fit,
            head_crank + jnp.where(borrows, _BORROW_BIT, 0),
            INF_I32)                                           # [C]
        mem_safe = jnp.maximum(members, 0)
        keys_gl = jnp.where(members >= 0, fit_key[mem_safe],
                            INF_I32)                           # [G, L]
        ord_gl = jnp.argsort(keys_gl, axis=1)
        keys_sorted = jnp.take_along_axis(keys_gl, ord_gl, axis=1)
        mat = jnp.where(keys_sorted < INF_I32,
                        jnp.take_along_axis(mem_safe, ord_gl, axis=1),
                        -1)                                    # [G, L]

        # -- admit scan: one fit head per forest per step -------------
        def step(u_pair, col):
            usage, u_cq = u_pair
            cqs = mat[:, col]                                  # [G]

            def lane(cq):
                cq_s = jnp.maximum(cq, 0)
                slot = jnp.maximum(fit_slot[cq_s], 0)
                frs_l = slot_fr[cq_s, slot]                    # [R]
                amt_l = req[cq_s]                              # [R]
                frs_ls = jnp.maximum(frs_l, 0)
                rel_l = (frs_l >= 0) & (amt_l > 0)
                avail_row = available_at(usage, subtree, guaranteed,
                                         borrow_cap, has_blim, parent,
                                         cq_s, depth)          # [F]
                ok = jnp.all(jnp.where(rel_l, amt_l <= avail_row[frs_ls],
                                       True))
                admit = (cq >= 0) & (fit_slot[cq_s] >= 0) & ok
                delta = jnp.zeros(F, dtype=jnp.int32).at[frs_ls].add(
                    jnp.where(rel_l & admit, amt_l, 0))
                return admit, jnp.where(admit, cq, -1), delta

            admit_l, nodes, deltas = jax.vmap(lane)(cqs)
            usage = add_usage_chain_batched(usage, nodes, deltas,
                                            guaranteed, parent, depth)
            nodes_s = jnp.maximum(nodes, 0)
            u_cq = u_cq.at[nodes_s].add(
                jnp.where((nodes >= 0)[:, None], deltas, 0))
            return (usage, u_cq), admit_l

        u_cq_before = u_cq
        (usage, u_cq), admit_cols = jax.lax.scan(
            step, (usage, u_cq), jnp.arange(L))
        # scatter scan lanes back to per-CQ admitted flags
        flat_cq = mat.T.reshape(-1)                            # [L*(G+1)]
        flat_ok = admit_cols.reshape(-1)
        admitted_c = jnp.zeros(C, dtype=bool).at[
            jnp.maximum(flat_cq, 0)].max(flat_ok & (flat_cq >= 0))

        # -- requeue semantics ---------------------------------------
        skipped = has_fit & ~admitted_c            # stays eligible
        park_new = has_head & ~has_fit & ~dirty_c & ~strict_cq
        gone = admitted_c | park_new
        elig = elig.at[cidx, row].set(
            jnp.where(gone, False, elig[cidx, row]))
        parked = parked.at[cidx, row].set(
            park_new | parked[cidx, row])
        # fungibility resume: a skipped fit head that did not try the
        # whole flavor list restarts mid-walk next time → dirty then
        resume = resume.at[cidx, row].set(
            resume[cidx, row] | (skipped & (fit_slot >= 0)
                                 & (fit_slot < S - 1)))

        # -- releases at end of cycle --------------------------------
        delta_cycle = u_cq - u_cq_before                       # [C,F]
        if runtime > 0:
            rel = rel.at[(k + runtime) % RTP].add(delta_cycle)
            release = rel[k % RTP] + ext_release[k]
            rel = rel.at[k % RTP].set(0)
        else:
            release = ext_release[k]
        u_cq = u_cq - release
        released_forest = jnp.zeros(G, dtype=bool).at[forest_of_cq].max(
            jnp.any(release > 0, axis=1))
        unpark_f = ext_unpark[k] | released_forest             # [G]
        do_unpark = unpark_f[forest_of_cq]                     # [C]
        back = parked & do_unpark[:, None]
        elig = elig | back
        parked = parked & ~back

        out = (jnp.where(has_head, row, -1), admitted_c, fit_slot,
               borrows, park_new, dirty)
        return (elig, parked, resume, u_cq, rel), out

    rel0 = jnp.zeros((RTP, C, F), dtype=jnp.int32)
    carry0 = (elig0, parked0, resume0, u_cq0, rel0)
    (elig, parked, resume, u_cq, _), outs = jax.lax.scan(
        cycle, carry0, jnp.arange(K, dtype=jnp.int32))
    head_row, admitted, fit_slot, borrows, park_new, dirty = outs
    return head_row, admitted, fit_slot, borrows, park_new, dirty, u_cq


def build_members(forest_of_cq: np.ndarray, n_forests: int,
                  max_per_forest: int) -> np.ndarray:
    """Static [G, L] matrix of CQ indices per forest (-1 pad)."""
    members = np.full((n_forests, max_per_forest), -1, dtype=np.int32)
    fill = np.zeros(n_forests, dtype=np.int64)
    for ci, f in enumerate(forest_of_cq):
        f = int(f)
        if fill[f] < max_per_forest:
            members[f, fill[f]] = ci
            fill[f] += 1
    return members


# ----------------------------------------------------------------------
# Roofline probe (synthetic; used by scripts/accel_roofline.py)
# ----------------------------------------------------------------------

_probe_cache: dict = {}


def burst_probe(C: int, M: int, R: int, K: int, runtime: int = 4):
    """One fused-burst dispatch on synthetic north-star-shaped data.
    Returns the device arrays (caller device_gets them)."""
    key = (C, M, R)
    if key not in _probe_cache:
        rng = np.random.default_rng(0)
        G = max(1, C // 5)
        N = C + G
        F = R
        parent = np.concatenate([
            C + (np.arange(C) % G), np.full(G, -1)]).astype(np.int32)
        node_level = np.concatenate([
            np.ones(C, np.int32), np.zeros(G, np.int32)])
        forest_of_cq = (np.arange(C) % G).astype(np.int32)
        subtree = np.full((N, F), 10**7, np.int32)
        guaranteed = np.full((N, F), 20_000, np.int32)
        guaranteed[C:] = 10**7
        borrow_cap = np.full((N, F), 2**25, np.int32)
        has_blim = np.zeros((N, F), bool)
        nominal_cq = np.full((C, F), 20_000, np.int32)
        slot_fr = np.tile(np.arange(R, dtype=np.int32), (C, 1, 1))
        slot_valid = np.ones((C, 1), bool)
        cpb = np.zeros(C, bool)
        strict = np.zeros(C, bool)
        members = build_members(forest_of_cq, G, 8)
        wl_req = rng.integers(200, 2000, (C, M, R)).astype(np.int32)
        wl_rank = np.argsort(rng.random((C, M))).astype(np.int32)
        wl_cycle_rank = rng.permutation(C * M).reshape(C, M).astype(np.int32)
        ones = np.ones((C, M), bool)
        zeros = np.zeros((C, M), bool)
        u_cq0 = np.zeros((C, F), np.int32)
        from .cycle import available_all_np
        potential0 = available_all_np(
            np.zeros((N, F), np.int64), subtree, guaranteed, borrow_cap,
            has_blim, parent, 2).astype(np.int32)
        _probe_cache[key] = dict(
            wl_req=wl_req, wl_rank=wl_rank, wl_cycle_rank=wl_cycle_rank,
            vec_ok=ones, elig0=ones, parked0=zeros, resume0=zeros,
            u_cq0=u_cq0, potential0=potential0, subtree=subtree,
            guaranteed=guaranteed, borrow_cap=borrow_cap,
            has_blim=has_blim, parent=parent, node_level=node_level,
            nominal_cq=nominal_cq, slot_fr=slot_fr,
            slot_valid=slot_valid, cq_can_preempt_borrow=cpb,
            forest_of_cq=forest_of_cq, strict_cq=strict, members=members,
            G=G)
    d = _probe_cache[key]
    G = d["G"]
    ext_release = np.zeros((K, C, R), np.int32)
    ext_unpark = np.zeros((K, G), bool)
    return burst_cycles(
        d["wl_req"], d["wl_rank"], d["wl_cycle_rank"], d["vec_ok"],
        d["elig0"], d["parked0"], d["resume0"], d["u_cq0"],
        d["potential0"], d["subtree"], d["guaranteed"], d["borrow_cap"],
        d["has_blim"], d["parent"], d["node_level"], d["nominal_cq"],
        d["slot_fr"], d["slot_valid"],
        d["cq_can_preempt_borrow"], d["forest_of_cq"], d["strict_cq"],
        d["members"], ext_release, ext_unpark,
        K=K, depth=2, L=8, S=1, RTP=runtime + 1, n_levels=2, G=G,
        runtime=runtime)


# ----------------------------------------------------------------------
# Host side: pack the live queue/cache state into a burst plan
# ----------------------------------------------------------------------

@dataclass
class BurstPlan:
    """Dense device state for one burst + the host maps to apply it."""
    structure: object                 # PackedStructure
    arrays: dict                      # kernel inputs (numpy)
    keys: list                        # [C][M] workload key or None
    C: int
    M: int
    L: int
    G: int
    n_levels: int


def _static_row(info, st, covers_pods: bool):
    """Per-Info static pack facts: (covers_pods, scaled request vector,
    static vectorized-eligibility).  Cached on the Info keyed by the
    structure generation — total_requests are immutable per Info."""
    R = len(st.resource_names)
    scale = st.resource_scale
    obj = info.obj
    ok = (len(obj.pod_sets) == 1
          and obj.pod_sets[0].topology_request is None
          and not any(ps.min_count is not None and ps.min_count < ps.count
                      for ps in obj.pod_sets))
    exact = True
    acc = np.zeros(R, dtype=np.int64)
    for psr in info.total_requests:
        for r, v in psr.requests.items():
            if r == "pods" and not covers_pods:
                continue
            ri = st.r_index.get(r)
            if ri is None:
                exact = False
                continue
            if v < 0:
                exact = False
                v = 0
            if st.scale_is_one:
                acc[ri] += int(v)
            else:
                s = int(scale[ri])
                q_, rem = divmod(int(v), s)
                if rem:
                    exact = False
                    q_ += 1
                acc[ri] += q_
    if acc.max(initial=0) > I32_MAX:
        exact = False
        np.clip(acc, None, I32_MAX, out=acc)
    return covers_pods, acc.astype(np.int32), ok and exact


def pack_burst(structure, queues, cache, scheduler, clock,
               min_m: int = 0) -> Optional[BurstPlan]:
    """Build the dense [C, M] state from the live queues + cache.

    Returns None when the cluster can't be burst-scheduled at all
    (inexact usage scaling, unknown flavor-resources).  Per-workload
    limitations never fail the pack — they mark the row ``vec_ok=False``
    so the cycle that would schedule the row goes dirty and runs on the
    normal host path instead."""
    st = structure
    C = len(st.cq_names)
    F = max(1, len(st.fr_index))
    R = len(st.resource_names)
    S = st.slot_fr.shape[1]
    ordering = scheduler.ordering

    # CQ-position order (the queue manager's heads enumeration order)
    cq_pos = {name: i for i, name in
              enumerate(queues.cluster_queue_names())}

    members_by_ci: list[list] = [[] for _ in range(C)]
    parked_by_ci: list[set] = [set() for _ in range(C)]
    strict = np.zeros(C, dtype=bool)
    from ..api.types import QueueingStrategy
    for name in queues.cluster_queue_names():
        ci = st.cq_index.get(name)
        q = queues.queue_for(name)
        if ci is None:
            if q is not None and q.active and q.pending_active():
                return None   # an active CQ the structure doesn't know
            continue
        if q is None or not q.active:
            continue
        strict[ci] = q.queueing_strategy == QueueingStrategy.STRICT_FIFO
        for info in q.heap.items():
            members_by_ci[ci].append(info)
        for key, info in q.inadmissible.items():
            rs = info.obj.requeue_state
            if rs is not None and rs.requeue_at is not None:
                # backoff-parked: excluded; a mid-burst expiry diverges
                # the heads and the application validator truncates
                continue
            members_by_ci[ci].append(info)
            parked_by_ci[ci].add(info.key)

    n_members = sum(len(m) for m in members_by_ci)
    if n_members == 0:
        return None
    from .packing import _bucket
    # sticky minimum keeps M stable across re-packs as queues drain
    # (every distinct M is a fresh XLA compilation)
    M = max(_bucket(max(len(m) for m in members_by_ci), minimum=4),
            min_m)

    wl_req = np.zeros((C, M, R), dtype=np.int32)
    wl_rank = np.full((C, M), INF_I32, dtype=np.int32)
    wl_cycle_rank = np.zeros((C, M), dtype=np.int32)
    vec_ok = np.zeros((C, M), dtype=bool)
    elig = np.zeros((C, M), dtype=bool)
    parked = np.zeros((C, M), dtype=bool)
    resume = np.zeros((C, M), dtype=bool)
    keys: list[list] = [[None] * M for _ in range(C)]

    scale = st.resource_scale
    scale_is_one = st.scale_is_one
    cq_ok = st.cq_vector_ok if st.cq_vector_ok is not None else np.zeros(C, bool)
    assumed = cache.assumed_workloads
    gen = st.generation

    # flatten members with one Python pass; static per-workload facts
    # (scaled request vector, shape eligibility) are cached on the Info
    # object keyed by structure generation — requests are immutable per
    # Info instance, so re-packs touch each workload only lightly
    n = n_members
    infos_flat: list = [None] * n
    ci_a = np.empty(n, dtype=np.int32)
    prio_a = np.empty(n, dtype=np.int64)
    ts_a = np.empty(n, dtype=np.float64)
    pos_a = np.empty(n, dtype=np.int32)
    parked_a = np.zeros(n, dtype=bool)
    ok_a = np.zeros(n, dtype=bool)
    resume_a = np.zeros(n, dtype=bool)
    req_mat = np.zeros((n, R), dtype=np.int32)
    key_a: list[str] = [""] * n
    qts = ordering.queue_order_timestamp

    i = 0
    for ci in range(C):
        mlist = members_by_ci[ci]
        if not mlist:
            continue
        cq_name = st.cq_names[ci]
        cq_live = cache.cluster_queue(cq_name)
        covers_pods = cq_name in st.cq_covers_pods
        pos = cq_pos.get(cq_name, C)
        cq_vec = bool(cq_ok[ci])
        if cq_vec and cq_live is not None and cq_live.spec.namespace_selector:
            cq_vec = False   # selector evaluation stays on the host path
        lr_summaries = scheduler.limit_range_summaries
        allocatable = (cq_live.allocatable_generation
                       if cq_live is not None else -1)
        pk = parked_by_ci[ci]
        for info in mlist:
            obj = info.obj
            row = getattr(info, "_burst_row", None)
            if row is None or row[0] != gen or row[1] != covers_pods:
                row = (gen, *_static_row(info, st, covers_pods))
                info._burst_row = row
            _, _, req_vec, static_ok = row
            key = info.key
            infos_flat[i] = info
            key_a[i] = key
            ci_a[i] = ci
            prio_a[i] = obj.priority
            ts_a[i] = qts(obj)
            pos_a[i] = pos
            parked_a[i] = key in pk
            req_mat[i] = req_vec
            ok = cq_vec and static_ok
            if ok and lr_summaries and lr_summaries.get(obj.namespace):
                ok = False   # LimitRange bounds stay on the host path
            if ok and (key in assumed or obj.admission is not None):
                ok = False
            if ok and obj.admission_check_states:
                from ..api.types import AdmissionCheckState
                if any(stt.state in (AdmissionCheckState.RETRY,
                                     AdmissionCheckState.REJECTED)
                       for stt in obj.admission_check_states.values()):
                    ok = False
            ok_a[i] = ok
            last = info.last_assignment
            if (last is not None
                    and getattr(last, "pending_flavors", False)
                    and last.cluster_queue_generation >= allocatable):
                resume_a[i] = True
            i += 1

    # heap rank within each CQ: one global lexsort replaces C Python
    # sorts (priority desc, queue-order ts asc, key asc —
    # cluster_queue.go:408)
    key_arr = np.asarray(key_a)
    order = np.lexsort((key_arr, ts_a, -prio_a, ci_a))
    ci_sorted = ci_a[order]
    first = np.ones(n, dtype=bool)
    first[1:] = ci_sorted[1:] != ci_sorted[:-1]
    seg_start = np.maximum.accumulate(
        np.where(first, np.arange(n), 0))
    mi_sorted = (np.arange(n) - seg_start).astype(np.int64)
    mi_a = np.empty(n, dtype=np.int64)
    mi_a[order] = mi_sorted
    # global cycle-order rank (priority desc, ts asc, heads-position)
    crank = np.empty(n, dtype=np.int64)
    crank[np.lexsort((pos_a, ts_a, -prio_a))] = np.arange(n)

    wl_rank[ci_a, mi_a] = mi_a
    wl_cycle_rank[ci_a, mi_a] = crank
    parked[ci_a, mi_a] = parked_a
    elig[ci_a, mi_a] = ~parked_a
    vec_ok[ci_a, mi_a] = ok_a
    resume[ci_a, mi_a] = resume_a
    wl_req[ci_a, mi_a] = req_mat
    for j in range(n):
        keys[int(ci_a[j])][int(mi_a[j])] = key_a[j]

    # CQ-level usage, scaled exactly (else no burst)
    u_cq = np.zeros((C, F), dtype=np.int32)
    for ci, name in enumerate(st.cq_names):
        cq_live = cache.cluster_queue(name)
        if cq_live is None:
            return None
        for fr, v in cq_live.resource_node.usage.items():
            fi = st.fr_index.get(fr)
            if fi is None:
                return None
            if scale_is_one:
                q_ = int(v)
            else:
                s = int(scale[st.r_index[fr.resource]])
                q_, rem = divmod(int(v), s)
                if rem:
                    return None
            if q_ > I32_MAX:
                return None
            u_cq[ci, fi] = q_

    # tree metadata
    parent = st.parent
    N = st.node_count
    node_level = np.zeros(N, dtype=np.int32)
    for ni in range(N):
        lvl, p = 0, parent[ni]
        while p >= 0:
            lvl += 1
            p = parent[p]
        node_level[ni] = lvl
    # node_level[ni] = distance from root (roots = 0); rebuild_usage
    # sweeps deepest levels first via range(n_levels-1, 0, -1)
    n_levels = int(node_level.max()) + 1
    G = st.n_forests
    forest_of_cq = st.forest_of_node[:C].astype(np.int32)
    per_forest = np.bincount(forest_of_cq, minlength=G)
    L = max(1, int(per_forest.max()))
    members = build_members(forest_of_cq, G, L)

    from .cycle import available_all_np
    potential0 = np.minimum(available_all_np(
        np.zeros((N, F), np.int64), st.subtree_quota, st.guaranteed,
        st.borrow_cap, st.has_borrow_limit, st.parent, st.depth),
        np.int64(I32_MAX)).astype(np.int32)

    arrays = dict(
        wl_req=wl_req, wl_rank=wl_rank, wl_cycle_rank=wl_cycle_rank,
        vec_ok=vec_ok, elig0=elig, parked0=parked, resume0=resume,
        u_cq0=u_cq, potential0=potential0,
        subtree=st.subtree_quota, guaranteed=st.guaranteed,
        borrow_cap=st.borrow_cap, has_blim=st.has_borrow_limit,
        parent=st.parent, node_level=node_level,
        nominal_cq=st.nominal_cq,
        slot_fr=st.slot_fr, slot_valid=st.slot_valid,
        cq_can_preempt_borrow=st.cq_can_preempt_borrow,
        forest_of_cq=forest_of_cq, strict_cq=strict, members=members)
    return BurstPlan(structure=st, arrays=arrays, keys=keys,
                     C=C, M=M, L=L, G=G, n_levels=n_levels)


K_BURST_LADDER = (8, 32, 64)


class BurstSolver:
    """Dispatch fused bursts and expose the decisions for application.

    ``backend``: "cpu" | "accel" | "auto" (auto = cpu; the roofline
    measurement ROOFLINE_r04.json shows XLA-CPU wins the fused kernel at
    every shape in this environment — the accel's incremental per-cycle
    compute matches the CPU's but each dispatch adds the tunnel RTT)."""

    def __init__(self, backend: str = "auto"):
        from ..compilecache import enable as _enable_compile_cache
        _enable_compile_cache()
        self.backend = backend
        self.stats = {"burst_dispatches": 0, "burst_cycles_decided": 0,
                      "burst_accel_dispatches": 0,
                      "burst_dispatch_s": 0.0,
                      # boundary + fallback visibility (VERDICT r4 item 9)
                      "burst_pack_s": 0.0, "burst_packs": 0,
                      "burst_suppressed_cycles": 0,
                      "burst_dirty_cycles": 0}

    def _device(self):
        import jax
        try:
            if self.backend == "accel":
                default = jax.devices()[0]
                if default.platform != "cpu":
                    return default
            return jax.devices("cpu")[0]
        except RuntimeError:
            # a registered accelerator plugin that can't initialize must
            # not take the CPU path down with it (solver.py discipline)
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            return jax.devices("cpu")[0]

    def run(self, plan: BurstPlan, K: int, runtime: int,
            ext_release: np.ndarray, ext_unpark: np.ndarray):
        """One fused dispatch of K cycles.  Returns numpy decision arrays
        (head_row, admitted, fit_slot, borrows, park_new, dirty)."""
        import jax
        import time as _time
        st = plan.structure
        dev = self._device()
        a = plan.arrays
        t0 = _time.perf_counter()
        with jax.default_device(dev):
            out = burst_cycles(
                a["wl_req"], a["wl_rank"], a["wl_cycle_rank"], a["vec_ok"],
                a["elig0"], a["parked0"], a["resume0"], a["u_cq0"],
                a["potential0"], a["subtree"], a["guaranteed"],
                a["borrow_cap"], a["has_blim"], a["parent"],
                a["node_level"], a["nominal_cq"],
                a["slot_fr"], a["slot_valid"], a["cq_can_preempt_borrow"],
                a["forest_of_cq"], a["strict_cq"], a["members"],
                ext_release, ext_unpark,
                K=K, depth=st.depth, L=plan.L,
                S=int(st.slot_fr.shape[1]), RTP=max(1, runtime + 1),
                n_levels=plan.n_levels, G=plan.G, runtime=max(0, runtime))
            out = jax.device_get(out)
        self.stats["burst_dispatches"] += 1
        self.stats["burst_cycles_decided"] += K
        self.stats["burst_dispatch_s"] += _time.perf_counter() - t0
        if dev.platform != "cpu":
            self.stats["burst_accel_dispatches"] += 1
        return out
